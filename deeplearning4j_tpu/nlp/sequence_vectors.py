"""SequenceVectors: the generic embedding-training engine (reference
`models/sequencevectors/SequenceVectors.java:50`, `fit():161`; learning
algorithms SPI `models/embeddings/learning/` — `SkipGram.java`, `CBOW.java`).

TPU-first pipeline: the host walks sequences, applies subsampling and the
shrinking window, and packs (center, targets, labels, mask) int32 batches;
every full batch is one donated-buffer jitted scatter step
(`nlp/kernels.py`). Learning rate decays linearly with words processed, as
in the reference (`SequenceVectors.java:260` alpha handling).
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp import kernels
from deeplearning4j_tpu.nlp.lookup_table import InMemoryLookupTable
from deeplearning4j_tpu.nlp.vocab import (
    AbstractCache,
    VocabConstructor,
    build_huffman_tree,
)


class SequenceVectors:
    """Train element embeddings over sequences of tokens.

    elements_learning_algorithm: 'skipgram' | 'cbow'
    (reference `ElementsLearningAlgorithm` SPI).
    """

    def __init__(self,
                 layer_size: int = 100,
                 window: int = 5,
                 min_word_frequency: float = 1.0,
                 negative: int = 5,
                 use_hierarchic_softmax: bool = False,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 epochs: int = 1,
                 iterations: int = 1,
                 batch_size: int = 1024,
                 sampling: float = 0.0,
                 seed: int = 42,
                 elements_learning_algorithm: str = "skipgram",
                 mesh=None,
                 data_axis: str = "data"):
        if negative <= 0 and not use_hierarchic_softmax:
            raise ValueError("need negative sampling (negative>0) and/or "
                             "hierarchical softmax")
        if negative > 0 and use_hierarchic_softmax and \
                elements_learning_algorithm == "cbow":
            raise NotImplementedError(
                "mixed HS+negative-sampling is only supported for skipgram")
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.negative = negative
        self.use_hs = use_hierarchic_softmax
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.epochs = epochs
        self.iterations = iterations
        self.batch_size = batch_size
        self.sampling = sampling
        self.seed = seed
        self.algorithm = elements_learning_algorithm
        self.vocab: Optional[AbstractCache] = None
        self.lookup_table: Optional[InMemoryLookupTable] = None
        self._rng = np.random.default_rng(seed)
        self._unigram: Optional[np.ndarray] = None
        self._loss_sum = 0.0
        self._loss_batches = 0
        # multi-chip data parallelism (the dl4j-spark-nlp role,
        # `spark/models/embeddings/word2vec/Word2VecPerformer.java`): pair
        # batches shard over the mesh's data axis, embedding tables stay
        # replicated, and XLA psums the scatter contributions over ICI —
        # where the reference map-reduces word2vec over Spark executors.
        self.mesh = mesh
        self.data_axis = data_axis
        if mesh is not None:
            n = mesh.shape[data_axis]
            if batch_size % n != 0:
                raise ValueError(
                    f"batch_size {batch_size} must divide by the "
                    f"'{data_axis}' mesh axis size {n}")
        self._sharded_kernels = None

    # -- vocab/init ---------------------------------------------------------
    def build_vocab(self, sequences: Iterable[Sequence[str]]) -> None:
        self.vocab = VocabConstructor(self.min_word_frequency).build_vocab(sequences)
        if self.use_hs:
            build_huffman_tree(self.vocab)
        self.lookup_table = InMemoryLookupTable(
            self.vocab, self.layer_size, seed=self.seed, use_hs=self.use_hs,
            negative=self.negative)
        if self.negative > 0:
            self._unigram = self.vocab.unigram_table()

    # -- training -----------------------------------------------------------
    def fit(self, sequences: Iterable[Sequence[str]]) -> None:
        seqs = [list(s) for s in sequences]
        if self.vocab is None:
            self.build_vocab(seqs)
        total_words = max(
            1.0, self.vocab.total_word_occurrences * self.epochs * self.iterations)
        words_seen = 0.0
        self._loss_sum, self._loss_batches = 0.0, 0
        batch = _PairBatcher(self)
        for _ in range(self.epochs * self.iterations):
            for seq in seqs:
                ids = self._to_ids(seq)
                if len(ids) < 2:
                    continue
                alpha = max(self.min_learning_rate,
                            self.learning_rate * (1.0 - words_seen / total_words))
                self._train_sequence(ids, alpha, batch)
                words_seen += len(ids)
        batch.flush()

    def _to_ids(self, seq: Sequence[str]) -> List[int]:
        ids = []
        for tok in seq:
            i = self.vocab.index_of(tok)
            if i < 0:
                continue
            if self.sampling > 0:
                # word2vec subsampling: P(keep) = sqrt(t/f) + t/f
                f = (self.vocab.element_at_index(i).count
                     / self.vocab.total_word_occurrences)
                keep = min(1.0, np.sqrt(self.sampling / f) + self.sampling / f)
                if self._rng.random() > keep:
                    continue
            ids.append(i)
        return ids

    def _train_sequence(self, ids: List[int], alpha: float, batch: "_PairBatcher"):
        window = self.window
        for pos, center in enumerate(ids):
            b = int(self._rng.integers(1, window + 1))  # shrinking window
            lo, hi = max(0, pos - b), min(len(ids), pos + b + 1)
            context = [ids[j] for j in range(lo, hi) if j != pos]
            if not context:
                continue
            if self.algorithm == "skipgram":
                for c in context:
                    batch.add_pair(center, c, alpha)
            elif self.algorithm == "cbow":
                batch.add_cbow(context, center, alpha)
            else:
                raise ValueError(self.algorithm)

    # hooks used by _PairBatcher ------------------------------------------
    def _kernels(self):
        """(skipgram_step, cbow_step) — module-level jits single-chip, or
        mesh-sharded jits when a mesh was given (batch on the data axis,
        tables replicated; XLA inserts the ICI all-reduce of the scatter
        contributions)."""
        if self.mesh is None:
            return kernels.skipgram_step, kernels.cbow_step
        if self._sharded_kernels is None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(self.mesh, P())
            bsh = NamedSharding(self.mesh, P(self.data_axis))
            sg = jax.jit(kernels.skipgram_step.__wrapped__,
                         in_shardings=(repl, repl, bsh, bsh, bsh, bsh, repl),
                         out_shardings=(repl, repl, None),
                         donate_argnums=(0, 1))
            cb = jax.jit(kernels.cbow_step.__wrapped__,
                         in_shardings=(repl, repl, bsh, bsh, bsh, bsh, bsh,
                                       repl),
                         out_shardings=(repl, repl, None),
                         donate_argnums=(0, 1))
            self._sharded_kernels = (sg, cb)
        return self._sharded_kernels

    def _sample_negatives(self, n: int) -> np.ndarray:
        return self._rng.choice(len(self._unigram), size=n, p=self._unigram)

    def _record_loss(self, loss: float) -> None:
        self._loss_sum += loss
        self._loss_batches += 1

    @property
    def mean_loss(self) -> float:
        return self._loss_sum / max(self._loss_batches, 1)

    # -- query passthrough --------------------------------------------------
    def words_nearest(self, word, top_n: int = 10):
        return self.lookup_table.words_nearest(word, top_n)

    def similarity(self, w1: str, w2: str) -> float:
        return self.lookup_table.similarity(w1, w2)

    def get_word_vector(self, word: str):
        return self.lookup_table.vector(word)


class _PairBatcher:
    """Accumulates training examples into fixed-shape arrays and flushes
    them through the jitted kernels (fixed batch shape ⇒ one XLA
    compilation; the tail batch is mask-padded)."""

    def __init__(self, sv: SequenceVectors):
        self.sv = sv
        B = sv.batch_size
        # target row count: negatives+1 (NS) and/or max code length (HS)
        self._max_codes = 0
        if sv.use_hs:
            self._max_codes = max((len(vw.codes)
                                   for vw in sv.vocab.vocab_words()), default=0)
        self.K = (sv.negative + 1 if sv.negative > 0 else 0) + self._max_codes
        self.W = 2 * sv.window
        self.center = np.zeros(B, np.int32)
        self.targets = np.zeros((B, self.K), np.int32)
        self.labels = np.zeros((B, self.K), np.float32)
        self.mask = np.zeros((B, self.K), np.float32)
        self.context = np.zeros((B, self.W), np.int32)
        self.cmask = np.zeros((B, self.W), np.float32)
        self.alpha = 0.025
        self.n = 0

    def _fill_targets(self, row: int, predicted: int):
        """Targets for predicting word id `predicted`: NS = [pos, negs];
        HS = its Huffman path (labels = 1 - code)."""
        sv = self.sv
        k = 0
        if sv.negative > 0:
            self.targets[row, 0] = predicted
            self.labels[row, 0] = 1.0
            self.mask[row, 0] = 1.0
            negs = sv._sample_negatives(sv.negative)
            for ng in negs:
                k += 1
                self.targets[row, k] = ng
                self.labels[row, k] = 0.0
                # word2vec skips a negative that equals the positive
                self.mask[row, k] = 0.0 if ng == predicted else 1.0
            k += 1
        if sv.use_hs:
            vw = sv.vocab.element_at_index(predicted)
            for code, point in zip(vw.codes, vw.points):
                self.targets[row, k] = point
                self.labels[row, k] = 1.0 - code
                self.mask[row, k] = 1.0
                k += 1

    def add_pair(self, center: int, context: int, alpha: float):
        """Skip-gram: center predicts context."""
        row = self.n
        self.center[row] = center
        self.targets[row] = 0
        self.labels[row] = 0
        self.mask[row] = 0
        self._fill_targets(row, context)
        self.alpha = alpha
        self.n += 1
        if self.n == len(self.center):
            self.flush()

    def add_cbow(self, context: List[int], center: int, alpha: float):
        row = self.n
        self.context[row] = 0
        self.cmask[row] = 0
        w = min(len(context), self.W)
        self.context[row, :w] = context[:w]
        self.cmask[row, :w] = 1.0
        self.targets[row] = 0
        self.labels[row] = 0
        self.mask[row] = 0
        self._fill_targets(row, center)
        self.alpha = alpha
        self.n += 1
        if self.n == len(self.center):
            self.flush()

    def flush(self):
        if self.n == 0:
            return
        sv = self.sv
        lt = sv.lookup_table
        self.mask[self.n:] = 0.0
        self.cmask[self.n:] = 0.0
        lr = jnp.float32(self.alpha)
        syn1 = lt.syn1neg if sv.negative > 0 else lt.syn1
        skipgram_step, cbow_step = sv._kernels()
        if sv.use_hs and sv.negative > 0:
            # mixed mode: split columns — NS rows live in syn1neg, HS rows
            # in syn1; run two steps on the column slices
            ns_cols = sv.negative + 1
            lt.syn0, lt.syn1neg, loss1 = skipgram_step(
                lt.syn0, lt.syn1neg, jnp.asarray(self.center),
                jnp.asarray(self.targets[:, :ns_cols]),
                jnp.asarray(self.labels[:, :ns_cols]),
                jnp.asarray(self.mask[:, :ns_cols]), lr)
            lt.syn0, lt.syn1, loss2 = skipgram_step(
                lt.syn0, lt.syn1, jnp.asarray(self.center),
                jnp.asarray(self.targets[:, ns_cols:]),
                jnp.asarray(self.labels[:, ns_cols:]),
                jnp.asarray(self.mask[:, ns_cols:]), lr)
            sv._record_loss(float(loss1) + float(loss2))
        elif sv.algorithm == "cbow":
            lt.syn0, new_syn1, loss = cbow_step(
                lt.syn0, syn1, jnp.asarray(self.context),
                jnp.asarray(self.cmask), jnp.asarray(self.targets),
                jnp.asarray(self.labels), jnp.asarray(self.mask), lr)
            self._store_syn1(new_syn1)
            sv._record_loss(float(loss))
        else:
            lt.syn0, new_syn1, loss = skipgram_step(
                lt.syn0, syn1, jnp.asarray(self.center),
                jnp.asarray(self.targets), jnp.asarray(self.labels),
                jnp.asarray(self.mask), lr)
            self._store_syn1(new_syn1)
            sv._record_loss(float(loss))
        self.n = 0

    def _store_syn1(self, new_syn1):
        lt = self.sv.lookup_table
        if self.sv.negative > 0:
            lt.syn1neg = new_syn1
        else:
            lt.syn1 = new_syn1
