"""SequenceVectors: the generic embedding-training engine (reference
`models/sequencevectors/SequenceVectors.java:50`, `fit():161`; learning
algorithms SPI `models/embeddings/learning/` — `SkipGram.java`, `CBOW.java`).

TPU-first pipeline: the host walks sequences, applies subsampling and the
shrinking window, and packs (center, targets, labels, mask) int32 batches;
every full batch is one donated-buffer jitted scatter step
(`nlp/kernels.py`). Learning rate decays linearly with words processed, as
in the reference (`SequenceVectors.java:260` alpha handling).
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp import kernels
from deeplearning4j_tpu.nlp.lookup_table import InMemoryLookupTable
from deeplearning4j_tpu.nlp.vocab import (
    AbstractCache,
    VocabConstructor,
    build_huffman_tree,
)


class SequenceVectors:
    """Train element embeddings over sequences of tokens.

    elements_learning_algorithm: 'skipgram' | 'cbow'
    (reference `ElementsLearningAlgorithm` SPI).
    """

    def __init__(self,
                 layer_size: int = 100,
                 window: int = 5,
                 min_word_frequency: float = 1.0,
                 negative: int = 5,
                 use_hierarchic_softmax: bool = False,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 epochs: int = 1,
                 iterations: int = 1,
                 batch_size: int = 1024,
                 sampling: float = 0.0,
                 seed: int = 42,
                 elements_learning_algorithm: str = "skipgram",
                 scan_flushes: int = 32,
                 mesh=None,
                 data_axis: str = "data"):
        if negative <= 0 and not use_hierarchic_softmax:
            raise ValueError("need negative sampling (negative>0) and/or "
                             "hierarchical softmax")
        if negative > 0 and use_hierarchic_softmax and \
                elements_learning_algorithm == "cbow":
            raise NotImplementedError(
                "mixed HS+negative-sampling is only supported for skipgram")
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.negative = negative
        self.use_hs = use_hierarchic_softmax
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.epochs = epochs
        self.iterations = iterations
        self.batch_size = batch_size
        self.sampling = sampling
        self.seed = seed
        self.algorithm = elements_learning_algorithm
        # NS fast path: how many flush-batches ride one scanned dispatch
        self.scan_flushes = max(1, int(scan_flushes))
        self.vocab: Optional[AbstractCache] = None
        self.lookup_table: Optional[InMemoryLookupTable] = None
        self._rng = np.random.default_rng(seed)
        self._keep_cache: Optional[np.ndarray] = None
        self._unigram: Optional[np.ndarray] = None
        self._unigram_cdf: Optional[np.ndarray] = None
        self._ns_cdf_dev = None  # device copy of the cdf (NS-on-device)
        self._ns_key = None      # carried PRNG state for device sampling
        self._loss_sum = 0.0
        self._loss_batches = 0
        self._loss_dev = None
        self._loss_dev_count = 0
        # multi-chip data parallelism (the dl4j-spark-nlp role,
        # `spark/models/embeddings/word2vec/Word2VecPerformer.java`): pair
        # batches shard over the mesh's data axis, embedding tables stay
        # replicated, and XLA psums the scatter contributions over ICI —
        # where the reference map-reduces word2vec over Spark executors.
        self.mesh = mesh
        self.data_axis = data_axis
        if mesh is not None:
            n = mesh.shape[data_axis]
            if batch_size % n != 0:
                raise ValueError(
                    f"batch_size {batch_size} must divide by the "
                    f"'{data_axis}' mesh axis size {n}")
        self._sharded_kernels = None
        self._sharded_ns_kernel = None

    # -- vocab/init ---------------------------------------------------------
    def build_vocab(self, sequences: Iterable[Sequence[str]]) -> None:
        self.vocab = VocabConstructor(self.min_word_frequency).build_vocab(sequences)
        self._keep_cache = None
        if self.use_hs:
            build_huffman_tree(self.vocab)
        self.lookup_table = InMemoryLookupTable(
            self.vocab, self.layer_size, seed=self.seed, use_hs=self.use_hs,
            negative=self.negative)
        if self.negative > 0:
            self._unigram = self.vocab.unigram_table()
            self._unigram_cdf = None
            self._ns_cdf_dev = None

    # -- training -----------------------------------------------------------
    def fit(self, sequences: Iterable[Sequence[str]]) -> None:
        seqs = [list(s) for s in sequences]
        if self.vocab is None:
            self.build_vocab(seqs)
        total_words = max(
            1.0, self.vocab.total_word_occurrences * self.epochs * self.iterations)
        self._reset_loss()
        batch = _PairBatcher(self)
        if self.algorithm == "skipgram" and self.negative > 0 \
                and not self.use_hs:
            # NS skip-gram (the common configuration — BASELINE config 4):
            # fully vectorized host pipeline, see _fit_vectorized
            self._fit_vectorized(seqs, total_words, batch)
            batch.flush()
            return
        words_seen = 0.0
        for _ in range(self.epochs * self.iterations):
            for seq in seqs:
                ids = self._to_ids(seq)
                if len(ids) < 2:
                    continue
                alpha = max(self.min_learning_rate,
                            self.learning_rate * (1.0 - words_seen / total_words))
                self._train_sequence(ids, alpha, batch)
                words_seen += len(ids)
        batch.flush()

    # chunk size (tokens) for the vectorized pipeline: big enough that the
    # per-chunk numpy fixed costs amortize, small enough that the (L, 2W)
    # windowing grid stays ~20 MB and alpha decay keeps per-chunk
    # granularity (the reference decays per sentence batch,
    # `SequenceVectors.java:260`)
    _CHUNK_TOKENS = 262_144

    def _encode_corpus(self, seqs):
        """token→id for the whole corpus in ONE pass (OOV dropped): flat
        int32 id array + per-sentence kept lengths. The per-token dict
        lookup — the irreducible host cost — happens exactly once per fit,
        not once per epoch, and everything downstream is numpy array math.
        This finishes the `AggregateSkipGram` replacement host-side
        (reference `SkipGram.java:216` made windowing a native op because
        interpreted per-pair loops cannot keep an accelerator fed)."""
        lookup = {vw.word: vw.index for vw in self.vocab.vocab_words()}
        flat: List[int] = []
        lens = np.empty(len(seqs), np.int64)
        for si, seq in enumerate(seqs):
            ids = [i for i in map(lookup.get, seq) if i is not None]
            flat.extend(ids)
            lens[si] = len(ids)
        return np.asarray(flat, np.int32), lens

    def _keep_probs(self) -> np.ndarray:
        """Per-vocab-index subsampling keep probability
        P(keep) = sqrt(t/f) + t/f (word2vec's formula), computed once per
        vocab and cached (both the vectorized and the per-sentence paths
        index this array, so the two cannot drift)."""
        if self._keep_cache is None:
            # vocab_words() is index-ordered, so position == vocab index
            counts = np.array([vw.count for vw in self.vocab.vocab_words()],
                              np.float64)
            f = counts / self.vocab.total_word_occurrences
            self._keep_cache = np.minimum(
                1.0, np.sqrt(self.sampling / f) + self.sampling / f)
        return self._keep_cache

    def _fit_vectorized(self, seqs, total_words: float,
                        batch: "_PairBatcher") -> None:
        """Corpus-level vectorized NS skip-gram training: encode once, then
        per epoch run chunked whole-corpus windowing (subsampling and the
        shrinking window drawn as arrays, sentence boundaries enforced by a
        mask) and ship the (center, context) id arrays straight to the
        scanned device kernel. Replaces the per-sentence Python loop that
        made r3's word2vec number measure host CPU contention instead of
        the chip."""
        flat, lens = self._encode_corpus(seqs)
        if flat.size == 0:
            return
        starts = np.zeros(lens.size + 1, np.int64)
        np.cumsum(lens, out=starts[1:])
        keep = self._keep_probs() if self.sampling > 0 else None
        # chunk edges in sentence space, each chunk ~_CHUNK_TOKENS ids
        edges = [0]
        tok = 0
        for si in range(lens.size):
            tok += int(lens[si])
            if tok >= self._CHUNK_TOKENS:
                edges.append(si + 1)
                tok = 0
        if edges[-1] != lens.size:
            edges.append(lens.size)
        words_seen = 0.0
        for _ in range(self.epochs * self.iterations):
            for ci in range(len(edges) - 1):
                i, j = edges[ci], edges[ci + 1]
                ids = flat[starts[i]:starts[j]]
                lens_c = lens[i:j]
                if ids.size == 0:
                    continue
                if keep is not None:
                    m = self._rng.random(ids.size) < keep[ids]
                    sent_idx = np.repeat(np.arange(j - i), lens_c)
                    ids = ids[m]
                    lens_c = np.bincount(sent_idx[m], minlength=j - i)
                centers, contexts, counts = _window_pairs(
                    ids, lens_c, self.window, self._rng)
                if centers.size:
                    # per-PAIR linear alpha decay, indexed by the word
                    # position each pair's center occupies — finer than the
                    # reference's per-sentence decay
                    # (`SequenceVectors.java:260`), and in particular still
                    # decaying inside a single-chunk corpus
                    pos = np.repeat(np.arange(ids.size), counts)
                    alphas = np.maximum(
                        self.min_learning_rate,
                        self.learning_rate
                        * (1.0 - (words_seen + pos) / total_words)
                    ).astype(np.float32)
                    batch.add_pairs(centers, contexts, alphas)
                words_seen += float(ids.size)

    def _to_ids(self, seq: Sequence[str]) -> List[int]:
        keep = self._keep_probs() if self.sampling > 0 else None
        ids = []
        for tok in seq:
            i = self.vocab.index_of(tok)
            if i < 0:
                continue
            if keep is not None and self._rng.random() > keep[i]:
                continue
            ids.append(i)
        return ids

    def _train_sequence(self, ids: List[int], alpha: float, batch: "_PairBatcher"):
        window = self.window
        if self.algorithm == "skipgram" and self.negative > 0 \
                and not self.use_hs:
            # vectorized fast path (the common NS configuration): build the
            # whole sentence's (center, context) pair list with array ops —
            # the per-pair Python loop was the training bottleneck, not the
            # XLA scatter step. (SequenceVectors.fit no longer comes here —
            # it runs the chunked corpus-level _fit_vectorized — but
            # ParagraphVectors DBOW word training still does, per document.)
            arr = np.asarray(ids, np.int32)
            centers, contexts, _ = _window_pairs(
                arr, np.array([len(ids)], np.int64), window, self._rng)
            batch.add_pairs(centers, contexts, alpha)
            return
        for pos, center in enumerate(ids):
            b = int(self._rng.integers(1, window + 1))  # shrinking window
            lo, hi = max(0, pos - b), min(len(ids), pos + b + 1)
            context = [ids[j] for j in range(lo, hi) if j != pos]
            if not context:
                continue
            if self.algorithm == "skipgram":
                for c in context:
                    batch.add_pair(center, c, alpha)
            elif self.algorithm == "cbow":
                batch.add_cbow(context, center, alpha)
            else:
                raise ValueError(self.algorithm)

    # hooks used by _PairBatcher ------------------------------------------
    def _kernels(self):
        """(skipgram_step, cbow_step) — module-level jits single-chip, or
        mesh-sharded jits when a mesh was given (batch on the data axis,
        tables replicated; XLA inserts the ICI all-reduce of the scatter
        contributions)."""
        if self.mesh is None:
            return kernels.skipgram_step, kernels.cbow_step
        if self._sharded_kernels is None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(self.mesh, P())
            bsh = NamedSharding(self.mesh, P(self.data_axis))
            sg = jax.jit(kernels.skipgram_step.__wrapped__,
                         in_shardings=(repl, repl, bsh, bsh, bsh, bsh, repl),
                         out_shardings=(repl, repl, None),
                         donate_argnums=(0, 1))
            cb = jax.jit(kernels.cbow_step.__wrapped__,
                         in_shardings=(repl, repl, bsh, bsh, bsh, bsh, bsh,
                                       repl),
                         out_shardings=(repl, repl, None),
                         donate_argnums=(0, 1))
            self._sharded_kernels = (sg, cb)
        return self._sharded_kernels

    def _ns_kernel(self):
        """Device-side negative-sampling scanned skip-gram step (see
        `kernels.skipgram_ns_scan`). Sharded variant draws are identical to
        the single-chip ones because threefry is partitionable — mesh vs
        single-chip parity holds bit-for-bit (enforced by
        `kernels.require_partitionable_rng`)."""
        kernels.require_partitionable_rng()
        if self.mesh is None:
            return kernels.skipgram_ns_scan
        if self._sharded_ns_kernel is None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(self.mesh, P())
            bsh = NamedSharding(self.mesh, P(None, self.data_axis))
            self._sharded_ns_kernel = jax.jit(
                kernels.skipgram_ns_scan.__wrapped__,
                in_shardings=(repl, repl, bsh, bsh, repl, repl, repl, repl,
                              repl),
                out_shardings=(repl, repl, None, None),
                donate_argnums=(0, 1, 6), static_argnums=(9,))
        return self._sharded_ns_kernel

    def _ns_device_state(self):
        """(device cdf, carried PRNG key) for on-device negative sampling.
        The cdf ships as uint32 fixed point (f64 cumsum × 2^32): f32 would
        round adjacent tail entries of a large vocabulary equal, making
        those words unsampleable (see `kernels._ns_batch`)."""
        if self._unigram_cdf is None:
            self._unigram_cdf = np.cumsum(self._unigram)
        if self._ns_cdf_dev is None:
            fixed = np.minimum(np.round(self._unigram_cdf * 2.0 ** 32),
                               2.0 ** 32 - 1).astype(np.uint32)
            self._ns_cdf_dev = jnp.asarray(fixed)
        if self._ns_key is None:
            self._ns_key = jax.random.PRNGKey(self.seed)
        return self._ns_cdf_dev, self._ns_key

    def _sample_negatives(self, n) -> np.ndarray:
        """Draw from the 0.75-power unigram distribution. Inverse-CDF via
        searchsorted: O(log V) per draw and fully vectorizable — the
        per-pair `rng.choice(p=...)` it replaces rebuilt an O(V) sampler
        per call and dominated the whole training loop. `n` may be a shape
        tuple."""
        if self._unigram_cdf is None:
            self._unigram_cdf = np.cumsum(self._unigram)
        idx = np.searchsorted(self._unigram_cdf, self._rng.random(n))
        # cumsum rounding can leave cdf[-1] slightly below 1.0, in which
        # case a draw above it would index past the vocabulary
        return np.minimum(idx, len(self._unigram) - 1).astype(np.int32)

    def _reset_loss(self) -> None:
        """Zero ALL loss-accumulation state (host f64 sum, batch count, and
        the carried device accumulator) — every fit entry point must call
        this, or a prior fit's undrained device sum leaks into the next."""
        self._loss_sum, self._loss_batches = 0.0, 0
        self._loss_dev, self._loss_dev_count = None, 0

    def _record_loss(self, loss) -> None:
        """Accumulate WITHOUT a per-flush host sync: reading `float(loss)`
        per flush cost a full tunnel round trip (~115ms) and was 80% of
        training wall-clock. The per-flush losses chain into ONE device
        scalar (an async eager add — never a list of buffers: fetching N
        separate remote scalars costs N round trips), which is folded into
        the host f64 sum every `_LOSS_FOLD` flushes with a single one-
        scalar sync — an f32 running sum alone would stop absorbing small
        increments on very long runs."""
        self._loss_dev = loss if self._loss_dev is None else self._loss_dev + loss
        self._loss_batches += 1
        self._loss_dev_count += 1
        if self._loss_dev_count >= self._LOSS_FOLD:
            self._drain_loss()

    def _record_loss_acc(self, acc, n_batches: int = 1) -> None:
        """Store a kernel-carried running sum (the accumulation already
        happened inside the jitted step — no eager dispatch here)."""
        self._loss_dev = acc
        self._loss_batches += n_batches
        self._loss_dev_count += n_batches
        if self._loss_dev_count >= self._LOSS_FOLD:
            self._drain_loss()

    _LOSS_FOLD = 256

    def _drain_loss(self) -> None:
        if self._loss_dev is not None:
            self._loss_sum += float(self._loss_dev)
            self._loss_dev = None
            self._loss_dev_count = 0

    @property
    def mean_loss(self) -> float:
        self._drain_loss()
        return self._loss_sum / max(self._loss_batches, 1)

    # -- query passthrough --------------------------------------------------
    def words_nearest(self, word, top_n: int = 10):
        return self.lookup_table.words_nearest(word, top_n)

    def similarity(self, w1: str, w2: str) -> float:
        return self.lookup_table.similarity(w1, w2)

    def get_word_vector(self, word: str):
        return self.lookup_table.vector(word)


def _window_pairs(ids: np.ndarray, lens: np.ndarray, window: int,
                  rng) -> tuple:
    """Skip-gram windowing over a chunk of concatenated sentences, fully
    vectorized: per-position shrinking windows b ~ U[1, window] drawn as one
    array, an (L, 2*window) index grid, and a validity mask that enforces
    both the window radius and same-sentence bounds. Returns aligned
    (centers, contexts) int32 arrays plus the per-position pair count —
    the host half of the reference's `AggregateSkipGram` native op
    (`SkipGram.java:216`)."""
    L = ids.size
    if L == 0:
        return (np.empty(0, np.int32),) * 2 + (np.empty(0, np.int64),)
    b = rng.integers(1, window + 1, L)  # shrinking windows
    offs = np.concatenate([np.arange(-window, 0), np.arange(1, window + 1)])
    grid = np.arange(L)[:, None] + offs[None, :]
    ends = np.cumsum(lens)
    sent_of = np.repeat(np.arange(lens.size), lens)
    lo = (ends - lens)[sent_of][:, None]
    hi = ends[sent_of][:, None]
    valid = ((np.abs(offs)[None, :] <= b[:, None])
             & (grid >= lo) & (grid < hi))
    counts = valid.sum(1)
    centers = np.repeat(ids, counts)
    contexts = ids[grid[valid]]  # row-major: aligned with repeat
    return centers, contexts, counts


class _PairBatcher:
    """Accumulates training examples into fixed-shape arrays and flushes
    them through the jitted kernels (fixed batch shape ⇒ one XLA
    compilation; the tail batch is mask-padded)."""

    def __init__(self, sv: SequenceVectors):
        self.sv = sv
        B = sv.batch_size
        # target row count: negatives+1 (NS) and/or max code length (HS)
        self._max_codes = 0
        if sv.use_hs:
            self._max_codes = max((len(vw.codes)
                                   for vw in sv.vocab.vocab_words()), default=0)
        self.K = (sv.negative + 1 if sv.negative > 0 else 0) + self._max_codes
        self.W = 2 * sv.window
        self.center = np.zeros(B, np.int32)
        self.targets = np.zeros((B, self.K), np.int32)
        self.labels = np.zeros((B, self.K), np.float32)
        self.mask = np.zeros((B, self.K), np.float32)
        self.context = np.zeros((B, self.W), np.int32)
        self.cmask = np.zeros((B, self.W), np.float32)
        # pair-mode staging: scan_k flush-batches accumulate and go to the
        # device as ONE scanned dispatch (per-operation tunnel latency is
        # the throughput ceiling, so amortize it over scan_k batches)
        self.scan_k = max(1, int(getattr(sv, "scan_flushes", 32)))
        self.pair_center = np.zeros(B * self.scan_k, np.int32)
        self.pair_context = np.zeros(B * self.scan_k, np.int32)
        self.row_alpha = np.full(self.scan_k, 0.025, np.float32)
        self.alpha = 0.025
        self.n = 0
        # "pairs" = NS-only skip-gram fast path (negatives drawn on device,
        # flush ships two (scan_k, B) id arrays); "generic" = host-built
        # (B, K) target/label/mask rows (HS, CBOW, ParagraphVectors
        # add_pair). A batcher serves ONE mode for its lifetime.
        self._mode: Optional[str] = None

    def _fill_targets(self, row: int, predicted: int):
        """Targets for predicting word id `predicted`: NS = [pos, negs];
        HS = its Huffman path (labels = 1 - code)."""
        sv = self.sv
        k = 0
        if sv.negative > 0:
            self.targets[row, 0] = predicted
            self.labels[row, 0] = 1.0
            self.mask[row, 0] = 1.0
            negs = sv._sample_negatives(sv.negative)
            for ng in negs:
                k += 1
                self.targets[row, k] = ng
                self.labels[row, k] = 0.0
                # word2vec skips a negative that equals the positive
                self.mask[row, k] = 0.0 if ng == predicted else 1.0
            k += 1
        if sv.use_hs:
            vw = sv.vocab.element_at_index(predicted)
            for code, point in zip(vw.codes, vw.points):
                self.targets[row, k] = point
                self.labels[row, k] = 1.0 - code
                self.mask[row, k] = 1.0
                k += 1

    def add_pairs(self, centers: np.ndarray, contexts: np.ndarray,
                  alpha):
        """Bulk skip-gram add (NS-only fast path): stages just the
        (center, context) id pairs — negatives, labels, and masks are built
        on device by `skipgram_ns_scan`. `alpha` is a scalar or a per-pair
        array (the kernel applies one learning rate per flush-row of B
        pairs; an array alpha sets each row's rate from its first pair)."""
        if self._mode == "generic":
            raise RuntimeError("batcher already in generic mode")
        self._mode = "pairs"
        B = len(self.center)
        cap = len(self.pair_center)
        i, n_total = 0, len(centers)
        while i < n_total:
            take = min(cap - self.n, n_total - i)
            rows = slice(self.n, self.n + take)
            self.pair_center[rows] = centers[i:i + take]
            self.pair_context[rows] = contexts[i:i + take]
            r0, r1 = self.n // B, (self.n + take - 1) // B + 1
            if np.ndim(alpha) == 0:
                self.row_alpha[r0:r1] = alpha
            else:
                firsts = np.maximum(np.arange(r0, r1) * B, self.n) \
                    - self.n + i
                self.row_alpha[r0:r1] = alpha[firsts]
            self.n += take
            i += take
            if self.n == cap:
                self.flush()

    def add_pair(self, center: int, context: int, alpha: float):
        """Skip-gram: center predicts context. In the NS-only configuration
        this stages the raw pair for device-side sampling (same mode as
        add_pairs, so DBOW doc-pairs and word training share one batcher);
        with hierarchical softmax the targets are built host-side."""
        sv = self.sv
        if sv.negative > 0 and not sv.use_hs:
            if self._mode == "generic":
                raise RuntimeError("batcher already in generic mode")
            self._mode = "pairs"
            row = self.n
            self.pair_center[row] = center
            self.pair_context[row] = context
            self.row_alpha[row // len(self.center)] = alpha
            self.n += 1
            if self.n == len(self.pair_center):
                self.flush()
            return
        if self._mode == "pairs":
            raise RuntimeError("batcher already in pairs mode")
        self._mode = "generic"
        row = self.n
        self.center[row] = center
        self.targets[row] = 0
        self.labels[row] = 0
        self.mask[row] = 0
        self._fill_targets(row, context)
        self.alpha = alpha
        self.n += 1
        if self.n == len(self.center):
            self.flush()

    def add_cbow(self, context: List[int], center: int, alpha: float):
        if self._mode == "pairs":
            raise RuntimeError("batcher already in pairs mode")
        self._mode = "generic"
        row = self.n
        self.context[row] = 0
        self.cmask[row] = 0
        w = min(len(context), self.W)
        self.context[row, :w] = context[:w]
        self.cmask[row, :w] = 1.0
        self.targets[row] = 0
        self.labels[row] = 0
        self.mask[row] = 0
        self._fill_targets(row, center)
        self.alpha = alpha
        self.n += 1
        if self.n == len(self.center):
            self.flush()

    def flush(self):
        if self.n == 0:
            return
        sv = self.sv
        lt = sv.lookup_table
        # COPY the staging buffers before dispatch: device_put of a numpy
        # array can be ZERO-COPY (it aliases host memory, notably on the CPU
        # backend), and the async step may still be reading while the next
        # batch overwrites these rows. Without copies, training corrupts
        # nondeterministically once nothing forces a per-flush sync.
        ja = lambda a: jnp.asarray(np.array(a))  # np.array always copies
        lr = jnp.float32(self.alpha)
        if self._mode == "pairs":
            cdf, key = sv._ns_device_state()
            step = sv._ns_kernel()
            acc = (sv._loss_dev if sv._loss_dev is not None
                   else jnp.float32(0.0))
            B = len(self.center)
            Ks = self.scan_k
            # always dispatch the full (scan_k, B) shape — tail rows get
            # nvalid=0 (fully masked) so there is exactly ONE compilation
            nvalids = np.clip(self.n - np.arange(Ks) * B, 0, B).astype(np.int32)
            n_rows = -(-self.n // B)  # batches actually represented
            lt.syn0, lt.syn1neg, new_acc, sv._ns_key = step(
                lt.syn0, lt.syn1neg,
                ja(self.pair_center.reshape(Ks, B)),
                ja(self.pair_context.reshape(Ks, B)),
                cdf, key, acc, ja(self.row_alpha), ja(nvalids), sv.negative)
            sv._record_loss_acc(new_acc, n_batches=n_rows)
            self.n = 0
            return
        self.mask[self.n:] = 0.0
        self.cmask[self.n:] = 0.0
        syn1 = lt.syn1neg if sv.negative > 0 else lt.syn1
        skipgram_step, cbow_step = sv._kernels()
        if sv.use_hs and sv.negative > 0:
            # mixed mode: split columns — NS rows live in syn1neg, HS rows
            # in syn1; run two steps on the column slices
            ns_cols = sv.negative + 1
            center = ja(self.center)
            lt.syn0, lt.syn1neg, loss1 = skipgram_step(
                lt.syn0, lt.syn1neg, center,
                ja(self.targets[:, :ns_cols]),
                ja(self.labels[:, :ns_cols]),
                ja(self.mask[:, :ns_cols]), lr)
            lt.syn0, lt.syn1, loss2 = skipgram_step(
                lt.syn0, lt.syn1, center,
                ja(self.targets[:, ns_cols:]),
                ja(self.labels[:, ns_cols:]),
                ja(self.mask[:, ns_cols:]), lr)
            sv._record_loss(loss1 + loss2)
        elif sv.algorithm == "cbow":
            lt.syn0, new_syn1, loss = cbow_step(
                lt.syn0, syn1, ja(self.context),
                ja(self.cmask), ja(self.targets),
                ja(self.labels), ja(self.mask), lr)
            self._store_syn1(new_syn1)
            sv._record_loss(loss)
        else:
            lt.syn0, new_syn1, loss = skipgram_step(
                lt.syn0, syn1, ja(self.center),
                ja(self.targets), ja(self.labels),
                ja(self.mask), lr)
            self._store_syn1(new_syn1)
            sv._record_loss(loss)
        self.n = 0

    def _store_syn1(self, new_syn1):
        lt = self.sv.lookup_table
        if self.sv.negative > 0:
            lt.syn1neg = new_syn1
        else:
            lt.syn1 = new_syn1
