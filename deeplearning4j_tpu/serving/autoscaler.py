"""Closed-loop autoscaler: elastic replica count driven by live load.

The pool machinery (PRs 6-15) made replica count a DEPLOY-TIME choice:
`ReplicaPool` routes across a fixed set, `ReplicaSupervisor` respawns
the fixed set, and a diurnal traffic swell either overloads the fixed
set (typed sheds) or wastes idle accelerators all night. `Autoscaler`
closes the loop:

- **signal** — every `interval` it samples the pool's own telemetry
  (the PR-11 metrics/stats contract): pool in-flight vs the admission
  budget, per-replica queue fill, decode-engine slot occupancy and
  `pages_in_use` / `pool_pages`, queued page demand vs the wait-room
  cap — and counts fresh p99-excursion pins in the locally readable
  flight recorders. The max of those ratios is the instantaneous
  *pressure* (1.0 = some resource is saturated), folded into an EWMA
  so one bursty sample cannot thrash the fleet.
- **hysteresis** — only `hysteresis` CONSECUTIVE samples with the EWMA
  past `high_watermark` scale up, and only as many consecutive samples
  under `low_watermark` scale down; every action starts a `cooldown`
  window in which no further action fires (the new replica needs time
  to take load before the signal is trusted again).
- **scale-up** — through the same machinery a crash-recovery uses:
  `RemoteReplicaPool.grow_replica()` (supervisor `grow_slot` → fresh
  readiness-gated process → `pool.add_replica`) or a caller-supplied
  `spawn()` for in-process pools. The new replica enters EVICTED and
  serves nothing until the probe ladder re-admits it — scale-up can
  never route traffic onto an unproven replica. Bounded by
  `max_replicas`; supervisor exhaustion surfaces as the typed
  `AutoscaleError` (counted, recorded, never fatal to the loop).
- **scale-down** — the rolling-reload drain discipline, zero failed
  requests: the victim stops taking traffic, its in-flight work
  FINISHES, and only then does it leave the pool
  (`ReplicaPool.remove_replica` aborts the removal typed if the drain
  cannot complete). Remote victims' supervisor slots are retired so
  the process is stopped and never respawned. Bounded by
  `min_replicas`.

Lock order: `Autoscaler._lock` is a LEAF — it guards only the
scaler's own counters/EWMA and is never held across a call into the
pool or supervisor (whose locks are acquired freely while no
autoscaler lock is held). Sample → decide (under `_lock`) → act
(no locks held) → account (under `_lock`).

`stats()` registers into the pool's metrics registry under
``autoscaler`` — `autoscale_events`, `scale_ups`, `scale_downs`,
failures, and the live pressure/EWMA — and every decision lands in
the pool's flight recorder as an ``autoscale`` event carrying the
deciding metric values (the chaos drill asserts the timeline names
every decision).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional

from deeplearning4j_tpu.serving.model_server import AutoscaleError

logger = logging.getLogger("deeplearning4j_tpu")


class Autoscaler:
    """Watch one `ReplicaPool`'s telemetry; grow/shrink its replica set.

    `spawn` (optional) builds one ready `ModelServer`-shaped server for
    in-process pools; without it the pool must expose `grow_replica`
    (`RemoteReplicaPool`). `dispose` (optional) tears down a server
    returned by `remove_replica` on the in-process path (default:
    ``server.shutdown()``)."""

    def __init__(self, pool, *,
                 min_replicas: int = 1,
                 max_replicas: int = 4,
                 interval: float = 0.5,
                 alpha: float = 0.3,
                 high_watermark: float = 0.75,
                 low_watermark: float = 0.25,
                 hysteresis: int = 3,
                 cooldown: float = 5.0,
                 drain_timeout: float = 30.0,
                 excursion_weight: float = 0.25,
                 spawn: Optional[Callable] = None,
                 dispose: Optional[Callable] = None):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if interval <= 0:
            raise ValueError("interval must be > 0")
        if not 0.0 < alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1]")
        if not 0.0 <= low_watermark < high_watermark:
            raise ValueError(
                "watermarks must satisfy 0 <= low < high")
        if hysteresis < 1:
            raise ValueError("hysteresis must be >= 1")
        self.pool = pool
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.interval = interval
        self.alpha = alpha
        self.high_watermark = high_watermark
        self.low_watermark = low_watermark
        self.hysteresis = hysteresis
        self.cooldown = cooldown
        self.drain_timeout = drain_timeout
        self.excursion_weight = excursion_weight
        self._spawn = spawn
        self._dispose = dispose
        self._lock = threading.Lock()
        self._pressure = 0.0  # guarded by: _lock
        self._pressure_ewma = 0.0  # guarded by: _lock
        self._above = 0  # guarded by: _lock
        self._below = 0  # guarded by: _lock
        self._cooldown_until = 0.0  # guarded by: _lock
        self._last_excursion_scan = time.time()  # guarded by: _lock
        self._last_decision = "none"  # guarded by: _lock
        # how long the most recent migrate-then-drain shrink took; stays
        # 0.0 until the first scale-down — guarded by: _lock
        self._last_scale_down_ms = 0.0
        self.autoscale_events = 0  # guarded by: _lock
        self.scale_ups = 0  # guarded by: _lock
        self.scale_downs = 0  # guarded by: _lock
        self.autoscale_failures = 0  # guarded by: _lock
        self.samples = 0  # guarded by: _lock
        self._closed = False  # guarded by: _lock
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        pool.metrics.register_stats("autoscaler", self.stats)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="autoscaler")
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            self._closed = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(self.interval + 5.0)

    def _loop(self) -> None:
        while True:
            self._wake.wait(self.interval)
            self._wake.clear()
            with self._lock:
                if self._closed:
                    return
            try:
                self.tick()
            # graftlint: disable=typed-error  the control loop must
            # outlive any one bad sample/action; the failure is counted
            # and recorded, and the next tick re-reads ground truth
            except BaseException as e:
                with self._lock:
                    self.autoscale_failures += 1
                self.pool.recorder.event(
                    "autoscale", direction="error",
                    error=type(e).__name__, detail=str(e)[:200])
                logger.warning("autoscaler: tick failed (%s: %s)",
                               type(e).__name__, e)

    # -- signal ------------------------------------------------------------
    def _sample_pressure(self) -> float:
        """Instantaneous pressure in [0, ~1]: the max saturation ratio
        across every resource that sheds when full, plus an excursion
        term — fresh p99 pins push pressure up even while queues are
        nominally short (tail latency is load the counters miss)."""
        st = self.pool.stats()
        ratios = [st["pool_in_flight"] / max(1, st["admission_budget"])]
        for s in st["replicas"].values():
            if s.get("state") != "healthy":
                continue
            depth = s.get("queue_depth") or 1
            ratios.append(s.get("queued", 0) / depth)
            gen = s.get("generation")
            if not gen:
                continue
            ratios.append(gen["active_slots"] / max(1, gen["n_slots"]))
            ratios.append(gen["pages_in_use"] / max(1, gen["pool_pages"]))
            ratios.append(gen["queued_page_demand"]
                          / max(1, gen["max_queued_pages"]))
        excursions = self._fresh_excursions()
        if excursions:
            ratios.append(min(1.0, excursions * self.excursion_weight))
        return max(ratios)

    def _recorders(self) -> List:
        """Locally readable flight recorders: the pool's own ring plus
        any in-process replica server's (a `RemoteReplica` keeps no
        local recorder — its excursions surface in the remote process
        and reach us through that replica's queue/occupancy ratios
        instead)."""
        recs = [self.pool.recorder]
        for rep in list(getattr(self.pool, "_replicas", [])):
            rec = getattr(rep.server, "recorder", None)
            if rec is not None and hasattr(rec, "dump"):
                recs.append(rec)
        return recs

    def _fresh_excursions(self) -> int:
        """p99-excursion events pinned since the previous sample."""
        with self._lock:
            since = self._last_excursion_scan
            self._last_excursion_scan = time.time()
        n = 0
        for rec in self._recorders():
            try:
                events = rec.dump().get("events", [])
            # graftlint: disable=typed-error  a replica mid-teardown
            # must not kill the sampling tick
            except Exception:
                continue
            n += sum(1 for e in events
                     if e.get("kind") == "excursion"
                     and e.get("wall_time", 0.0) > since)
        return n

    # -- decision ----------------------------------------------------------
    def tick(self) -> Optional[str]:
        """One control iteration: sample, fold, decide, act. Returns
        the action taken ("up"/"down") or None. Exposed for tests and
        for callers that drive the loop themselves."""
        pressure = self._sample_pressure()
        now = time.monotonic()
        with self._lock:
            self.samples += 1
            self._pressure = pressure
            self._pressure_ewma = ((1 - self.alpha) * self._pressure_ewma
                                   + self.alpha * pressure)
            ewma = self._pressure_ewma
            if ewma > self.high_watermark:
                self._above += 1
                self._below = 0
            elif ewma < self.low_watermark:
                self._below += 1
                self._above = 0
            else:
                self._above = 0
                self._below = 0
            in_cooldown = now < self._cooldown_until
            want_up = self._above >= self.hysteresis and not in_cooldown
            want_down = self._below >= self.hysteresis and not in_cooldown
        n = self.pool.n_replicas
        if want_up and n < self.max_replicas:
            self.scale_up()
            return "up"
        if want_down and n > self.min_replicas:
            self.scale_down()
            return "down"
        return None

    def _account(self, direction: str, **attrs) -> None:
        with self._lock:
            self.autoscale_events += 1
            if direction == "up":
                self.scale_ups += 1
            elif direction == "down":
                self.scale_downs += 1
            self._above = 0
            self._below = 0
            self._cooldown_until = time.monotonic() + self.cooldown
            self._last_decision = direction
            pressure, ewma = self._pressure, self._pressure_ewma
        self.pool.recorder.event(
            "autoscale", direction=direction, pressure=round(pressure, 4),
            pressure_ewma=round(ewma, 4), n_replicas=self.pool.n_replicas,
            high_watermark=self.high_watermark,
            low_watermark=self.low_watermark, **attrs)

    # -- actions -----------------------------------------------------------
    def scale_up(self) -> int:
        """Add one replica (probe-ladder gated). Returns the new pool
        replica id. Raises the typed `AutoscaleError` when the bound is
        hit or the spawn path is exhausted."""
        if self.pool.n_replicas >= self.max_replicas:
            raise AutoscaleError(
                f"already at max_replicas={self.max_replicas}")
        try:
            if self._spawn is not None:
                rid = self.pool.add_replica(self._spawn())
            elif hasattr(self.pool, "grow_replica"):
                rid = self.pool.grow_replica()
            else:
                raise AutoscaleError(
                    "no scale-up path: pool has no grow_replica and no "
                    "spawn callable was configured")
        except AutoscaleError:
            with self._lock:
                self.autoscale_failures += 1
                self._cooldown_until = time.monotonic() + self.cooldown
            raise
        # graftlint: disable=typed-error  supervisor/spawn failures wrap
        # into the control plane's typed error; the pool keeps serving
        # at its previous size
        except BaseException as e:
            with self._lock:
                self.autoscale_failures += 1
                self._cooldown_until = time.monotonic() + self.cooldown
            self.pool.recorder.event("autoscale", direction="up-failed",
                                     error=type(e).__name__)
            raise AutoscaleError(
                f"scale-up failed: {type(e).__name__}: {e}") from e
        self._account("up", replica=rid)
        logger.info("autoscaler: scaled up to %d replicas (replica %d)",
                    self.pool.n_replicas, rid)
        return rid

    def scale_down(self) -> int:
        """Drain + remove one replica (zero failed requests — aborts
        typed if the victim cannot drain). The pool's drain is
        migrate-then-drain: the victim's in-flight generations export
        as leased KV handoffs and resume mid-sequence on surviving
        replicas (`serving.kv_transfer`), so scale-down no longer waits
        on — or re-computes — long decode tails. Returns the removed
        replica id."""
        if self.pool.n_replicas <= self.min_replicas:
            raise AutoscaleError(
                f"already at min_replicas={self.min_replicas}")
        victim = self._pick_victim()
        if victim is None:
            raise AutoscaleError(
                "no healthy replica is removable right now")
        t0 = time.monotonic()
        try:
            if hasattr(self.pool, "shrink_replica"):
                self.pool.shrink_replica(
                    victim, drain_timeout=self.drain_timeout)
            else:
                server = self.pool.remove_replica(
                    victim, drain_timeout=self.drain_timeout)
                if self._dispose is not None:
                    self._dispose(server)
                else:
                    server.shutdown()
        except AutoscaleError:
            with self._lock:
                self.autoscale_failures += 1
                self._cooldown_until = time.monotonic() + self.cooldown
            raise
        # migrate-then-drain makes this a bounded handoff, not a wait
        # on the longest in-flight generation — the duration stat is
        # the regression alarm for that property
        duration_ms = round((time.monotonic() - t0) * 1000.0, 1)
        with self._lock:
            self._last_scale_down_ms = duration_ms
        self._account("down", replica=victim, duration_ms=duration_ms)
        logger.info("autoscaler: scaled down to %d replicas (removed %d "
                    "in %.1fms)", self.pool.n_replicas, victim,
                    duration_ms)
        return victim

    def _pick_victim(self) -> Optional[int]:
        """Least-loaded healthy replica: it drains fastest and the
        pool loses the least in-flight capacity."""
        st = self.pool.stats()
        candidates = [
            (s.get("queued", 0) + s.get("in_flight", 0), int(rid))
            for rid, s in st["replicas"].items()
            if s.get("state") == "healthy"]
        if not candidates:
            return None
        return min(candidates)[1]

    # -- telemetry ---------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "autoscale_events": self.autoscale_events,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "autoscale_failures": self.autoscale_failures,
                "samples": self.samples,
                "pressure": round(self._pressure, 4),
                "pressure_ewma": round(self._pressure_ewma, 4),
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "cooldown_remaining": round(
                    max(0.0, self._cooldown_until - time.monotonic()), 3),
                "last_decision": self._last_decision,
                "last_scale_down_ms": self._last_scale_down_ms,
            }
