"""Robust serving tier: admission control, per-request deadlines with
adaptive micro-batching, circuit breaking, safe hot model reload, a
continuous-batching generation path (`DecodeEngine`: paged KV cache,
chunked prefill + iteration-level scheduling, with an opt-in latency
tier — `PrefixCache` shared-prefix KV reuse and `SpeculativeDecoder`
draft-verify decoding), and a replicated serving pool (`ReplicaPool`:
health-probed replicas, least-loaded routing with failover, hedged
predicts, zero-downtime rolling reload) — the inference-path
counterpart of the training robustness tier (elastic workers / durable
checkpoints / health sentinel). See `docs/serving.md` for the ladder
semantics and tuning knobs.
"""
from deeplearning4j_tpu.serving.autoscaler import Autoscaler
from deeplearning4j_tpu.serving.chaos import (
    BrokenModelInjector,
    ChaosProxy,
    ConnectionResetInjector,
    GarbageResponseInjector,
    InjectedServingFault,
    JournalCorruptionInjector,
    KVTransferCorruptionInjector,
    LoadSpikeInjector,
    NetworkLatencyInjector,
    PartitionInjector,
    PrefixFetchSaboteur,
    ReloadCorruptionInjector,
    ReplicaCrashInjector,
    ReplicaHangInjector,
    SlowConsumerInjector,
    SlowInferenceInjector,
    SlowLorisInjector,
    TenantFloodInjector,
)
from deeplearning4j_tpu.serving.decode_engine import DecodeEngine
from deeplearning4j_tpu.serving.exactly_once import (
    DedupCache,
    ExactlyOnceDoor,
    RequestJournal,
    ResultPendingError,
    UnknownRequestError,
)
from deeplearning4j_tpu.serving.kv_transfer import (
    DisaggCoordinator,
    KVTransferError,
    LeaseTable,
    SlotMigratedError,
)
from deeplearning4j_tpu.serving.observability import (
    FlightRecorder,
    MetricsRegistry,
    Trace,
    attach_trace,
    current_trace,
    maybe_trace,
    tracing_enabled,
    use_trace,
)
from deeplearning4j_tpu.serving.prefix_cache import PrefixCache, chain_keys
from deeplearning4j_tpu.serving.prefix_directory import PrefixDirectory
from deeplearning4j_tpu.serving.quantize import (
    argmax_drift_rate,
    drift_report,
    perplexity,
    quantize_net_weights,
)
from deeplearning4j_tpu.serving.speculative import SpeculativeDecoder
from deeplearning4j_tpu.serving.streaming import (
    StreamBackpressureError,
    StreamRegistry,
    TokenStream,
)
from deeplearning4j_tpu.serving.model_server import (
    AutoscaleError,
    CircuitBreaker,
    DeadlineExceededError,
    InferenceFailedError,
    ModelServer,
    ModelValidationError,
    OutOfPagesError,
    ServerClosedError,
    ServerOverloadedError,
    ServiceUnavailableError,
    ServingError,
    TenantQuotaExceededError,
)
from deeplearning4j_tpu.serving.replica_pool import (
    ReplicaEvictedError,
    ReplicaPool,
)

# the cross-process tier resolves lazily (PEP 562): remote_replica
# imports gateway, and gateway imports THIS package for observability —
# an eager import here would close that cycle while gateway is still
# half-executed. By the time anyone touches these names, gateway is
# fully loaded.
_REMOTE_NAMES = frozenset({
    "RemoteReplica",
    "RemoteReplicaPool",
    "ReplicaEntryPoint",
    "ReplicaSpawnError",
    "ReplicaSupervisor",
    "spawn_replica_pool",
})


def __getattr__(name):
    if name in _REMOTE_NAMES:
        from deeplearning4j_tpu.serving import remote_replica

        return getattr(remote_replica, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "AutoscaleError",
    "Autoscaler",
    "BrokenModelInjector",
    "ChaosProxy",
    "CircuitBreaker",
    "ConnectionResetInjector",
    "DeadlineExceededError",
    "DecodeEngine",
    "DedupCache",
    "DisaggCoordinator",
    "ExactlyOnceDoor",
    "FlightRecorder",
    "GarbageResponseInjector",
    "InferenceFailedError",
    "InjectedServingFault",
    "JournalCorruptionInjector",
    "KVTransferCorruptionInjector",
    "KVTransferError",
    "LeaseTable",
    "LoadSpikeInjector",
    "MetricsRegistry",
    "ModelServer",
    "ModelValidationError",
    "NetworkLatencyInjector",
    "OutOfPagesError",
    "PartitionInjector",
    "PrefixCache",
    "PrefixDirectory",
    "PrefixFetchSaboteur",
    "RemoteReplica",
    "RemoteReplicaPool",
    "ReplicaEntryPoint",
    "ReplicaSpawnError",
    "ReplicaSupervisor",
    "RequestJournal",
    "ResultPendingError",
    "SpeculativeDecoder",
    "ReloadCorruptionInjector",
    "ReplicaCrashInjector",
    "ReplicaEvictedError",
    "ReplicaHangInjector",
    "ReplicaPool",
    "ServerClosedError",
    "ServerOverloadedError",
    "ServiceUnavailableError",
    "ServingError",
    "SlotMigratedError",
    "SlowConsumerInjector",
    "SlowInferenceInjector",
    "SlowLorisInjector",
    "TenantFloodInjector",
    "TenantQuotaExceededError",
    "TokenStream",
    "StreamBackpressureError",
    "StreamRegistry",
    "Trace",
    "UnknownRequestError",
    "spawn_replica_pool",
    "argmax_drift_rate",
    "attach_trace",
    "chain_keys",
    "current_trace",
    "drift_report",
    "maybe_trace",
    "perplexity",
    "quantize_net_weights",
    "tracing_enabled",
    "use_trace",
]
