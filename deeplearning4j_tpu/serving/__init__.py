"""Robust serving tier: admission control, per-request deadlines with
adaptive micro-batching, circuit breaking, safe hot model reload, and a
continuous-batching generation path (`DecodeEngine`: paged KV cache,
chunked prefill + iteration-level scheduling) — the inference-path
counterpart of the
training robustness tier (elastic workers / durable checkpoints /
health sentinel). See `docs/serving.md` for the ladder semantics and
tuning knobs.
"""
from deeplearning4j_tpu.serving.chaos import (
    BrokenModelInjector,
    InjectedServingFault,
    ReloadCorruptionInjector,
    SlowInferenceInjector,
)
from deeplearning4j_tpu.serving.decode_engine import DecodeEngine
from deeplearning4j_tpu.serving.model_server import (
    CircuitBreaker,
    DeadlineExceededError,
    InferenceFailedError,
    ModelServer,
    ModelValidationError,
    OutOfPagesError,
    ServerClosedError,
    ServerOverloadedError,
    ServiceUnavailableError,
    ServingError,
)

__all__ = [
    "BrokenModelInjector",
    "CircuitBreaker",
    "DeadlineExceededError",
    "DecodeEngine",
    "InferenceFailedError",
    "InjectedServingFault",
    "ModelServer",
    "ModelValidationError",
    "OutOfPagesError",
    "ReloadCorruptionInjector",
    "ServerClosedError",
    "ServerOverloadedError",
    "ServiceUnavailableError",
    "ServingError",
    "SlowInferenceInjector",
]
