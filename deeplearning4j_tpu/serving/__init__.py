"""Robust serving tier: admission control, per-request deadlines with
adaptive micro-batching, circuit breaking, safe hot model reload, a
continuous-batching generation path (`DecodeEngine`: paged KV cache,
chunked prefill + iteration-level scheduling, with an opt-in latency
tier — `PrefixCache` shared-prefix KV reuse and `SpeculativeDecoder`
draft-verify decoding), and a replicated serving pool (`ReplicaPool`:
health-probed replicas, least-loaded routing with failover, hedged
predicts, zero-downtime rolling reload) — the inference-path
counterpart of the training robustness tier (elastic workers / durable
checkpoints / health sentinel). See `docs/serving.md` for the ladder
semantics and tuning knobs.
"""
from deeplearning4j_tpu.serving.chaos import (
    BrokenModelInjector,
    InjectedServingFault,
    ReloadCorruptionInjector,
    ReplicaCrashInjector,
    ReplicaHangInjector,
    SlowInferenceInjector,
)
from deeplearning4j_tpu.serving.decode_engine import DecodeEngine
from deeplearning4j_tpu.serving.observability import (
    FlightRecorder,
    MetricsRegistry,
    Trace,
    attach_trace,
    current_trace,
    maybe_trace,
    tracing_enabled,
    use_trace,
)
from deeplearning4j_tpu.serving.prefix_cache import PrefixCache
from deeplearning4j_tpu.serving.quantize import (
    argmax_drift_rate,
    drift_report,
    perplexity,
    quantize_net_weights,
)
from deeplearning4j_tpu.serving.speculative import SpeculativeDecoder
from deeplearning4j_tpu.serving.model_server import (
    CircuitBreaker,
    DeadlineExceededError,
    InferenceFailedError,
    ModelServer,
    ModelValidationError,
    OutOfPagesError,
    ServerClosedError,
    ServerOverloadedError,
    ServiceUnavailableError,
    ServingError,
)
from deeplearning4j_tpu.serving.replica_pool import (
    ReplicaEvictedError,
    ReplicaPool,
)

__all__ = [
    "BrokenModelInjector",
    "CircuitBreaker",
    "DeadlineExceededError",
    "DecodeEngine",
    "FlightRecorder",
    "InferenceFailedError",
    "InjectedServingFault",
    "MetricsRegistry",
    "ModelServer",
    "ModelValidationError",
    "OutOfPagesError",
    "PrefixCache",
    "SpeculativeDecoder",
    "ReloadCorruptionInjector",
    "ReplicaCrashInjector",
    "ReplicaEvictedError",
    "ReplicaHangInjector",
    "ReplicaPool",
    "ServerClosedError",
    "ServerOverloadedError",
    "ServiceUnavailableError",
    "ServingError",
    "SlowInferenceInjector",
    "Trace",
    "argmax_drift_rate",
    "attach_trace",
    "current_trace",
    "drift_report",
    "maybe_trace",
    "perplexity",
    "quantize_net_weights",
    "tracing_enabled",
    "use_trace",
]
