"""Robust serving tier: admission control, per-request deadlines with
adaptive micro-batching, circuit breaking, and safe hot model reload —
the inference-path counterpart of the training robustness tier
(elastic workers / durable checkpoints / health sentinel). See
`docs/serving.md` for the ladder semantics and tuning knobs.
"""
from deeplearning4j_tpu.serving.chaos import (
    BrokenModelInjector,
    InjectedServingFault,
    ReloadCorruptionInjector,
    SlowInferenceInjector,
)
from deeplearning4j_tpu.serving.model_server import (
    CircuitBreaker,
    DeadlineExceededError,
    InferenceFailedError,
    ModelServer,
    ModelValidationError,
    ServerClosedError,
    ServerOverloadedError,
    ServiceUnavailableError,
    ServingError,
)

__all__ = [
    "BrokenModelInjector",
    "CircuitBreaker",
    "DeadlineExceededError",
    "InferenceFailedError",
    "InjectedServingFault",
    "ModelServer",
    "ModelValidationError",
    "ReloadCorruptionInjector",
    "ServerClosedError",
    "ServerOverloadedError",
    "ServiceUnavailableError",
    "ServingError",
    "SlowInferenceInjector",
]
