"""Draft-verify speculative decoding for the paged decode engine.

The decode engine emits ONE token per verified target-model step — at
serving shapes that step is dispatch/cache-bandwidth bound, so the chip
spends most of each iteration waiting on a single token's worth of
work. Speculative decoding (Leviathan et al., 2023) converts that slack
into tokens: a cheap DRAFT model proposes `k` candidate tokens per slot
(one scanned dispatch), then the target model scores all proposals in
ONE batched verify step — a (k+1)-wide chunk per slot through the paged
KV cache, the exact shape `ops.attention.cached_attention_chunk`
already computes for chunked prefill. Accepted tokens advance the slot;
the first disagreement emits the target's own token instead.

Exactness is the load-bearing contract, inherited per-path:

- **greedy (temperature <= 0)**: a proposal is accepted only when it
  EQUALS the target's argmax at that position, and the stop position
  emits the target argmax itself — the emitted stream is the vanilla
  greedy rollout token for token, for ANY draft (a garbage draft only
  costs acceptance rate, never correctness). Argmax-exact parity with
  whole-batch `generate` is pinned in `tests/test_prefix_spec.py`.
- **sampled (temperature > 0)**: proposals drawn from the draft
  distribution q are accepted with probability min(1, p/q) against the
  target distribution p; the first rejection resamples from the
  residual norm(max(p - q, 0)), and a stop forced by anything OTHER
  than a rejection (all k accepted, or the slot nearing its token
  budget) draws from p directly. Each emitted token is distributed
  EXACTLY as a vanilla sample from p (Leviathan Thm. 1; the
  forced-stop draw is unbiased because it ignores the unconsumed
  accept coin) — pinned by a Monte-Carlo distribution test.

Rollback is free by construction: speculative KV writes land at
positions past each slot's committed length, where the engine's
position masking already hides them, and are overwritten in place when
decoding actually reaches those positions — the same trash-page
discipline that protects reallocated pages. Writes that would run past
a slot's reserved span (tail slots) are redirected to the trash page.

The draft model keeps its OWN paged KV pools indexed by the engine's
page table — same page ids, same refcounts — so prefix-cache hits skip
the draft's prefill too, and a page promotion shares both models'
KV in one move.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.serving import observability


def resolve_draft_net(draft, target_net):
    """Materialize the `speculative={"draft": ...}` config value:
    a fitted network instance is used as-is; the string "self" means
    self-speculation (draft = the target — every step still amortizes
    dispatches via the batched verify); a JSON config dict builds a
    fresh (randomly initialized) net, the wire-friendly form the
    gateway can ship."""
    if draft is None:
        raise ValueError(
            'speculative={...} needs a "draft": a gpt net instance, '
            '"self", or a gpt_configuration JSON dict')
    if isinstance(draft, str):
        if draft != "self":
            raise ValueError(f'unknown speculative draft {draft!r} — '
                             'pass a net, "self", or a config dict')
        return target_net
    if isinstance(draft, dict):
        import json

        from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        net = MultiLayerNetwork(
            MultiLayerConfiguration.from_json(json.dumps(draft)))
        net.init()
        return net
    return draft


class SpeculativeDecoder:
    """Compiled draft-propose + target-verify machinery for one
    `DecodeEngine` geometry. Built by the engine's `_build` (and
    rebuilt on weight swap); owns the draft model's paged KV pools and
    per-slot draft PRNG keys, reset alongside the engine's device state.
    """

    def __init__(self, *, target_plan, target_net, draft_net, k: int,
                 n_slots: int, page: int, L_logical: int,
                 pool_pages: int, top_k: int, donate: bool,
                 kv_quant: Optional[str] = None,
                 tp=None, tp_params=None):
        if k < 1:
            raise ValueError("speculative k must be >= 1")
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.transformer import (
            GPTPlan,
            _block_ffn,
            _block_heads,
            _block_out_proj,
            _prefill_block_attention,
            _top_k_filter,
        )
        from deeplearning4j_tpu.ops.attention import (
            paged_attention_chunk_auto,
            paged_attention_step_auto,
        )
        from deeplearning4j_tpu.serving.decode_engine import _write_pages
        from deeplearning4j_tpu.serving.quantize import (
            _write_scale_pages,
            quantize_heads,
        )

        # the engine's resolved KV quantization mode is inherited
        # verbatim: the verify step writes into the ENGINE's pools, and
        # the draft pools mirror the same halved-residency layout so
        # "same page ids" stays memory-true
        self._kv_quant = kv_quant
        self.k = int(k)
        self.n_slots = n_slots
        self.page = page
        self.pool_pages = pool_pages
        self.draft_net = draft_net
        self._donate = donate
        tplan = target_plan
        # NOTE: self-speculation still allocates its own draft pools
        # (reset_state) — ~2x KV HBM. Aliasing the engine's pools is
        # unsound under donation (propose would invalidate the target's
        # cache reference), so "self" is the acceptance-rate-ceiling /
        # dispatch-amortization config, not a memory-neutral one
        self.self_draft = draft_net is target_net
        # host-side mirror counters (surfaced via stats() → the engine's
        # metrics registry): how often the draft pools were (re)filled
        self.draft_prefills = 0
        self.draft_chunk_prefills = 0
        dplan = tplan if self.self_draft else GPTPlan(draft_net)
        self.draft_plan = dplan
        if dplan.emb.n_in != tplan.emb.n_in:
            raise ValueError(
                f"draft vocab {dplan.emb.n_in} != target vocab "
                f"{tplan.emb.n_in} — speculative verification compares "
                "token ids, so the vocabularies must match")
        if dplan.emb.positional and dplan.emb.max_length < L_logical \
                and dplan.emb.max_length < tplan.emb.max_length:
            raise ValueError(
                f"draft max_length {dplan.emb.max_length} is shorter than "
                f"the engine's logical cache ({L_logical}) — the draft "
                "could not embed positions the target serves")
        # tensor parallelism: the engine's TPPlan (target geometry) is
        # shared for the verify step; the draft gets its OWN plan unless
        # self-drafting (same net → reuse the engine's already-placed
        # sharded params instead of device_put-ing them twice). A draft
        # whose heads/FFN don't divide the degree fails HERE with the
        # same typed ValueError the engine raises for the target.
        self._tp = tp
        if tp is not None:
            if self.self_draft:
                dtp = tp
                self._dparams_sharded = tp_params
            else:
                from deeplearning4j_tpu.serving.tp_engine import TPPlan

                dtp = TPPlan(draft_net, dplan, tp.degree)
                self._dparams_sharded = dtp.shard_params(draft_net._params)
        else:
            dtp = None
            self._dparams_sharded = None
        self._dtp = dtp
        tp_axis = tp.axis if tp is not None else None
        tp_shard = tp.degree if tp is not None else None

        def _shard_d(fn, n_in, n_out):
            return fn if dtp is None else dtp.shard(
                fn, n_in=n_in, n_out=n_out, caches_out_at=0)

        S, kk = n_slots, self.k
        C = kk + 1

        def scale_and_filter(logits, temps):
            # temps broadcasts over every leading dim of `logits`
            safe_t = jnp.where(temps > 0, temps, 1.0).astype(logits.dtype)
            while safe_t.ndim < logits.ndim:
                safe_t = safe_t[..., None]
            return _top_k_filter(logits / safe_t, top_k)

        # -- draft prefill (one-shot + chunk): KV writes only, no head --
        def draft_prefill(dparams, dcaches, ids, wpids):
            bp = dplan.cast_blocks(dparams)
            P = ids.shape[1]
            x = bp[dplan.emb_i]["W"][ids]
            if dplan.emb.positional:
                x = x + bp[dplan.emb_i]["P"][
                    jnp.minimum(jnp.arange(P), dplan.emb.max_length - 1)]
            x = x.astype(dplan.cdt)
            new_caches = []
            for bi, i in enumerate(dplan.block_is):
                p = bp[i]
                layer = dplan.layers[i]
                q, kh, vh = _block_heads(layer, p, x, jnp.arange(P),
                                         shard=tp_shard)
                att = _prefill_block_attention(layer, q, kh, vh)
                att = _block_out_proj(p, att.reshape(1, P, -1), tp_axis)
                x = _block_ffn(layer, p, x + att, axis_name=tp_axis)
                kcol = jnp.transpose(kh, (0, 2, 3, 1))
                vrow = jnp.transpose(vh, (0, 2, 1, 3))
                z0 = jnp.zeros((), jnp.int32)
                if kv_quant:
                    kp_, vp_, ks_, vs_ = dcaches[bi]
                    kcol, kscol = quantize_heads(kcol, axis=2)
                    vrow, vscol = quantize_heads(vrow, axis=3)
                    ks_ = _write_scale_pages(ks_, kscol, wpids, z0, page)
                    vs_ = _write_scale_pages(vs_, vscol, wpids, z0, page)
                    kp_, vp_ = _write_pages(kp_, vp_, kcol, vrow, wpids,
                                            z0, page)
                    new_caches.append((kp_, vp_, ks_, vs_))
                else:
                    kp_, vp_ = dcaches[bi]
                    kp_, vp_ = _write_pages(kp_, vp_, kcol, vrow, wpids,
                                            z0, page)
                    new_caches.append((kp_, vp_))
            return new_caches

        def draft_prefill_chunk(dparams, dcaches, page_row, ids, off, woff,
                                wpids):
            bp = dplan.cast_blocks(dparams)
            Cw = ids.shape[1]
            qpos = off + jnp.arange(Cw)
            x = bp[dplan.emb_i]["W"][ids]
            if dplan.emb.positional:
                x = x + bp[dplan.emb_i]["P"][
                    jnp.minimum(qpos, dplan.emb.max_length - 1)]
            x = x.astype(dplan.cdt)
            new_caches = []
            for bi, i in enumerate(dplan.block_is):
                p = bp[i]
                layer = dplan.layers[i]
                q, kh, vh = _block_heads(layer, p, x, qpos, shard=tp_shard)
                kcol = jnp.transpose(kh, (0, 2, 3, 1))
                vrow = jnp.transpose(vh, (0, 2, 1, 3))
                if kv_quant:
                    kp_, vp_, ks_, vs_ = dcaches[bi]
                    kcol, kscol = quantize_heads(kcol, axis=2)
                    vrow, vscol = quantize_heads(vrow, axis=3)
                    ks_ = _write_scale_pages(ks_, kscol, wpids, woff, page)
                    vs_ = _write_scale_pages(vs_, vscol, wpids, woff, page)
                else:
                    kp_, vp_ = dcaches[bi]
                    ks_ = vs_ = None
                kp_, vp_ = _write_pages(kp_, vp_, kcol, vrow, wpids, woff,
                                        page)
                att = paged_attention_chunk_auto(q, kp_, vp_,
                                                 page_row[None], off[None],
                                                 k_scale=ks_, v_scale=vs_)
                att = _block_out_proj(p, att.reshape(1, Cw, -1), tp_axis)
                x = _block_ffn(layer, p, x + att, axis_name=tp_axis)
                new_caches.append((kp_, vp_, ks_, vs_) if kv_quant
                                  else (kp_, vp_))
            return new_caches

        # -- draft proposal: k+1 scanned draft steps ------------------------
        # k proposals plus one cache-completion step, so the draft's KV
        # covers every position the NEXT round may start from (an
        # all-accepted verify advances the slot past the k-th write)
        def draft_propose(dparams, dcaches, page_table, tok, pos, dkeys,
                          temps, active, wlimit):
            bp = dplan.cast_blocks(dparams)
            rows = jnp.arange(S)

            def body(carry, j):
                caches, cur, keys = carry
                p_j = pos + j
                x = bp[dplan.emb_i]["W"][cur]
                if dplan.emb.positional:
                    x = x + bp[dplan.emb_i]["P"][
                        jnp.minimum(p_j, dplan.emb.max_length - 1)]
                x = x.astype(dplan.cdt)
                wpos = jnp.minimum(p_j, L_logical - 1)
                # writes past a slot's reserved span go to the trash
                # page — speculative state never corrupts another
                # request's pages
                writable = active & ((j == 0) | (p_j <= wlimit))
                pids = jnp.where(writable, page_table[rows, wpos // page], 0)
                loff = wpos % page
                new_caches = []
                for bi, i in enumerate(dplan.block_is):
                    p = bp[i]
                    layer = dplan.layers[i]
                    q, kh, vh = _block_heads(layer, p, x[:, None, :],
                                             p_j[:, None], shard=tp_shard)
                    q, kh, vh = q[:, 0], kh[:, 0], vh[:, 0]
                    if kv_quant:
                        kp_, vp_, ks_, vs_ = caches[bi]
                        kq, ksc = quantize_heads(kh)
                        vq, vsc = quantize_heads(vh)
                        kp_ = kp_.at[pids, :, :, loff].set(kq)
                        vp_ = vp_.at[pids, :, loff, :].set(vq)
                        ks_ = ks_.at[pids, :, loff].set(ksc)
                        vs_ = vs_.at[pids, :, loff].set(vsc)
                    else:
                        kp_, vp_ = caches[bi]
                        ks_ = vs_ = None
                        kp_ = kp_.at[pids, :, :, loff].set(kh)
                        vp_ = vp_.at[pids, :, loff, :].set(vh)
                    att = paged_attention_step_auto(q, kp_, vp_,
                                                    page_table, p_j,
                                                    active,
                                                    k_scale=ks_,
                                                    v_scale=vs_)
                    att = _block_out_proj(p, att, tp_axis)
                    x = _block_ffn(layer, p, x + att, axis_name=tp_axis)
                    new_caches.append((kp_, vp_, ks_, vs_) if kv_quant
                                      else (kp_, vp_))
                logits = dplan.final_logits(bp, dparams, x)
                scaled = scale_and_filter(logits, temps)
                qdist = jax.nn.softmax(scaled.astype(jnp.float32), axis=-1)
                ks = jax.vmap(jax.random.split)(keys)
                keys2, subs = ks[:, 0], ks[:, 1]
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                drawn = jax.vmap(
                    lambda kx, lg: jax.random.categorical(kx, lg))(
                        subs, scaled).astype(jnp.int32)
                nxt = jnp.where(temps > 0, drawn, greedy)
                nxt = jnp.where(active, nxt, cur)
                return (new_caches, nxt, keys2), (nxt, qdist)

            (caches, _, keys), (toks, qdists) = jax.lax.scan(
                body, (dcaches, tok, dkeys), jnp.arange(C))
            props = jnp.swapaxes(toks[:kk], 0, 1)          # (S, k)
            qd = jnp.moveaxis(qdists[:kk], 0, 1)           # (S, k, V)
            return caches, keys, props, qd

        # -- target verify: one (k+1)-wide chunk per slot -------------------
        def verify(params, caches, page_table, tok, pos, keys, temps,
                   active, wlimit, props, qdists):
            bp = tplan.cast_blocks(params)
            rows = jnp.arange(S)
            block = jnp.concatenate([tok[:, None], props], axis=1)  # (S,C)
            qpos = pos[:, None] + jnp.arange(C)[None, :]            # (S,C)
            x = bp[tplan.emb_i]["W"][block]
            if tplan.emb.positional:
                x = x + bp[tplan.emb_i]["P"][
                    jnp.minimum(qpos, tplan.emb.max_length - 1)]
            x = x.astype(tplan.cdt)
            new_caches = []
            for bi, i in enumerate(tplan.block_is):
                p = bp[i]
                layer = tplan.layers[i]
                q, kh, vh = _block_heads(layer, p, x, qpos, shard=tp_shard)
                if kv_quant:
                    kp_, vp_, ks_, vs_ = caches[bi]
                else:
                    kp_, vp_ = caches[bi]
                    ks_ = vs_ = None
                for j in range(C):
                    p_j = pos + j
                    wpos = jnp.minimum(p_j, L_logical - 1)
                    writable = active & ((j == 0) | (p_j <= wlimit))
                    pids = jnp.where(writable,
                                     page_table[rows, wpos // page], 0)
                    loff = wpos % page
                    if kv_quant:
                        kq, ksc = quantize_heads(kh[:, j])
                        vq, vsc = quantize_heads(vh[:, j])
                        kp_ = kp_.at[pids, :, :, loff].set(kq)
                        vp_ = vp_.at[pids, :, loff, :].set(vq)
                        ks_ = ks_.at[pids, :, loff].set(ksc)
                        vs_ = vs_.at[pids, :, loff].set(vsc)
                    else:
                        kp_ = kp_.at[pids, :, :, loff].set(kh[:, j])
                        vp_ = vp_.at[pids, :, loff, :].set(vh[:, j])
                # one (k+1)-wide paged chunk per slot: the kernel walks
                # the page table in place; the fallback is exactly
                # `_verify_block_attention` (gather + vmapped chunk)
                att = paged_attention_chunk_auto(q, kp_, vp_, page_table,
                                                 pos, active,
                                                 k_scale=ks_, v_scale=vs_)
                att = _block_out_proj(p, att, tp_axis)
                x = _block_ffn(layer, p, x + att, axis_name=tp_axis)
                new_caches.append((kp_, vp_, ks_, vs_) if kv_quant
                                  else (kp_, vp_))
            logits = tplan.final_logits(bp, params, x)       # (S, C, V)

            # --- acceptance (Leviathan rejection sampling; greedy =
            # argmax equality). Query j consumed [tok, props][j] and its
            # distribution governs the token at offset j+1.
            e = jnp.argmax(logits, axis=-1).astype(jnp.int32)     # (S, C)
            scaled = scale_and_filter(logits, temps)
            pdist = jax.nn.softmax(scaled.astype(jnp.float32), axis=-1)
            qn = jnp.where(jnp.isfinite(qdists), qdists, 0.0)
            ks = jax.vmap(lambda kx: jax.random.split(kx, 3))(keys)
            new_keys, ku, kr = ks[:, 0], ks[:, 1], ks[:, 2]
            us = jax.vmap(lambda kx: jax.random.uniform(kx, (kk,)))(ku)
            p_at = jnp.take_along_axis(pdist[:, :kk], props[..., None],
                                       axis=-1)[..., 0]            # (S, k)
            q_at = jnp.take_along_axis(qn, props[..., None],
                                       axis=-1)[..., 0]            # (S, k)
            accept = us < jnp.minimum(1.0, p_at / jnp.maximum(q_at, 1e-30))
            match = e[:, :kk] == props
            acc = jnp.where(temps[:, None] > 0, accept, match)
            lead = jnp.cumprod(acc.astype(jnp.int32), axis=1)
            m_rej = jnp.sum(lead, axis=1)                   # 0..k
            # the slot's remaining write budget caps how deep this round
            # may commit; m_cap == 0 degrades the slot to a vanilla step
            m_cap = jnp.clip(wlimit - pos, 0, kk)
            m = jnp.minimum(m_rej, m_cap)
            # stop forced by the cap or by running out of proposals
            # (m_rej >= m_cap): the unconsumed accept coin is IGNORED
            # and the stop token samples from the full target
            # distribution — conditioning on it would bias the draw.
            # A genuine rejection (m_rej < m_cap) resamples the residual
            forced = m_rej >= m_cap
            resid = jnp.maximum(pdist[:, :kk] - qn, 0.0)
            rsum = jnp.sum(resid, axis=-1, keepdims=True)
            resid = jnp.where(rsum > 0, resid, pdist[:, :kk])
            rlog = jnp.where(resid > 0,
                             jnp.log(jnp.maximum(resid, 1e-38)), -1e30)
            res_draws = jax.vmap(
                lambda kx, lg: jax.random.categorical(kx, lg, axis=-1))(
                    kr, rlog).astype(jnp.int32)             # (S, k)
            # graftlint: disable=rng-reuse  deliberate: res_draws and
            # full_draws are mutually exclusive per row (jnp.where picks
            # one), so reusing kr keeps the accepted draw identical to the
            # single-sample rejection-sampling recurrence
            full_draws = jax.vmap(
                lambda kx, lg: jax.random.categorical(kx, lg, axis=-1))(
                    kr, scaled.astype(jnp.float32)).astype(jnp.int32)
            m1 = m[:, None]
            res_at_m = jnp.take_along_axis(
                res_draws, jnp.minimum(m1, kk - 1), axis=1)[:, 0]
            full_at_m = jnp.take_along_axis(full_draws, m1, axis=1)[:, 0]
            e_at_m = jnp.take_along_axis(e, m1, axis=1)[:, 0]
            fin_sampled = jnp.where(forced, full_at_m, res_at_m)
            fin = jnp.where(temps > 0, fin_sampled, e_at_m).astype(jnp.int32)
            idx = jnp.arange(C)[None, :]
            acc_tok = jnp.where(temps[:, None] > 0,
                                jnp.concatenate([props, props[:, -1:]],
                                                axis=1), e)
            out = jnp.where(idx < m1, acc_tok, 0)
            out = jnp.where(idx == m1, fin[:, None], out).astype(jnp.int32)
            n_emit = jnp.where(active, m + 1, 0)
            new_tok = jnp.where(active,
                                jnp.take_along_axis(out, m1, axis=1)[:, 0],
                                tok)
            new_pos = jnp.where(active, pos + m + 1, pos)
            new_keys = jnp.where(active[:, None], new_keys, keys)
            row_ok = jnp.all(
                jnp.isfinite(logits.astype(jnp.float32)), axis=(1, 2))
            oks = jnp.where(active, row_ok, True)
            return new_caches, new_tok, new_pos, new_keys, out, n_emit, oks

        # jit OUTSIDE shard_map (identity when tp is off) so pool
        # donation aliases the sharded buffers; draft closures shard
        # with the DRAFT plan's specs, verify with the target's
        draft_prefill = jax.jit(_shard_d(draft_prefill, 4, 1),
                                donate_argnums=(1,) if donate else ())
        draft_prefill_chunk = jax.jit(_shard_d(draft_prefill_chunk, 7, 1),
                                      donate_argnums=(1,) if donate else ())
        draft_propose = jax.jit(_shard_d(draft_propose, 9, 4),
                                donate_argnums=(1,) if donate else ())
        verify = jax.jit(
            verify if tp is None else tp.shard(verify, n_in=11, n_out=7),
            donate_argnums=(1,) if donate else ())
        self._draft_prefill = draft_prefill
        self._draft_prefill_chunk = draft_prefill_chunk
        self._propose = draft_propose
        self._verify = verify
        self.reset_state()

    # -- device state ------------------------------------------------------
    def reset_state(self) -> None:
        """Fresh draft pools + per-slot draft keys (construction, weight
        swap, post-failure recovery — always alongside the engine's own
        `_reset_device_state`, so draft and target pages can never skew)."""
        import jax
        import jax.numpy as jnp

        dplan, S = self.draft_plan, self.n_slots
        page, P = self.page, self.pool_pages
        caches = []
        for i in dplan.block_is:
            layer = dplan.layers[i]
            hd = layer.n_out // layer.n_heads
            Hkv = layer._kv_heads
            if self._kv_quant:
                # int8 draft pools + f32 scale sidecars, mirroring the
                # engine's layout (see DecodeEngine._reset_device_state)
                caches.append(
                    (jnp.zeros((P + 1, Hkv, hd, page), jnp.int8),
                     jnp.zeros((P + 1, Hkv, page, hd), jnp.int8),
                     jnp.ones((P + 1, Hkv, page), jnp.float32),
                     jnp.ones((P + 1, Hkv, page), jnp.float32)))
            else:
                caches.append(
                    (jnp.zeros((P + 1, Hkv, hd, page), dplan.cdt),
                     jnp.zeros((P + 1, Hkv, page, hd), dplan.cdt)))
        if self._dtp is not None:
            # head axis over tp, mirroring the engine's pools — the
            # shared page table addresses the same per-device head slice
            # in both models' pools
            caches = [tuple(self._dtp.shard_pool(x) for x in c)
                      for c in caches]
        self._caches = caches
        self._keys = jnp.stack(
            [jax.random.PRNGKey(1000 + i) for i in range(S)])

    def _draft_params(self):
        """The params list the compiled draft closures consume: the
        permuted+placed shards under TP, the net's own list otherwise."""
        if self._dparams_sharded is not None:
            return self._dparams_sharded
        return self.draft_net._params

    def seed_slot(self, slot: int, seed: int) -> None:
        """Per-request draft PRNG stream (deterministic per seed, on a
        different fold than the target's kp/kd split)."""
        import jax

        self._keys = self._keys.at[slot].set(
            jax.random.fold_in(jax.random.PRNGKey(seed), 7))

    # -- host drivers (called by the engine scheduler) ---------------------
    def prefill_one_shot(self, ids, wpids) -> None:
        """Mirror one target one-shot prefill into the draft pools (same
        pages, same padded ids). Materializes a probe scalar so a failed
        draft dispatch surfaces HERE, attributable, not inside a later
        verify."""
        import jax
        import jax.numpy as jnp

        with observability.annotation("draft-prefill"):
            self._caches = self._draft_prefill(
                self._draft_params(), self._caches, jnp.asarray(ids),
                wpids)
            jax.device_get(self._caches[0][0][0, 0, 0, 0])
        self.draft_prefills += 1

    def prefill_chunk(self, page_row, ids, off, woff, pids) -> None:
        """Mirror one target prefill chunk into the draft pools."""
        import jax
        import jax.numpy as jnp

        with observability.annotation("draft-prefill-chunk"):
            self._caches = self._draft_prefill_chunk(
                self._draft_params(), self._caches, page_row,
                jnp.asarray(ids), jnp.asarray(off, jnp.int32),
                jnp.asarray(woff, jnp.int32),
                jnp.asarray(np.asarray(pids, np.int32)))
            jax.device_get(self._caches[0][0][0, 0, 0, 0])
        self.draft_chunk_prefills += 1

    def stats(self) -> dict:
        return {"k": self.k, "draft_is_target": self.self_draft,
                "draft_prefills": self.draft_prefills,
                "draft_chunk_prefills": self.draft_chunk_prefills}
