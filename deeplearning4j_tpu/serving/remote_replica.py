"""Cross-process replica pool: remote replicas over the gateway protocol.

PR 7's `ReplicaPool` made N replicas one service — but all N share the
pool's address space, so a hard crash (`kill -9`), a wedged
interpreter, or a host partition is still one failure domain. The
reference stack's scaleout tier serves model replicas across JVM
processes and hosts; this module is that promotion for our pool:

- **`RemoteReplica`** — an adapter presenting the replica seam the
  pool already routes through (`predict`/`generate`/`probe`/`pending`/
  `stats`/`flight_record`/`restore_model`/`reload`/`breaker.state`/
  `metrics.exposition`) over the gateway wire protocol to a
  `ModelServer` living in ANOTHER process or host. Every network edge
  carries fault discipline: read deadlines derived from the request
  deadline (+`deadline_margin`), bounded exponential-backoff retries
  for idempotent calls only (`GatewayClient`), keep-alive connection
  pooling with stale-connection replacement, and partial-read /
  oversize / garbage-response handling (`GatewayProtocolError`) mapped
  onto the existing typed `ServingError` taxonomy — so eviction,
  three-valued probe verdicts, failover, hedging, degraded mode, and
  the shared admission budget all work UNCHANGED on remote replicas.
- **`ReplicaEntryPoint`** — the replica-process side: the gateway
  `EntryPoint` plus the pool-management RPCs the seam needs
  (`snapshot_model`/`restore_snapshot` for rolling-reload rollback
  across the process boundary, `replica_metrics`, `health`). Runnable
  as ``python -m deeplearning4j_tpu.serving.remote_replica``.
- **`ReplicaSupervisor`** — spawns, watches, and respawns replica
  processes with bounded restart backoff (doubling per quick death up
  to `max_backoff`, give-up past `max_restarts` deaths inside
  `restart_window`). A `kill -9` costs the pool a failover plus one
  supervised respawn — never the service.
- **`RemoteReplicaPool` / `spawn_replica_pool`** — the pool subclass
  binding the two, keeping `rolling_reload`'s pool-wide-rollback
  guarantee when a replica dies mid-deploy (weights roll back via
  per-replica snapshots; a peer that dies mid-rollback is evicted +
  marked stale instead of stranding the others on the new version).

Traces cross the wire: the pool's trace context (trace_id + a
monotonic/wall-clock anchor pair) travels on each request, the remote
gateway JOINS that trace_id, and the returned remote timeline is
grafted into the local one via the wall-clock anchors
(`observability.graft_remote_trace`) — one causally-ordered timeline
per request in the flight recorder, process boundary and all.

Single-host-multi-process vs multi-host: the supervisor spawns local
processes, and `snapshot_model`/`restore_snapshot`/`reload` exchange
CHECKPOINT PATHS — both ends must see the same filesystem. Multi-host
deployments point `RemoteReplica` at remote gateways directly (no
supervisor) over a shared filesystem for the deploy paths.

`tests/test_remote_replica.py` drives the wire ladders in-process;
`tests/test_remote_replica_mp.py` runs the separate-process chaos
drills (kill -9 / partition / crash-mid-deploy under live traffic).
"""
from __future__ import annotations

import contextlib
import itertools
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import weakref
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.gateway import (
    EntryPoint,
    GatewayClient,
    GatewayError,
    GatewayProtocolError,
    GatewayServer,
)
from deeplearning4j_tpu.serving import observability
from deeplearning4j_tpu.serving.kv_transfer import (
    KVTransferError,
    SlotMigratedError,
)
from deeplearning4j_tpu.serving.model_server import (
    DeadlineExceededError,
    InferenceFailedError,
    ModelValidationError,
    OutOfPagesError,
    ServerClosedError,
    ServerOverloadedError,
    ServiceUnavailableError,
    ServingError,
    TenantQuotaExceededError,
)
from deeplearning4j_tpu.serving.replica_pool import (
    ReplicaEvictedError,
    ReplicaPool,
)
from deeplearning4j_tpu.util.serialization import (
    restore_model as _read_model_file,
    write_model as _write_model_file,
)

logger = logging.getLogger("deeplearning4j_tpu")

_REPO_ROOT = Path(__file__).resolve().parents[2]

# every replica pid this process ever spawned and has not yet reaped —
# the test suite's autouse reaper kills leftovers so a failing chaos
# drill cannot leak interpreter processes past its test
_ORPHAN_PIDS: set = set()
# live supervisors, weakly held: their pids are NOT orphans while the
# supervisor is open (a shared long-lived pool must survive the reaper
# running between tests)
_LIVE_SUPERVISORS: "weakref.WeakSet" = weakref.WeakSet()


def reap_orphans() -> int:
    """SIGKILL every replica process this process spawned whose
    supervisor is closed or gone (crash-test hygiene; normal shutdown
    goes through `ReplicaSupervisor.stop`). Returns how many were
    signalled."""
    protected = set()
    for sup in list(_LIVE_SUPERVISORS or ()):
        if not sup._closed:
            protected.update(p.pid for p in sup._procs if p is not None)
    n = 0
    for pid in list(_ORPHAN_PIDS):
        if pid in protected:
            continue
        with contextlib.suppress(OSError):
            os.kill(pid, signal.SIGKILL)
            n += 1
        _ORPHAN_PIDS.discard(pid)
    return n


class ReplicaSpawnError(ServingError):
    """A replica process failed to come up (died during startup or
    never wrote its ready file within `spawn_timeout`)."""


# wire error_type -> local typed error. The remote's `ServerClosedError`
# deliberately maps to `ServiceUnavailableError`: the REMOTE server
# shutting down means THIS pool's replica went away (fail over), not
# that this pool is closed (terminal).
_WIRE_ERRORS: Dict[str, type] = {
    "ServerOverloadedError": ServerOverloadedError,
    "OutOfPagesError": OutOfPagesError,
    "ServiceUnavailableError": ServiceUnavailableError,
    "DeadlineExceededError": DeadlineExceededError,
    "InferenceFailedError": InferenceFailedError,
    "ModelValidationError": ModelValidationError,
    "ReplicaEvictedError": ReplicaEvictedError,
    "TenantQuotaExceededError": TenantQuotaExceededError,
    "ServerClosedError": ServiceUnavailableError,
    "KVTransferError": KVTransferError,
    "SlotMigratedError": SlotMigratedError,
}

# the transport failures a remote call can surface (socket.timeout IS
# TimeoutError on this Python; ConnectionError subclasses OSError)
_TRANSPORT_ERRORS = (GatewayError, GatewayProtocolError, TimeoutError,
                     ConnectionError, OSError)


class _RemoteSnapshot:
    """Pool-side handle to a replica-written weight snapshot: the
    `rolling_reload` rollback currency. Holding a PATH instead of a
    live net keeps pre-deploy snapshots out of this process's memory —
    restore ships the path back over the wire and the replica reloads
    it locally."""

    __slots__ = ("path", "version")

    def __init__(self, path: str, version: int):
        self.path = str(path)
        self.version = int(version)

    def __repr__(self):
        return f"_RemoteSnapshot({self.path!r}, v{self.version})"


class _RemoteBreakerView:
    """The pool's probe loop reads `rep.server.breaker.state`; for a
    remote replica that is the LAST OBSERVED state (refreshed by
    `stats()` and batchless probes). A remotely-open breaker the cache
    has not seen yet still evicts promptly — its typed sheds fail the
    next probe."""

    __slots__ = ("_replica",)

    def __init__(self, replica: "RemoteReplica"):
        self._replica = replica

    @property
    def state(self) -> str:
        return self._replica._breaker_state


class _RemoteMetricsView:
    """`rep.server.metrics.exposition(labels=...)` seam: fetches the
    remote server's full Prometheus text page over the wire."""

    __slots__ = ("_replica",)

    def __init__(self, replica: "RemoteReplica"):
        self._replica = replica

    def exposition(self, namespace: str = "dl4j", labels=None) -> str:
        rep = self._replica
        try:
            return rep._client.call("replica_metrics", name=rep.MODEL,
                                    labels=labels,
                                    _timeout=rep.rpc_timeout)
        except _TRANSPORT_ERRORS as e:
            logger.warning("remote replica %s: metrics unreachable (%s)",
                           rep.endpoint, type(e).__name__)
            return (f"# remote replica {rep.endpoint} unreachable: "
                    f"{type(e).__name__}\n")


class RemoteReplica:
    """One pool replica living in another process/host, reached over
    the gateway wire protocol (see module docstring). Presents exactly
    the seam `ReplicaPool` routes through, with every wire failure
    mapped into the typed `ServingError` taxonomy:

    - server-side typed errors travel as `error_type` and are
      reconstructed locally (`retry_after` hints survive — satellite
      of the failover contract);
    - transport failures (refused/reset/EOF) become
      `ServiceUnavailableError` — retryable, so the pool fails over;
    - protocol garbage (unparseable/truncated/oversize responses)
      becomes `InferenceFailedError` — retryable sickness that feeds
      passive eviction;
    - a fired read deadline becomes `DeadlineExceededError` when the
      caller bounded the request (terminal — the time is gone), else
      `ServiceUnavailableError`.

    Read deadlines derive from the request deadline: a call with
    `timeout=T` reads with `T + deadline_margin` so the remote's own
    typed deadline verdict wins the race against the socket timer
    whenever the peer is alive to deliver it."""

    MODEL = "replica"

    def __init__(self, host: str, port: int, *,
                 rpc_timeout: float = 30.0,
                 admin_timeout: float = 120.0,
                 deadline_margin: float = 2.0,
                 max_queue: int = 64,
                 retry_backoff: float = 0.05,
                 max_retries: int = 1,
                 pool_size: int = 2,
                 max_idle: float = 30.0,
                 scratch_dir=None):
        self.endpoint = f"{host}:{port}"
        self.rpc_timeout = rpc_timeout
        self.admin_timeout = admin_timeout
        self.deadline_margin = deadline_margin
        # the pool sums replica `max_queue`s into its admission budget;
        # mirror the remote server's configured queue depth here
        self.max_queue = max_queue
        self._scratch = Path(scratch_dir) if scratch_dir is not None \
            else Path(tempfile.gettempdir())
        # eager_connect=False: a replica process still booting must not
        # fail pool construction — the probe ladder owns reachability
        self._client = GatewayClient(host=host, port=port,
                                     timeout=rpc_timeout,
                                     retry_backoff=retry_backoff,
                                     max_retries=max_retries,
                                     pool_size=pool_size,
                                     max_idle=max_idle,
                                     eager_connect=False)
        self._lock = threading.Lock()
        self._pending = 0  # guarded by: _lock
        self._breaker_state = "closed"  # last observed; guarded by: _lock
        self._restore_counter = itertools.count()
        self.breaker = _RemoteBreakerView(self)
        self.metrics = _RemoteMetricsView(self)

    # -- error mapping -----------------------------------------------------
    def _wire_error(self, e: BaseException, *, deadline_bound: bool,
                    what: str) -> BaseException:
        """Map one wire failure into the typed taxonomy; returns `e`
        itself for error types with no local mapping (re-raised
        unchanged by the caller)."""
        if isinstance(e, GatewayError):
            cls = _WIRE_ERRORS.get(e.error_type or "")
            if cls is None:
                return e
            if cls is SlotMigratedError:
                # a redirect, not a failure: rebuild its routing fields
                # from the structured error payload so the pool can
                # fetch + resume the handoff on a peer
                data = getattr(e, "payload", None) or {}
                return SlotMigratedError(
                    f"remote replica {self.endpoint}: {e}",
                    handoff_id=str(data.get("handoff_id", "")),
                    tokens=[int(t) for t in data.get("tokens", [])],
                    source=data.get("source") or self.endpoint)
            err = cls(f"remote replica {self.endpoint}: {e}")
            retry_after = getattr(e, "retry_after", None)
            if retry_after is not None:
                err.retry_after = float(retry_after)
            return err
        if isinstance(e, GatewayProtocolError):
            return InferenceFailedError(
                f"remote replica {self.endpoint} answered {what} with "
                f"undecodable bytes: {e}")
        if isinstance(e, TimeoutError):
            if deadline_bound:
                return DeadlineExceededError(
                    f"remote replica {self.endpoint} exceeded the "
                    f"{what} deadline (read timed out)")
            return ServiceUnavailableError(
                f"remote replica {self.endpoint} timed out on {what} "
                "with no caller deadline", retry_after=0.05)
        if isinstance(e, OSError):  # incl. ConnectionError subclasses
            return ServiceUnavailableError(
                f"remote replica {self.endpoint} unreachable during "
                f"{what}: {type(e).__name__}: {e}", retry_after=0.05)
        return e

    def _raise_mapped(self, e: BaseException, *, deadline_bound: bool,
                      what: str):
        mapped = self._wire_error(e, deadline_bound=deadline_bound,
                                  what=what)
        if mapped is e:
            raise e
        raise mapped from e

    # -- data path ---------------------------------------------------------
    @contextlib.contextmanager
    def _count_pending(self):
        with self._lock:
            self._pending += 1
        try:
            yield
        finally:
            with self._lock:
                self._pending -= 1

    def pending(self) -> int:
        """In-flight wire calls from THIS pool — the least-loaded
        routing signal. Local by design: asking the remote for its
        queue depth would cost a round-trip per routing decision."""
        with self._lock:
            return self._pending

    def _wire_deadline(self, timeout: Optional[float]) -> float:
        if timeout is None:
            return self.rpc_timeout
        return float(timeout) + self.deadline_margin

    def _graft(self, trace, remote: Optional[dict]) -> None:
        if trace and remote:
            observability.graft_remote_trace(trace, remote,
                                             endpoint=self.endpoint)

    def _data_call(self, what: str, timeout: Optional[float],
                   **params):
        """One traced data-path RPC: trace context on the request,
        remote timeline grafted on the way out (success AND failure),
        wire failures mapped typed."""
        trace = observability.current_trace()
        ctx = observability.wire_trace_context(trace)
        with self._count_pending():
            try:
                out = self._client.call(
                    what, name=self.MODEL, timeout=timeout,
                    _timeout=self._wire_deadline(timeout), _trace=ctx,
                    **params)
            except _TRANSPORT_ERRORS as e:
                # a typed remote failure carries its timeline — graft
                # it so the pinned local trace names the remote spans
                self._graft(trace, getattr(e, "trace", None))
                self._raise_mapped(e, deadline_bound=timeout is not None,
                                   what=what)
            self._graft(trace, self._client.last_trace)
            return out

    def predict(self, x, timeout: Optional[float] = None) -> np.ndarray:
        return np.asarray(self._data_call(
            "predict", timeout, features=np.asarray(x, np.float32)))

    def generate(self, prompt_ids, n_tokens: int, *,
                 temperature: float = 0.0, seed: int = 0,
                 timeout: Optional[float] = None,
                 tenant: Optional[str] = None,
                 priority: str = "interactive",
                 logprobs: int = 0):
        # logprobs rides the wire as a plain kwarg (omitted when 0 so
        # older gateways keep accepting the call); the dict reply passes
        # through un-coerced
        kw = {"logprobs": int(logprobs)} if logprobs else {}
        out = self._data_call(
            "generate", timeout, prompt_ids=np.asarray(prompt_ids),
            n_tokens=int(n_tokens), temperature=float(temperature),
            seed=int(seed), tenant=tenant, priority=priority, **kw)
        return out if isinstance(out, dict) else np.asarray(out)

    def set_tenant_quota(self, tenant: str, rate=None, burst=None,
                         max_pages=None, weight=None) -> None:
        """Push one tenant's token-rate quota, page ceiling, and
        batch-lane fair-queueing weight to the remote engine (the wire
        mirror of `ModelServer.set_tenant_quota`)."""
        self._client.call("set_tenant_quota", name=self.MODEL,
                          tenant=tenant, rate=rate, burst=burst,
                          max_pages=max_pages, weight=weight,
                          _timeout=self.rpc_timeout)

    # -- KV handoff / live migration ---------------------------------------
    def migrate_slots(self, wait: Optional[float] = 5.0) -> int:
        """Ask the remote engine to export its in-flight generations as
        leased handoffs (migrate-then-drain). Idempotent: re-running on
        an already-drained engine migrates zero slots."""
        try:
            return int(self._client.call(
                "migrate_slots", name=self.MODEL, wait=wait,
                _timeout=self._wire_deadline(wait)))
        except _TRANSPORT_ERRORS as e:
            self._raise_mapped(e, deadline_bound=wait is not None,
                               what="migrate_slots")

    def resume_generate(self, payload: dict,
                        timeout: Optional[float] = None):
        """Admit a fetched handoff payload on the remote engine; returns
        the TAIL tokens it generates (a `{"tokens", "logprobs"}` dict
        when the handoff carries logprobs). NOT retried on ambiguous
        wire failures — a re-send could double-admit the same handoff
        (the caller's fallback is re-prefill, which is always safe)."""
        out = self._data_call(
            "resume_generate", timeout, payload=payload,
            _idempotent=False)
        return out if isinstance(out, dict) else np.asarray(out)

    def fetch_handoff(self, handoff_id: str,
                      timeout: Optional[float] = None) -> dict:
        """Fetch a leased handoff payload from the remote sender
        (extends the lease TTL). Read-only, so retryable."""
        try:
            return self._client.call(
                "fetch_handoff", name=self.MODEL, handoff_id=handoff_id,
                _timeout=self._wire_deadline(timeout))
        except _TRANSPORT_ERRORS as e:
            self._raise_mapped(e, deadline_bound=timeout is not None,
                               what="fetch_handoff")

    def commit_handoff(self, handoff_id: str) -> bool:
        """Resolve a handoff lease after a successful resume (sender
        frees the shipped pages). Resolve-by-id, so retryable."""
        try:
            return bool(self._client.call(
                "commit_handoff", name=self.MODEL, handoff_id=handoff_id,
                _timeout=self.rpc_timeout))
        except _TRANSPORT_ERRORS as e:
            self._raise_mapped(e, deadline_bound=False,
                               what="commit_handoff")

    def abort_handoff(self, handoff_id: str) -> bool:
        """Resolve a handoff lease after a FAILED resume (sender
        reclaims the shipped pages now, not at TTL expiry)."""
        try:
            return bool(self._client.call(
                "abort_handoff", name=self.MODEL, handoff_id=handoff_id,
                _timeout=self.rpc_timeout))
        except _TRANSPORT_ERRORS as e:
            self._raise_mapped(e, deadline_bound=False,
                               what="abort_handoff")

    # -- cluster prefix cache ----------------------------------------------
    # The wire mirror of the ModelServer prefix surface, so a remote
    # replica can serve as a fetch HOLDER (export + frames) and a delta
    # RECEIVER probe (prefix_depth), and publish into a pool's directory
    # via `ReplicaPool.refresh_prefix_directory` (prefix_chains pull).

    def export_prefix(self, prompt_ids, have_pages: int = 0,
                      tenant: Optional[str] = None,
                      frame_pages: Optional[int] = None,
                      timeout: Optional[float] = None) -> dict:
        """Lease the remote's resident prefix-chain pages for
        `prompt_ids` beyond `have_pages`; returns the framed-transfer
        header. Retryable: a duplicate grant's lease TTL unpins it."""
        try:
            return self._client.call(
                "export_prefix", name=self.MODEL,
                prompt_ids=[int(x) for x in np.asarray(prompt_ids)],
                have_pages=int(have_pages), tenant=tenant,
                frame_pages=frame_pages, timeout=timeout,
                _timeout=self._wire_deadline(timeout))
        except _TRANSPORT_ERRORS as e:
            self._raise_mapped(e, deadline_bound=timeout is not None,
                               what="export_prefix")

    def fetch_handoff_header(self, handoff_id: str, skip_pages: int = 0,
                             frame_pages: Optional[int] = None) -> dict:
        """Blockless delta header of a leased handoff (read-only)."""
        try:
            return self._client.call(
                "fetch_handoff_header", name=self.MODEL,
                handoff_id=handoff_id, skip_pages=int(skip_pages),
                frame_pages=frame_pages, _timeout=self.rpc_timeout)
        except _TRANSPORT_ERRORS as e:
            self._raise_mapped(e, deadline_bound=False,
                               what="fetch_handoff_header")

    def fetch_handoff_frame(self, handoff_id: str, frame: int,
                            skip_pages: int = 0,
                            frame_pages: Optional[int] = None) -> dict:
        """One bounded frame of a leased handoff (read-only)."""
        try:
            return self._client.call(
                "fetch_handoff_frame", name=self.MODEL,
                handoff_id=handoff_id, frame=int(frame),
                skip_pages=int(skip_pages), frame_pages=frame_pages,
                _timeout=self.rpc_timeout)
        except _TRANSPORT_ERRORS as e:
            self._raise_mapped(e, deadline_bound=False,
                               what="fetch_handoff_frame")

    def prefix_depth(self, prompt_ids,
                     tenant: Optional[str] = None) -> int:
        """Resident prefix-chain depth (pages) on the remote engine."""
        try:
            return int(self._client.call(
                "prefix_depth", name=self.MODEL,
                prompt_ids=[int(x) for x in np.asarray(prompt_ids)],
                tenant=tenant, _timeout=self.rpc_timeout))
        except _TRANSPORT_ERRORS as e:
            self._raise_mapped(e, deadline_bound=False,
                               what="prefix_depth")

    def prefix_chains(self) -> dict:
        """Resident chain-key snapshot — the pull-mode directory feed."""
        try:
            return self._client.call(
                "prefix_chains", name=self.MODEL,
                _timeout=self.rpc_timeout)
        except _TRANSPORT_ERRORS as e:
            self._raise_mapped(e, deadline_bound=False,
                               what="prefix_chains")

    # -- health ------------------------------------------------------------
    def probe(self, x=None, timeout: Optional[float] = None
              ) -> Optional[bool]:
        """Three-valued, mirroring `ModelServer.probe`: True healthy,
        False sick (unreachable, garbage, typed sickness, breaker
        open), None inconclusive (shed on load/time — busyness proves
        nothing). Probes never retry (`_idempotent=False`): a verdict
        must reflect ONE observation, not the best of two."""
        wire_timeout = self._wire_deadline(timeout) \
            if timeout is not None else self.rpc_timeout
        if x is None:
            # no batch to serve: reachability + the remote breaker
            try:
                st = self._client.call("server_stats", name=self.MODEL,
                                       _timeout=wire_timeout,
                                       _idempotent=False)
            except _TRANSPORT_ERRORS:
                return False
            state = st.get("breaker_state", "closed")
            with self._lock:
                self._breaker_state = state
            return False if state == "open" else None
        try:
            self._client.call("predict", name=self.MODEL,
                              features=np.asarray(x, np.float32),
                              timeout=timeout, _timeout=wire_timeout,
                              _idempotent=False)
        except GatewayError as e:
            mapped = self._wire_error(e, deadline_bound=True,
                                      what="probe")
            if isinstance(mapped, (ServerOverloadedError,
                                   DeadlineExceededError)):
                return None  # load/time signal, not sickness
            return False
        except (GatewayProtocolError, TimeoutError, OSError):
            # garbage, a wedged read, or an unreachable peer: all
            # sickness — the pool's watchdog semantics for "hung"
            return False
        return True

    def stats(self) -> dict:
        """The remote server's `stats()` dict; when the replica is
        unreachable, a zeroed schema-complete dict with
        ``unreachable: True`` and the last observed breaker state —
        `pool_stats` aggregation must survive a dead replica."""
        try:
            st = self._client.call("server_stats", name=self.MODEL,
                                   _timeout=self.rpc_timeout)
        except _TRANSPORT_ERRORS as e:
            logger.warning("remote replica %s: stats unreachable (%s)",
                           self.endpoint, type(e).__name__)
            st = {k: 0 for k in observability.MODEL_SERVER_STATS_KEYS}
            with self._lock:
                st["breaker_state"] = self._breaker_state
            st["endpoint"] = self.endpoint
            st["unreachable"] = True
            return st
        with self._lock:
            self._breaker_state = st.get("breaker_state",
                                         self._breaker_state)
        st["endpoint"] = self.endpoint
        st["unreachable"] = False
        return st

    def flight_record(self) -> dict:
        """The remote server's flight-recorder dump (pinned failure
        timelines survive the process boundary by crossing it here);
        ``{"unreachable": True}`` when the replica cannot answer."""
        try:
            rec = self._client.call("flight_record", name=self.MODEL,
                                    _timeout=self.rpc_timeout)
        except _TRANSPORT_ERRORS as e:
            logger.warning(
                "remote replica %s: flight record unreachable (%s)",
                self.endpoint, type(e).__name__)
            return {"endpoint": self.endpoint, "unreachable": True}
        rec["endpoint"] = self.endpoint
        return rec

    # -- deploy seam -------------------------------------------------------
    def _admin_call(self, method: str, _idempotent=None, **params):
        try:
            return self._client.call(method, _timeout=self.admin_timeout,
                                     _idempotent=_idempotent, **params)
        except _TRANSPORT_ERRORS as e:
            self._raise_mapped(e, deadline_bound=False, what=method)

    @property
    def net(self):
        """A `_RemoteSnapshot` of the replica's CURRENT weights (the
        replica writes them to scratch and answers with the path) —
        what `rolling_reload` captures before a deploy so rollback can
        restore across the process boundary. Requires a filesystem
        both processes share."""
        info = self._admin_call("snapshot_model", name=self.MODEL)
        return _RemoteSnapshot(info["path"], info["version"])

    def restore_model(self, obj) -> int:
        """Swap the remote replica onto `obj`: a `_RemoteSnapshot`
        (rollback — ship the path back) or a live net (`sync_net` —
        serialize to scratch first). Idempotent on the wire: restoring
        the same weights twice is the same outcome, so a mid-restore
        connection hiccup retries instead of evicting the replica."""
        if isinstance(obj, _RemoteSnapshot):
            path = obj.path
        else:
            path = str(self._scratch /
                       f"restore-{os.getpid()}-"
                       f"{next(self._restore_counter)}.zip")
            _write_model_file(obj, path)
        return self._admin_call("restore_snapshot", _idempotent=True,
                                name=self.MODEL, path=str(path))

    def reload(self, source, step: Optional[int] = None) -> int:
        """Run the remote server's full reload ladder (manifest verify
        + canary) against a checkpoint path/store directory BOTH
        processes can see. Never auto-retried: the ladder is
        side-effectful and its typed rejection must reach the deploy
        loop un-doubled."""
        path = str(getattr(source, "directory", source))
        return self._admin_call("reload_model", name=self.MODEL,
                                path=path, step=step)

    def shutdown(self, drain_timeout: float = 10.0) -> bool:
        """Close this side's connections. The replica PROCESS outlives
        its pool handle on purpose — the supervisor owns process
        lifecycle (SIGTERM → remote `GatewayServer.stop` drains)."""
        self._client.close()
        return True


class ReplicaEntryPoint(EntryPoint):
    """The replica-process side of the seam: the full gateway
    `EntryPoint` plus the pool-management RPCs `RemoteReplica` needs.
    Always constructed WITH the serving tier (a replica without
    admission control would turn the pool's typed sheds into hangs).

    `chaos={"die_on_reload": True}` arms the crash-mid-deploy drill:
    the process SIGKILLs itself on the next `reload_model`, before the
    swap — exactly the window `rolling_reload`'s pool-wide rollback
    must survive."""

    def __init__(self, serving: Optional[dict] = None, *,
                 scratch_dir=None, chaos: Optional[dict] = None):
        super().__init__(serving=serving if serving is not None else {})
        self._scratch = Path(scratch_dir) if scratch_dir is not None \
            else Path(tempfile.gettempdir())
        self._scratch.mkdir(parents=True, exist_ok=True)
        self._snap_counter = itertools.count()
        self._chaos = dict(chaos or {})

    def serve_net(self, net, name: str = "replica") -> str:
        """Install a live net under `name` (the in-process test seam;
        subprocess replicas load via `--model`)."""
        self._install(name, net)
        return name

    def health(self) -> dict:
        return {"ok": True, "pid": os.getpid(),
                "models": sorted(self._models)}

    def snapshot_model(self, name: str) -> dict:
        """Write the CURRENT weights to scratch; answer the path +
        model_version. The rolling-reload rollback currency — the pool
        holds paths, not remote processes' live memory."""
        srv = self._server(name)
        version = int(getattr(srv, "model_version", 0))
        path = self._scratch / (f"snapshot-{name}-v{version}-"
                                f"{os.getpid()}-"
                                f"{next(self._snap_counter)}.zip")
        _write_model_file(srv.net, path)
        return {"path": str(path), "version": version}

    def restore_snapshot(self, name: str, path: str) -> int:
        """Swap this replica onto the weights at `path` (no canary —
        mirrors `ModelServer.restore_model`'s rollback semantics)."""
        srv = self._server(name)
        version = srv.restore_model(_read_model_file(path))
        self._models[name] = srv.net
        return version

    def replica_metrics(self, name: str, labels=None) -> str:
        return self._server(name).metrics_text(labels=labels)

    def reload_model(self, name: str, path: str,
                     step: Optional[int] = None) -> int:
        if self._chaos.get("die_on_reload"):
            logger.warning("replica %d: chaos die_on_reload armed — "
                           "SIGKILLing self", os.getpid())
            os.kill(os.getpid(), signal.SIGKILL)
        return super().reload_model(name, path, step=step)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """Replica-process entry: serve one model behind a gateway until
    SIGTERM/SIGINT. Readiness is published by ATOMICALLY writing
    ``<port> <pid>`` to `--ready-file` after the listener is up."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.serving.remote_replica",
        description="One supervised pool replica: a ModelServer behind "
                    "a gateway endpoint.")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--model", default=None,
                        help="checkpoint to serve (write_model format)")
    parser.add_argument("--scratch", default=None,
                        help="shared scratch dir for snapshot exchange")
    parser.add_argument("--serving", default=None,
                        help="JSON dict of ModelServer kwargs")
    parser.add_argument("--ready-file", default=None)
    parser.add_argument("--chaos-die-on-reload", action="store_true",
                        help="chaos drill: SIGKILL self on reload_model")
    args = parser.parse_args(argv)

    serving = json.loads(args.serving) if args.serving else {}
    chaos = {"die_on_reload": True} if args.chaos_die_on_reload else None
    entry = ReplicaEntryPoint(serving=serving, scratch_dir=args.scratch,
                              chaos=chaos)
    if args.model:
        entry.load_model("replica", args.model)
    server = GatewayServer(entry_point=entry, host=args.host,
                           port=args.port).start()

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())

    if args.ready_file:
        # atomic publish: the supervisor must never read a half-written
        # ready file
        tmp = Path(args.ready_file + ".tmp")
        tmp.write_text(f"{server.port} {os.getpid()}\n")
        tmp.rename(args.ready_file)
    logger.info("replica %d serving on %s:%d", os.getpid(), args.host,
                server.port)
    stop.wait()
    server.stop(drain_timeout=10.0)
    return 0


class ReplicaSupervisor:
    """Spawns and keeps alive N replica processes, one fixed port per
    slot (ports survive respawns, so `RemoteReplica` endpoints stay
    stable and the pool's probe ladder re-admits a respawned replica
    with zero reconfiguration).

    Restart discipline per slot: a death is respawned after a backoff
    that DOUBLES per quick death (`restart_backoff` up to
    `max_backoff`) and resets once a replica survives
    `restart_window` seconds; more than `max_restarts` deaths inside
    one window gives the slot up (a crash-looping binary must not burn
    the host forever). Respawn does NOT wait for readiness — the
    pool's probes own re-admission.

    `kill(i)` is the chaos drill seam (`kill -9` by default);
    `chaos_die_on_reload` arms specific slots to SIGKILL themselves
    mid-`reload_model`."""

    def __init__(self, model_path, n_replicas: int, *,
                 scratch_dir, serving: Optional[dict] = None,
                 host: str = "127.0.0.1",
                 python: str = sys.executable,
                 restart_backoff: float = 0.25,
                 max_backoff: float = 5.0,
                 max_restarts: int = 5,
                 restart_window: float = 30.0,
                 poll_interval: float = 0.2,
                 spawn_timeout: float = 90.0,
                 env: Optional[dict] = None,
                 chaos_die_on_reload: Sequence[int] = ()):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self.n_replicas = n_replicas
        self._model_path = str(model_path)
        self._scratch = Path(scratch_dir)
        self._scratch.mkdir(parents=True, exist_ok=True)
        self._serving = dict(serving or {})
        self._host = host
        self._python = python
        self.restart_backoff = restart_backoff
        self.max_backoff = max_backoff
        self.max_restarts = max_restarts
        self.restart_window = restart_window
        self.poll_interval = poll_interval
        self.spawn_timeout = spawn_timeout
        self._env = dict(os.environ)
        self._env.update(env or {})
        self._chaos = frozenset(chaos_die_on_reload)
        from deeplearning4j_tpu.parallel.multiprocess import free_port
        self.ports = [free_port() for _ in range(n_replicas)]
        self._procs: List[Optional[subprocess.Popen]] = [None] * n_replicas
        self._lock = threading.Lock()
        self._closed = False  # guarded by: _lock
        self._wake = threading.Event()
        self._last_spawn = [0.0] * n_replicas
        self._restarts_in_window = [0] * n_replicas
        self._backoffs = [restart_backoff] * n_replicas
        self._retired: set = set()  # guarded by: _lock
        self.respawns = 0  # guarded by: _lock
        self._monitor: Optional[threading.Thread] = None
        _LIVE_SUPERVISORS.add(self)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ReplicaSupervisor":
        try:
            for i in range(self.n_replicas):
                self._spawn(i)
            deadline = time.monotonic() + self.spawn_timeout
            for i in range(self.n_replicas):
                self._await_ready(i, deadline)
        except BaseException:
            self.stop()
            raise
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         daemon=True,
                                         name="replica-supervisor")
        self._monitor.start()
        return self

    def _ready_path(self, i: int) -> Path:
        return self._scratch / f"replica-{i}.ready"

    def _cmd(self, i: int) -> List[str]:
        cmd = [self._python, "-m",
               "deeplearning4j_tpu.serving.remote_replica",
               "--host", self._host, "--port", str(self.ports[i]),
               "--model", self._model_path,
               "--scratch", str(self._scratch),
               "--ready-file", str(self._ready_path(i))]
        if self._serving:
            cmd += ["--serving", json.dumps(self._serving)]
        if i in self._chaos:
            cmd += ["--chaos-die-on-reload"]
        return cmd

    def _spawn(self, i: int) -> None:
        ready = self._ready_path(i)
        with contextlib.suppress(OSError):
            ready.unlink()
        log_path = self._scratch / f"replica-{i}.log"
        with open(log_path, "ab") as log:
            proc = subprocess.Popen(self._cmd(i), cwd=str(_REPO_ROOT),
                                    env=self._env, stdout=log,
                                    stderr=subprocess.STDOUT)
        self._procs[i] = proc
        self._last_spawn[i] = time.monotonic()
        _ORPHAN_PIDS.add(proc.pid)
        logger.info("replica supervisor: spawned replica %d (pid %d, "
                    "port %d)", i, proc.pid, self.ports[i])

    def _log_tail(self, i: int, n: int = 20) -> str:
        try:
            lines = (self._scratch / f"replica-{i}.log") \
                .read_text(errors="replace").splitlines()
            return "\n".join(lines[-n:])
        except OSError:
            return "<no log>"

    def _await_ready(self, i: int, deadline: float) -> None:
        ready = self._ready_path(i)
        while time.monotonic() < deadline:
            if ready.exists():
                return
            proc = self._procs[i]
            if proc is not None and proc.poll() is not None:
                raise ReplicaSpawnError(
                    f"replica {i} (port {self.ports[i]}) died during "
                    f"startup (exit {proc.returncode}); log tail:\n"
                    f"{self._log_tail(i)}")
            time.sleep(0.05)
        raise ReplicaSpawnError(
            f"replica {i} (port {self.ports[i]}) not ready within "
            f"{self.spawn_timeout:.0f}s; log tail:\n{self._log_tail(i)}")

    # -- respawn loop ------------------------------------------------------
    def _monitor_loop(self) -> None:
        while True:
            self._wake.wait(self.poll_interval)
            self._wake.clear()
            with self._lock:
                if self._closed:
                    return
                retired = set(self._retired)
            for i in range(self.n_replicas):
                if i in retired:
                    continue  # scale-down: never respawn a retired slot
                proc = self._procs[i]
                if proc is None or proc.poll() is None:
                    continue
                _ORPHAN_PIDS.discard(proc.pid)
                lived = time.monotonic() - self._last_spawn[i]
                if lived > self.restart_window:
                    # it ran long enough to count as stable: forgive
                    self._backoffs[i] = self.restart_backoff
                    self._restarts_in_window[i] = 0
                self._restarts_in_window[i] += 1
                if self._restarts_in_window[i] > self.max_restarts:
                    logger.error(
                        "replica supervisor: replica %d died %d times "
                        "within %.0fs — giving the slot up; log "
                        "tail:\n%s", i, self._restarts_in_window[i],
                        self.restart_window, self._log_tail(i))
                    self._procs[i] = None
                    continue
                backoff = self._backoffs[i]
                self._backoffs[i] = min(backoff * 2, self.max_backoff)
                logger.warning(
                    "replica supervisor: replica %d (pid %d) exited "
                    "%s — respawn %d/%d after %.2fs backoff", i,
                    proc.pid, proc.returncode,
                    self._restarts_in_window[i], self.max_restarts,
                    backoff)
                if self._wake.wait(backoff):
                    self._wake.clear()
                with self._lock:
                    if self._closed:
                        return
                self._spawn(i)
                with self._lock:
                    self.respawns += 1

    # -- elasticity (the autoscaler's seam) --------------------------------
    def grow_slot(self) -> int:
        """Scale-up: allocate a NEW slot (fresh port), spawn its replica
        process, and wait for readiness. Returns the slot index. On any
        failure the half-born slot is retired (the monitor must never
        respawn it) and `ReplicaSpawnError` propagates — the autoscaler
        wraps it in `AutoscaleError`."""
        from deeplearning4j_tpu.parallel.multiprocess import free_port
        with self._lock:
            if self._closed:
                raise ReplicaSpawnError("supervisor is stopped")
            i = len(self.ports)
            self.ports.append(free_port())
            self._procs.append(None)
            self._last_spawn.append(0.0)
            self._restarts_in_window.append(0)
            self._backoffs.append(self.restart_backoff)
            # n_replicas grows LAST: the monitor iterates
            # range(n_replicas) without the lock, so every parallel
            # array must already cover the new slot when it does
            self.n_replicas += 1
        try:
            self._spawn(i)
            self._await_ready(i, time.monotonic() + self.spawn_timeout)
        except BaseException:
            self.retire_slot(i)
            raise
        logger.info("replica supervisor: grew slot %d (port %d)", i,
                    self.ports[i])
        return i

    def retire_slot(self, i: int) -> None:
        """Scale-down: permanently stop slot `i`. The slot is marked
        retired BEFORE its process is signalled — otherwise the monitor
        could observe the death and respawn it in the gap. Slot indices
        and ports are never reused, so surviving `RemoteReplica`
        endpoints stay stable. Idempotent."""
        if not 0 <= i < self.n_replicas:
            raise ValueError(f"no supervisor slot {i}")
        with self._lock:
            self._retired.add(i)
        proc = self._procs[i]
        self._procs[i] = None
        if proc is not None:
            _ORPHAN_PIDS.discard(proc.pid)
        if proc is not None and proc.poll() is None:
            with contextlib.suppress(OSError):
                proc.terminate()
            with contextlib.suppress(Exception):
                proc.wait(timeout=5.0)
            if proc.poll() is None:
                with contextlib.suppress(OSError):
                    proc.kill()
                with contextlib.suppress(Exception):
                    proc.wait(timeout=5.0)
        logger.info("replica supervisor: retired slot %d (port %d)", i,
                    self.ports[i])

    def slot_for_port(self, port: int) -> int:
        """Map a replica endpoint's port back to its supervisor slot
        (the autoscaler removes a pool replica first, then retires the
        slot that served it)."""
        with self._lock:
            retired = set(self._retired)
        for i, p in enumerate(self.ports):
            if p == port and i not in retired:
                return i
        raise ValueError(f"no live supervisor slot serving port {port}")

    def live_slots(self) -> int:
        """Slots that can currently hold a process (not retired, not
        given up) — the autoscaler's view of supervisor capacity."""
        with self._lock:
            retired = set(self._retired)
        return sum(1 for i in range(self.n_replicas) if i not in retired)

    # -- drills / introspection --------------------------------------------
    def kill(self, i: int, sig: int = signal.SIGKILL) -> int:
        """Chaos seam: signal replica `i`'s process (default SIGKILL —
        the hard-crash drill). Returns the signalled pid."""
        proc = self._procs[i]
        if proc is None:
            raise ValueError(f"replica {i} has no live process")
        os.kill(proc.pid, sig)
        return proc.pid

    def is_alive(self, i: int) -> bool:
        proc = self._procs[i]
        return proc is not None and proc.poll() is None

    def endpoints(self) -> List[Tuple[str, int]]:
        return [(self._host, p) for p in self.ports]

    def set_model_path(self, path) -> None:
        """Point future respawns at newly-deployed weights (called by
        `RemoteReplicaPool.rolling_reload` on success — a replica
        respawned after a deploy must not resurrect the old
        version)."""
        self._model_path = str(path)

    def stop(self) -> None:
        """Terminate every replica (SIGTERM → the process drains its
        gateway; SIGKILL after a bounded wait) and stop respawning.
        Idempotent."""
        with self._lock:
            self._closed = True
        self._wake.set()
        procs = [p for p in self._procs if p is not None]
        for proc in procs:
            with contextlib.suppress(OSError):
                proc.terminate()
        deadline = time.monotonic() + 5.0
        for proc in procs:
            with contextlib.suppress(Exception):
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            if proc.poll() is None:
                with contextlib.suppress(OSError):
                    proc.kill()
                with contextlib.suppress(Exception):
                    proc.wait(timeout=5.0)
            _ORPHAN_PIDS.discard(proc.pid)
        if self._monitor is not None:
            self._monitor.join(self.poll_interval + self.max_backoff
                               + 5.0)


class RemoteReplicaPool(ReplicaPool):
    """`ReplicaPool` over `RemoteReplica`s, plus the glue the process
    boundary needs: `.net` answers the spawn-time template net instead
    of a snapshot RPC per registry peek, `sync_net` serializes ONCE
    and ships the path to every replica (a dead replica is evicted +
    marked stale, not fatal), `rolling_reload` re-points the
    supervisor at the deployed weights so respawns serve the new
    version, and `shutdown` stops the supervisor."""

    # a streaming sink is a callable — it cannot cross the process
    # boundary, so remote pools serve streams unary-fallback style
    supports_stream_sink = False

    def __init__(self, replicas: Sequence, *, supervisor=None,
                 template_net=None, scratch_dir=None, **pool_kwargs):
        self._supervisor = supervisor
        self._template_net = template_net
        self._scratch = Path(scratch_dir) if scratch_dir is not None \
            else Path(tempfile.gettempdir())
        self._sync_counter = itertools.count()
        super().__init__(replicas, **pool_kwargs)

    @property
    def supervisor(self):
        return self._supervisor

    # -- elasticity (the autoscaler's seam) --------------------------------
    def grow_replica(self) -> int:
        """Scale-up across the process boundary: grow a supervisor slot
        (fresh process, fresh port, readiness-gated) and attach it to
        the pool EVICTED — the probe ladder owns re-admission, exactly
        like a respawned crashed replica. Returns the pool replica id.
        `ReplicaSpawnError` propagates on supervisor exhaustion."""
        sup = self._supervisor
        if sup is None:
            raise ReplicaSpawnError(
                "pool has no supervisor to spawn replicas with")
        slot = sup.grow_slot()
        rep = RemoteReplica(
            sup._host, sup.ports[slot], scratch_dir=self._scratch,
            max_queue=sup._serving.get("max_queue", 64))
        return self.add_replica(rep)

    def shrink_replica(self, replica_id: int, *,
                       drain_timeout: float = 30.0) -> None:
        """Scale-down across the process boundary: drain + detach the
        pool replica (zero-failed-requests discipline — aborts typed if
        the drain cannot finish), then retire the supervisor slot that
        served it so the process is stopped and never respawned."""
        server = self.remove_replica(replica_id,
                                     drain_timeout=drain_timeout)
        if self._supervisor is not None:
            port = int(server.endpoint.rsplit(":", 1)[1])
            try:
                self._supervisor.retire_slot(
                    self._supervisor.slot_for_port(port))
            except ValueError:
                logger.warning(
                    "remote pool: no live supervisor slot for removed "
                    "replica %d (port %d) — already retired?",
                    replica_id, port)

    @property
    def net(self):
        """The template net the pool was spawned from (kept in step by
        `sync_net`) — NOT a live replica's weights; reading those
        would cost a snapshot RPC per access."""
        return self._template_net

    def sync_net(self, net) -> None:
        with self._reload_lock:
            path = self._scratch / (f"sync-{os.getpid()}-"
                                    f"{next(self._sync_counter)}.zip")
            _write_model_file(net, path)
            snap = _RemoteSnapshot(str(path), 0)
            for rep in self._replicas:
                try:
                    rep.server.restore_model(snap)
                except (ServingError, GatewayError) as e:
                    # a replica that cannot take the sync is on OLD
                    # weights: evict + stale bars it from re-admission
                    # until a later reload/sync lands, so it cannot
                    # version-split the pool
                    with self._lock:
                        self._evict_locked(
                            rep, f"sync_net failed: {type(e).__name__}")
                        rep.stale = True
                    continue
                with self._lock:
                    rep.stale = False
            self._template_net = net

    @staticmethod
    def _resolve_deploy_path(source, step: Optional[int]):
        """The concrete checkpoint file a deploy landed — what future
        respawns must serve."""
        if hasattr(source, "path_for"):
            if step is not None:
                return source.path_for(step)
            latest = source.latest_verified()
            return None if latest is None else latest[1]
        return source

    def rolling_reload(self, source, step: Optional[int] = None,
                       drain_timeout: float = 30.0) -> List[int]:
        versions = super().rolling_reload(source, step=step,
                                          drain_timeout=drain_timeout)
        if self._supervisor is not None:
            try:
                path = self._resolve_deploy_path(source, step)
            except (OSError, ValueError, ServingError) as e:
                logger.warning(
                    "remote pool: could not resolve the deployed "
                    "checkpoint path (%s) — respawns keep the previous "
                    "weights until the next deploy", type(e).__name__)
                path = None
            if path is not None:
                self._supervisor.set_model_path(path)
        return versions

    def shutdown(self, drain_timeout: float = 10.0) -> bool:
        ok = super().shutdown(drain_timeout=drain_timeout)
        if self._supervisor is not None:
            self._supervisor.stop()
        return ok


def spawn_replica_pool(net, n_replicas: int, *,
                       scratch_dir=None,
                       server_kwargs: Optional[dict] = None,
                       pool_kwargs: Optional[dict] = None,
                       supervisor_kwargs: Optional[dict] = None,
                       host: str = "127.0.0.1",
                       rpc_timeout: float = 30.0,
                       admin_timeout: float = 120.0,
                       deadline_margin: float = 2.0) -> RemoteReplicaPool:
    """The one-call cross-process pool: serialize `net`, spawn
    `n_replicas` supervised replica processes each serving it behind a
    gateway endpoint, and wire a `RemoteReplicaPool` over them.
    `server_kwargs` configure each replica's ModelServer (shipped as
    the process's `--serving` JSON), `pool_kwargs` the pool,
    `supervisor_kwargs` the restart discipline. The gateway's
    `serving={"replicas": N, "remote": {...}}` config lands here."""
    server_kwargs = dict(server_kwargs or {})
    scratch = Path(scratch_dir) if scratch_dir is not None else \
        Path(tempfile.mkdtemp(prefix="dl4j-remote-pool-"))
    scratch.mkdir(parents=True, exist_ok=True)
    model_path = scratch / "model.zip"
    _write_model_file(net, model_path)
    supervisor = ReplicaSupervisor(model_path, n_replicas,
                                   scratch_dir=scratch,
                                   serving=server_kwargs, host=host,
                                   **(supervisor_kwargs or {}))
    try:
        supervisor.start()
        replicas = [
            RemoteReplica(host, port, scratch_dir=scratch,
                          rpc_timeout=rpc_timeout,
                          admin_timeout=admin_timeout,
                          deadline_margin=deadline_margin,
                          max_queue=server_kwargs.get("max_queue", 64))
            for port in supervisor.ports]
        return RemoteReplicaPool(replicas, supervisor=supervisor,
                                 template_net=net, scratch_dir=scratch,
                                 **(pool_kwargs or {}))
    except BaseException:
        supervisor.stop()
        raise


if __name__ == "__main__":
    sys.exit(main())
