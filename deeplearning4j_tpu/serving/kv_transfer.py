"""KV-page shipping between decode engines: wire format, leases, disagg.

Continuous batching (PR 12) made the KV page the unit of *ownership*
inside one engine — refcounted, promoted into the prefix cache, freed
exactly once. This module makes the page the unit of ownership
*between* engines: a serialized handoff payload carries everything a
peer needs to resume a generation mid-sequence with bit-identical
output — the used KV pages of every block (plus int8 scale sidecars),
the page-table span, the slot position/last-token registers, the live
per-slot PRNG key, and the emitted-token transcript.

Fault discipline, because the wire is the failure domain:

- **Leases with TTL** — the sender never frees shipped pages on export;
  it grants a lease holding the pages (and any prefix-cache pins) until
  the receiver commits. A receiver that dies mid-transfer simply lets
  the lease expire: the sender's sweep reclaims the pages. No
  double-free, no leak, regardless of which side dies.
- **Per-page checksums** — every page slice is checksummed at build and
  re-verified at import. A corrupted frame is a typed
  `KVTransferError`, never silently-wrong tokens.
- **Deadline-derived timeouts** — transfer RPCs inherit the request's
  remaining deadline, so a stuck wire cannot outlive the request.
- **Degradation ladder** — any transfer failure (corruption, expiry,
  version skew, partition) maps to a typed error and the caller falls
  back to re-prefill from the prompt: same seed, same output, just
  slower. Migration is an optimization that can only lose time, never
  tokens.

Two consumers:

- `DisaggCoordinator` — disaggregated serving: prefill-role engines
  (compute-bound chunked prefill) ship freshly computed KV to
  decode-role engines (bandwidth-bound C=1 steps), selected via
  `serving={"disagg": {...}}` through the gateway.
- `ReplicaPool` live migration — drain/scale-down/failover export
  in-flight slots via `SlotMigratedError` and resume them on a healthy
  peer (see `replica_pool._resume_migrated`).
"""
from __future__ import annotations

import hashlib
import logging
import threading
import time
import uuid
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.serving.model_server import (
    DeadlineExceededError,
    ServerClosedError,
    ServingError,
)

logger = logging.getLogger(__name__)

WIRE_VERSION = 1

# payload fields every well-formed handoff must carry (block arrays are
# validated separately — their shapes depend on kind/quantization)
_REQUIRED_FIELDS = (
    "version", "handoff_id", "kind", "weight_version", "kv_quant",
    "page_size", "n_blocks", "prompt", "n_tokens", "temperature",
    "seed", "resumed_at", "tokens", "pages_shipped", "blocks", "sums",
)


class KVTransferError(ServingError):
    """A KV handoff could not be completed or trusted: checksum
    mismatch, truncated frame, expired/unknown lease, weight-version or
    geometry skew, or a role refusal. Always recoverable by the
    fallback ladder — re-prefill from the prompt reproduces the exact
    output."""


class SlotMigratedError(ServingError):
    """Not a failure: a redirect. The engine exported this request's
    decode state under a lease instead of finishing it; the caller
    should fetch the handoff payload with `fetch_handoff(handoff_id)`,
    resume it on a peer, and splice `tokens` (everything emitted before
    export) in front of the peer's tail."""

    def __init__(self, message: str, handoff_id: str = "",
                 tokens: Optional[List[int]] = None,
                 source: Optional[str] = None):
        super().__init__(message)
        self.handoff_id = handoff_id
        self.tokens = list(tokens or [])
        self.source = source

    def wire_payload(self) -> dict:
        # rides the gateway error frame so a remote caller can rebuild
        # the redirect with its routing fields intact
        return {"handoff_id": self.handoff_id,
                "tokens": [int(t) for t in self.tokens],
                "source": self.source}


# ---------------------------------------------------------------------------
# checksums + payload build/verify


def page_checksum(page: np.ndarray) -> str:
    """Stable 64-bit content hash of one page slice (dtype- and
    shape-sensitive, so a truncated or re-typed frame can never
    collide with the original)."""
    arr = np.ascontiguousarray(page)
    h = hashlib.blake2b(digest_size=8)
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(arr.tobytes())
    return h.hexdigest()


def _block_sums(block: Dict[str, np.ndarray]) -> Dict[str, List[str]]:
    return {name: [page_checksum(arr[i]) for i in range(arr.shape[0])]
            for name, arr in block.items()}


def payload_nbytes(payload: dict) -> int:
    """Wire-side KV bytes of a handoff (pages + scales, excluding the
    scalar envelope) — the numerator of kv_transfer_mbytes_per_sec."""
    return sum(int(arr.nbytes)
               for block in payload.get("blocks", ())
               for arr in block.values())


def build_payload(*, handoff_id: str, kind: str, weight_version: str,
                  kv_quant: Optional[str], page_size: int, n_blocks: int,
                  prompt: np.ndarray, n_tokens: int, temperature: float,
                  seed: int, resumed_at: int, tokens: List[int],
                  blocks: List[Dict[str, np.ndarray]],
                  pages_shipped: int, pos: int = 0, tok: int = 0,
                  key: Optional[np.ndarray] = None, temp: float = 0.0,
                  tenant: Optional[str] = None, priority: str = "normal",
                  preempted: int = 0,
                  deadline_remaining: Optional[float] = None,
                  source: Optional[str] = None,
                  logprobs: int = 0,
                  logprob_values: Optional[List[dict]] = None,
                  pages_omitted: int = 0) -> dict:
    """Assemble one handoff payload (checksums computed here). All
    leaves are plain scalars / lists / numpy arrays, so the gateway's
    recursive codec ships it without a custom frame type.

    `pages_omitted` is the DELTA-transfer contract: the shipped blocks
    cover logical pages ``[pages_omitted, pages_omitted +
    pages_shipped)`` of the sequence; the receiver supplies the first
    `pages_omitted` pages from its own resident prefix chain (and must
    refuse the payload, typed, if it cannot)."""
    return {
        "version": WIRE_VERSION,
        "handoff_id": handoff_id,
        # "warm" = KV pages ride along; "cold" = re-prefill;
        # "prefix" = prompt-prefix pages only (cluster prefix fetch)
        "kind": kind,
        "weight_version": weight_version,
        "kv_quant": kv_quant,
        "page_size": int(page_size),
        "n_blocks": int(n_blocks),
        "prompt": np.asarray(prompt, np.int32),
        "n_tokens": int(n_tokens),
        "temperature": float(temperature),
        "seed": int(seed),
        "tenant": tenant,
        "priority": priority,
        "resumed_at": int(resumed_at),
        "preempted": int(preempted),
        "tokens": [int(t) for t in tokens],
        "deadline_remaining": (None if deadline_remaining is None
                               else float(deadline_remaining)),
        "pos": int(pos),
        "tok": int(tok),
        "key": (np.zeros((2,), np.uint32) if key is None
                else np.asarray(key, np.uint32)),
        "temp": float(temp),
        "pages_shipped": int(pages_shipped),
        "pages_omitted": int(pages_omitted),
        "blocks": blocks,
        "sums": [_block_sums(b) for b in blocks],
        "source": source,
        # streaming/logprobs state rides the handoff so the peer keeps
        # emitting per-step entries under the same cursor
        "logprobs": int(logprobs),
        "logprob_values": list(logprob_values or []),
    }


def verify_payload(payload: dict, *, weight_version: Optional[str] = None,
                   kv_quant: Optional[str] = "unchecked",
                   page_size: Optional[int] = None,
                   n_blocks: Optional[int] = None,
                   max_len: Optional[int] = None,
                   kinds=("warm", "cold")) -> dict:
    """Validate a handoff payload structurally and against the
    receiving engine's geometry, then re-verify every page checksum.
    Raises the typed `KVTransferError` on ANY discrepancy — a payload
    that fails here has touched no engine state. `kinds` is the
    caller's acceptance policy: `resume_submit` takes warm/cold, the
    cluster prefix-fetch path takes only "prefix" — a payload of the
    wrong kind is refused typed, never half-bound."""
    if not isinstance(payload, dict):
        raise KVTransferError(
            f"malformed handoff payload: expected dict, got "
            f"{type(payload).__name__}")
    missing = [f for f in _REQUIRED_FIELDS if f not in payload]
    if missing:
        raise KVTransferError(
            f"truncated handoff payload: missing fields {missing}")
    if int(payload["version"]) != WIRE_VERSION:
        raise KVTransferError(
            f"handoff wire version {payload['version']} != "
            f"{WIRE_VERSION}")
    if payload["kind"] not in ("warm", "cold", "prefix"):
        raise KVTransferError(
            f"unknown handoff kind {payload['kind']!r}")
    if payload["kind"] not in kinds:
        raise KVTransferError(
            f"handoff kind {payload['kind']!r} refused here "
            f"(acceptable: {list(kinds)})")
    if weight_version is not None \
            and payload["weight_version"] != weight_version:
        raise KVTransferError(
            "stale-weights handoff refused: sender weight version "
            f"{payload['weight_version']} != receiver {weight_version}")
    if kv_quant != "unchecked" and payload["kv_quant"] != kv_quant:
        raise KVTransferError(
            f"KV quantization mismatch: sender {payload['kv_quant']!r} "
            f"!= receiver {kv_quant!r}")
    if page_size is not None and int(payload["page_size"]) != page_size:
        raise KVTransferError(
            f"page-size mismatch: sender {payload['page_size']} != "
            f"receiver {page_size}")
    if n_blocks is not None and int(payload["n_blocks"]) != n_blocks:
        raise KVTransferError(
            f"block-count mismatch: sender {payload['n_blocks']} != "
            f"receiver {n_blocks}")
    prompt = np.asarray(payload["prompt"])
    if prompt.ndim != 1 or prompt.size == 0:
        raise KVTransferError("handoff prompt must be a non-empty 1-D "
                              f"array, got shape {prompt.shape}")
    n_tok = int(payload["n_tokens"])
    resumed_at = int(payload["resumed_at"])
    if not 0 <= resumed_at <= n_tok:
        raise KVTransferError(
            f"handoff resumed_at={resumed_at} outside [0, {n_tok}]")
    if len(payload["tokens"]) > n_tok:
        raise KVTransferError(
            f"handoff carries {len(payload['tokens'])} emitted tokens "
            f"but n_tokens={n_tok}")
    if max_len is not None:
        span = prompt.shape[0] + max(1, n_tok - resumed_at) - 1
        if span > max_len:
            raise KVTransferError(
                f"handoff span {span} exceeds receiver max_len "
                f"{max_len}")
    shipped = int(payload["pages_shipped"])
    omitted = int(payload.get("pages_omitted", 0))
    if omitted < 0:
        raise KVTransferError(
            f"handoff pages_omitted={omitted} must be >= 0")
    blocks = payload["blocks"]
    sums = payload["sums"]
    if payload["kind"] == "cold":
        if shipped != 0 or blocks:
            raise KVTransferError("cold handoff must carry zero pages")
        return payload
    if shipped <= 0:
        raise KVTransferError(
            f"{payload['kind']} handoff carries zero shipped pages")
    if len(blocks) != len(sums):
        raise KVTransferError(
            f"truncated handoff: {len(blocks)} blocks vs "
            f"{len(sums)} checksum sets")
    if n_blocks is not None and len(blocks) != n_blocks:
        raise KVTransferError(
            f"truncated handoff: {len(blocks)} blocks shipped, "
            f"receiver has {n_blocks}")
    for bi, (block, ref) in enumerate(zip(blocks, sums)):
        if set(block) != set(ref):
            raise KVTransferError(
                f"handoff block {bi} tensors {sorted(block)} != "
                f"checksummed {sorted(ref)}")
        for name, arr in block.items():
            arr = np.asarray(arr)
            if arr.shape[0] != shipped or len(ref[name]) != shipped:
                raise KVTransferError(
                    f"truncated handoff: block {bi} tensor {name!r} "
                    f"ships {arr.shape[0]} pages / {len(ref[name])} "
                    f"sums, expected {shipped}")
            for i in range(shipped):
                got = page_checksum(arr[i])
                if got != ref[name][i]:
                    raise KVTransferError(
                        f"corrupted handoff frame: block {bi} tensor "
                        f"{name!r} page {i} checksum {got} != "
                        f"{ref[name][i]}")
    return payload


# ---------------------------------------------------------------------------
# delta framing — ship only what the receiver lacks, in bounded frames
#
# A 32k-token handoff serialized as ONE message is both a memory spike
# and an all-or-nothing wire unit. The frame protocol splits a leased
# payload into a blockless HEADER (scalars + per-page checksums) plus N
# bounded FRAMES of page slices, and lets the receiver skip the leading
# pages it already holds for the sequence's prefix chain
# (`pages_omitted`). The header's checksums are sliced to exactly the
# shipped span, so `verify_payload` on the reassembled payload re-proves
# every page end-to-end — a frame corrupted, duplicated, reordered, or
# dropped in transit is a typed refusal, never silently-wrong tokens.

_FRAME_META = ("n_frames", "frame_pages")


def payload_header(payload: dict, *, skip_pages: int = 0,
                   frame_pages: Optional[int] = None) -> dict:
    """Blockless copy of a leased payload, advanced by `skip_pages`
    already-held pages and annotated with the frame schedule
    (`n_frames`, `frame_pages`). The caller clamps `skip_pages` to what
    the receiver proved it holds; this function clamps it to the
    shipped span (at least one page always ships — the resume point's
    page is never elidable)."""
    shipped = int(payload["pages_shipped"])
    skip = max(0, min(int(skip_pages), shipped - 1))
    fp = shipped - skip if frame_pages is None else int(frame_pages)
    if fp < 1:
        raise KVTransferError(f"frame_pages must be >= 1, got {fp}")
    header = {k: v for k, v in payload.items() if k != "blocks"}
    header["sums"] = [{name: sums[skip:] for name, sums in ref.items()}
                      for ref in payload["sums"]]
    header["pages_shipped"] = shipped - skip
    header["pages_omitted"] = int(payload.get("pages_omitted", 0)) + skip
    header["n_frames"] = -(-(shipped - skip) // fp)
    header["frame_pages"] = fp
    return header


def slice_frame(payload: dict, frame: int, *, skip_pages: int = 0,
                frame_pages: Optional[int] = None) -> dict:
    """One bounded frame of a leased payload: page slices
    ``[skip + frame*fp, skip + (frame+1)*fp)`` of every block tensor.
    Stateless — the receiver passes back the (skip, frame_pages) pair
    from its header, so the sender keeps no per-receiver cursor."""
    shipped = int(payload["pages_shipped"])
    skip = max(0, min(int(skip_pages), shipped - 1))
    fp = shipped - skip if frame_pages is None else int(frame_pages)
    if fp < 1:
        raise KVTransferError(f"frame_pages must be >= 1, got {fp}")
    n_frames = -(-(shipped - skip) // fp)
    if not 0 <= int(frame) < n_frames:
        raise KVTransferError(
            f"frame {frame} outside [0, {n_frames}) for "
            f"{shipped - skip} shipped pages / {fp} per frame")
    lo = skip + int(frame) * fp
    hi = min(skip + (int(frame) + 1) * fp, shipped)
    return {"handoff_id": payload["handoff_id"],
            "frame": int(frame), "n_frames": n_frames,
            "blocks": [{name: np.asarray(arr)[lo:hi]
                        for name, arr in block.items()}
                       for block in payload["blocks"]]}


def assemble_payload(header: dict, frames: List[dict]) -> dict:
    """Reassemble a full payload from a header plus its frames,
    checking identity, order, and page-count closure. The result still
    goes through `verify_payload` (checksums) before anything binds."""
    n_frames = int(header.get("n_frames", 0))
    if len(frames) != n_frames:
        raise KVTransferError(
            f"truncated framed handoff: {len(frames)} frames received, "
            f"header promised {n_frames}")
    n_blocks = int(header["n_blocks"])
    for i, fr in enumerate(frames):
        if fr.get("handoff_id") != header["handoff_id"]:
            raise KVTransferError(
                f"framed handoff identity mismatch at frame {i}: "
                f"{fr.get('handoff_id')!r} != {header['handoff_id']!r}")
        if int(fr.get("frame", -1)) != i:
            raise KVTransferError(
                f"framed handoff out of order: got frame "
                f"{fr.get('frame')} at position {i}")
        if len(fr.get("blocks", ())) != n_blocks:
            raise KVTransferError(
                f"framed handoff frame {i} carries "
                f"{len(fr.get('blocks', ()))} blocks, expected {n_blocks}")
    payload = {k: v for k, v in header.items() if k not in _FRAME_META}
    if n_frames == 0:
        payload["blocks"] = []
        return payload
    names = list(frames[0]["blocks"][0].keys()) if n_blocks else []
    blocks = []
    for bi in range(n_blocks):
        blocks.append({
            name: np.concatenate(
                [np.asarray(fr["blocks"][bi][name]) for fr in frames],
                axis=0)
            for name in names})
    payload["blocks"] = blocks
    shipped = int(header["pages_shipped"])
    for bi, block in enumerate(blocks):
        for name, arr in block.items():
            if arr.shape[0] != shipped:
                raise KVTransferError(
                    f"framed handoff block {bi} tensor {name!r} "
                    f"reassembles {arr.shape[0]} pages, header promised "
                    f"{shipped}")
    return payload


# ---------------------------------------------------------------------------
# leases


class _Lease:
    """One granted handoff on the sender: the payload (fetchable until
    resolution) plus the page/prefix-pin ownership that must be freed
    exactly once — by commit, abort, or TTL expiry."""

    __slots__ = ("handoff_id", "payload", "pages", "n_shared", "nodes",
                 "created_at", "expires_at", "fetched")

    def __init__(self, handoff_id, payload, pages, n_shared, nodes,
                 now, ttl):
        self.handoff_id = handoff_id
        self.payload = payload
        self.pages = pages          # full page list (incl. shared prefix)
        self.n_shared = n_shared    # leading pages owned by cache nodes
        self.nodes = nodes          # acquired prefix-cache pins, if any
        self.created_at = now
        self.expires_at = now + ttl
        # the receiver has fetched the payload at least once: the bytes
        # left this process, so a sender dying afterward costs only the
        # commit (TTL-irrelevant), not the resume
        self.fetched = False


class LeaseTable:
    """Sender-side ledger of in-flight handoffs. NOT self-locking: the
    owning engine guards every call with its scheduler condvar (the
    same lock that guards the free-page list the leases feed back
    into), so grant/resolve/sweep are atomic with page accounting."""

    def __init__(self, ttl: float = 30.0):
        if ttl <= 0:
            raise ValueError(f"lease ttl must be > 0, got {ttl}")
        self.ttl = float(ttl)
        self._leases: Dict[str, _Lease] = {}

    def __len__(self) -> int:
        return len(self._leases)

    @staticmethod
    def new_id() -> str:
        return uuid.uuid4().hex

    def grant(self, payload: dict, *, pages: Optional[List[int]] = None,
              n_shared: int = 0, nodes: Optional[list] = None,
              now: Optional[float] = None) -> _Lease:
        now = time.monotonic() if now is None else now
        lease = _Lease(payload["handoff_id"], payload, pages, n_shared,
                       nodes, now, self.ttl)
        self._leases[lease.handoff_id] = lease
        return lease

    def get(self, handoff_id: str) -> Optional[_Lease]:
        return self._leases.get(handoff_id)

    def touch(self, handoff_id: str,
              now: Optional[float] = None) -> Optional[_Lease]:
        """Extend a lease's TTL (called on fetch, so a slow receiver
        that is still actively resuming cannot lose the race against
        the sweep)."""
        lease = self._leases.get(handoff_id)
        if lease is not None:
            now = time.monotonic() if now is None else now
            lease.expires_at = now + self.ttl
            lease.fetched = True
        return lease

    def unfetched(self) -> int:
        """Leases whose payload no receiver has fetched yet — the count
        a migrate-then-drain must wait on (bounded) before the sender
        may be disposed, or every export degrades to a fallback."""
        return sum(1 for lease in self._leases.values()
                   if not lease.fetched)

    def resolve(self, handoff_id: str) -> Optional[_Lease]:
        """Pop a lease (commit or abort — the caller frees the pages).
        Idempotent: a second resolve returns None."""
        return self._leases.pop(handoff_id, None)

    def expired_pending(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        return any(lease.expires_at <= now
                   for lease in self._leases.values())

    def sweep(self, now: Optional[float] = None) -> List[_Lease]:
        """Pop and return every expired lease (orphan reclamation: the
        receiver died or never committed; the caller reclaims pages)."""
        now = time.monotonic() if now is None else now
        dead = [hid for hid, lease in self._leases.items()
                if lease.expires_at <= now]
        return [self._leases.pop(hid) for hid in dead]

    def invalidate_pages(self) -> None:
        """Device-state reset on the sender: the pools the leased pages
        index into were rebuilt wholesale, so page ownership is void —
        but payloads stay fetchable (they are host copies; a receiver
        mid-resume still gets valid bytes)."""
        for lease in self._leases.values():
            lease.pages = None
            lease.n_shared = 0
            lease.nodes = None


# ---------------------------------------------------------------------------
# disaggregated serving


class DisaggCoordinator:
    """Prefill/decode disaggregation behind one server-shaped facade.

    Prefill-role servers run chunked prefill into their paged pools and
    export the finished slot as a handoff (never entering the decode
    loop); decode-role servers accept `resume_generate` imports and run
    only the C=1 decode step. `generate` routes: prefill → fetch the
    exported handoff → resume on a decode server → splice the tails.

    The degradation ladder is the coordinator's contract: if shipping
    fails (corruption, expiry, dead decode server), the whole flow
    retries once from a fresh prefill — same seed, identical output.
    When that also fails the typed error propagates; nothing is ever
    silently absorbed.

    When disagg pays: prefill-heavy mixes (long prompts, short
    completions) keep decode replicas' batch lanes dense instead of
    stalling them behind compute-bound prefills. Decode-heavy mixes pay
    the wire cost for nothing — stay colocated (see
    `bench.py serve_disagg`).
    """

    def __init__(self, net, *, prefill_replicas: int = 1,
                 decode_replicas: int = 1, server_kwargs: Optional[dict] = None,
                 prefix_cluster: bool = False, affinity_margin: int = 2,
                 frame_pages: int = 8):
        from deeplearning4j_tpu.serving.model_server import ModelServer

        if prefill_replicas < 1 or decode_replicas < 1:
            raise ValueError(
                "disagg needs >= 1 prefill and >= 1 decode replica, got "
                f"{prefill_replicas}/{decode_replicas}")
        kw = dict(server_kwargs or {})
        gen = kw.pop("generation", None)
        gen = {} if gen in (None, True) else dict(gen)
        gen.pop("role", None)

        def _server(role, first):
            g = dict(gen)
            g["role"] = role
            return ModelServer(net if first else net.clone(),
                               generation=g, **kw)

        self.prefill = [_server("prefill", i == 0)
                        for i in range(prefill_replicas)]
        self.decode = [_server("decode", False)
                       for _ in range(decode_replicas)]
        self._servers = self.prefill + self.decode
        self._lock = threading.Lock()
        self._rr_prefill = 0
        self._rr_decode = 0
        self._closed = False
        self.handoffs = 0
        self.fallbacks = 0
        self.transfer_bytes = 0
        self.transfer_seconds = 0.0
        # cluster-global prefix cache: one directory across both roles,
        # so a system prompt prefilled on prefill-0 is fetchable by
        # prefill-1 (skipping its prefill) and delta handoffs to decode
        # servers skip pages the receiver already holds
        self._prefix_cluster = bool(prefix_cluster)
        self._affinity_margin = int(affinity_margin)
        self._frame_pages = int(frame_pages)
        self.affinity_routes = 0      # guarded by: _lock
        self.delta_pages_skipped = 0  # guarded by: _lock
        self.prefix_directory = None
        self._holders: Dict[str, object] = {}
        if self._prefix_cluster:
            from deeplearning4j_tpu.serving.prefix_directory import (
                PrefixDirectory,
            )

            self.prefix_directory = PrefixDirectory()
            for i, srv in enumerate(self.prefill):
                self._holders[f"prefill-{i}"] = srv
            for i, srv in enumerate(self.decode):
                self._holders[f"decode-{i}"] = srv
            for holder_id, srv in self._holders.items():
                srv.bind_prefix_directory(
                    self.prefix_directory, holder_id,
                    peers=self._holders.get,
                    frame_pages=self._frame_pages)

    # -- routing ----------------------------------------------------------

    def _next(self, servers: list, which: str, prompt=None,
              tenant: Optional[str] = None) -> tuple:
        with self._lock:
            if self._closed:
                raise ServerClosedError("disagg coordinator is shut down")
            if which == "prefill":
                i = self._rr_prefill = (self._rr_prefill + 1) % len(servers)
            else:
                i = self._rr_decode = (self._rr_decode + 1) % len(servers)
        if prompt is not None:
            j = self._affine(servers, which, prompt, tenant)
            if j is not None:
                return j, servers[j]
        return i, servers[i]

    def _affine(self, servers: list, which: str, prompt,
                tenant: Optional[str]) -> Optional[int]:
        """Prefix-affinity override of round-robin: when the directory
        names a server in this role as holding the prompt's deepest
        cached chain AND that server is no more than `affinity_margin`
        pending requests busier than the least-loaded one, route to the
        holder — its prefill covers only the uncached suffix. Load
        always wins past the margin: a hot holder must not become a
        hotspot."""
        if self.prefix_directory is None:
            return None
        hit = self.prefix_directory.best_holder(
            np.asarray(prompt), tenant)
        if hit is None:
            return None
        mine = [int(h.split("-", 1)[1]) for h in hit["holders"]
                if h.startswith(which + "-")]
        mine = [j for j in mine if j < len(servers)]
        if not mine:
            return None
        loads = [s.pending() for s in servers]
        floor = min(loads)
        best = min((j for j in mine
                    if loads[j] <= floor + self._affinity_margin),
                   key=lambda j: loads[j], default=None)
        if best is None:
            return None
        with self._lock:
            self.affinity_routes += 1
        self.prefill[0].recorder.event(
            "affinity-route", role=which, holder=f"{which}-{best}",
            depth_pages=hit["depth"], pending=loads[best])
        return best

    @property
    def net(self):
        return self.prefill[0].net

    def generate(self, prompt_ids, n_tokens: int, *,
                 temperature: float = 0.0, seed: int = 0,
                 timeout: Optional[float] = None,
                 tenant: Optional[str] = None,
                 priority: str = "interactive") -> np.ndarray:
        deadline = None if timeout is None else time.monotonic() + timeout

        def remaining():
            if deadline is None:
                return None
            rem = deadline - time.monotonic()
            if rem <= 0:
                raise DeadlineExceededError(
                    "deadline expired during disagg handoff")
            return rem

        last_err: Optional[BaseException] = None
        avoid_decode = -1
        for round_ in range(2):  # ladder: one full re-prefill retry
            _, psrv = self._next(self.prefill, "prefill",
                                 prompt=prompt_ids, tenant=tenant)
            try:
                toks = psrv.generate(
                    np.asarray(prompt_ids), int(n_tokens),
                    temperature=temperature, seed=seed,
                    timeout=remaining(), tenant=tenant, priority=priority)
                return toks  # finished at prefill (n_tokens==1 / EOS)
            except SlotMigratedError as redirect:
                try:
                    return self._resume(psrv, redirect, remaining,
                                        avoid_decode)
                except DeadlineExceededError:
                    raise
                except ServingError as e:
                    last_err = e
                    avoid_decode = self._rr_decode
                    with self._lock:
                        self.fallbacks += 1
                    logger.warning(
                        "disagg transfer failed (%s: %s); %s", type(e).__name__,
                        e, "re-prefilling" if round_ == 0 else "giving up")
        raise KVTransferError(
            f"disagg handoff failed twice; last error: {last_err}")

    def _resume(self, psrv, redirect: SlotMigratedError, remaining,
                avoid_decode: int) -> np.ndarray:
        i, dsrv = self._next(self.decode, "decode")
        if i == avoid_decode and len(self.decode) > 1:
            i, dsrv = self._next(self.decode, "decode")
        if self._prefix_cluster:
            payload, skipped = self._fetch_framed(
                psrv, redirect.handoff_id, dsrv)
        else:
            payload = psrv.fetch_handoff(redirect.handoff_id)
            skipped = 0
        t0 = time.monotonic()
        try:
            tail = dsrv.resume_generate(payload, timeout=remaining())
        except KVTransferError:
            if not skipped:
                raise
            # the decode server's resident prefix vanished between the
            # depth probe and admit (eviction race) — one full re-fetch,
            # same handoff, before the outer ladder re-prefills
            payload, skipped = self._fetch_framed(
                psrv, redirect.handoff_id, dsrv, skip=0)
            tail = dsrv.resume_generate(payload, timeout=remaining())
        dt = time.monotonic() - t0
        try:
            psrv.commit_handoff(redirect.handoff_id)
        except ServingError:
            # commit is an optimization (early page reclaim); the lease
            # TTL sweep reclaims regardless, so a lost commit is logged
            # and absorbed — the request already has its tokens
            logger.warning("disagg commit_handoff(%s) failed; lease "
                           "sweep will reclaim", redirect.handoff_id)
        with self._lock:
            self.handoffs += 1
            self.transfer_bytes += payload_nbytes(payload)
            self.transfer_seconds += dt
            self.delta_pages_skipped += skipped
        return np.concatenate(
            [np.asarray(redirect.tokens, np.int32),
             np.asarray(tail, np.int32)])

    def _fetch_framed(self, psrv, handoff_id: str, dsrv,
                      skip: Optional[int] = None) -> tuple:
        """Delta-framed handoff fetch: probe the receiver for how many
        leading pages of this sequence's prefix chain it already holds,
        then pull only the remainder in bounded frames. Returns
        ``(payload, pages_skipped)``; checksums re-verify the
        reassembled payload at admit, so a bad frame is a typed refusal
        upstream of any binding."""
        header = psrv.fetch_handoff_header(
            handoff_id, frame_pages=self._frame_pages)
        if skip is None:
            already = int(header.get("pages_omitted", 0))
            have = dsrv.prefix_depth(header["prompt"],
                                     header.get("tenant"))
            skip = max(0, int(have) - already)
        if skip:
            base = int(header.get("pages_omitted", 0))
            header = psrv.fetch_handoff_header(
                handoff_id, skip_pages=skip,
                frame_pages=self._frame_pages)
            # the sender clamps skip to shipped-1 (the resume point's
            # page always ships); honor its clamp so the frame requests
            # and the skipped-page count both match the wire truth
            skip = int(header.get("pages_omitted", 0)) - base
        frames = [psrv.fetch_handoff_frame(
                      handoff_id, f, skip_pages=skip,
                      frame_pages=header["frame_pages"])
                  for f in range(int(header["n_frames"]))]
        return assemble_payload(header, frames), int(skip)

    # -- server-shaped facade (gateway RPC surface) ------------------------

    def predict(self, x, timeout: Optional[float] = None) -> np.ndarray:
        _, srv = self._next(self.prefill, "prefill")
        return srv.predict(x, timeout=timeout)

    def pending(self) -> int:
        return sum(s.pending() for s in self._servers)

    def stats(self) -> dict:
        with self._lock:
            mb = self.transfer_bytes / 1e6
            secs = self.transfer_seconds
            out = {
                "disagg": True,
                "prefill_replicas": len(self.prefill),
                "decode_replicas": len(self.decode),
                "handoffs": self.handoffs,
                "fallbacks": self.fallbacks,
                "kv_transfer_mbytes": mb,
                "kv_transfer_mbytes_per_sec": mb / secs if secs else 0.0,
                "prefix_cluster": self._prefix_cluster,
                "affinity_routes": self.affinity_routes,
                "delta_pages_skipped": self.delta_pages_skipped,
            }
        if self.prefix_directory is not None:
            out.update(self.prefix_directory.stats())
        out["prefill"] = [s.stats() for s in self.prefill]
        out["decode"] = [s.stats() for s in self.decode]
        return out

    def set_tenant_quota(self, tenant: str, rate=None, burst=None,
                         max_pages=None, weight=None) -> None:
        for s in self._servers:
            s.set_tenant_quota(tenant, rate=rate, burst=burst,
                               max_pages=max_pages, weight=weight)

    def flight_record(self) -> dict:
        return self.prefill[0].flight_record()

    def metrics_text(self, labels=None) -> str:
        return "".join(s.metrics_text(labels) for s in self._servers)

    def shutdown(self, drain_timeout: float = 10.0) -> bool:
        with self._lock:
            if self._closed:
                return True
            self._closed = True
        ok = True
        for s in self._servers:
            ok = s.shutdown(drain_timeout=drain_timeout) and ok
        return ok
