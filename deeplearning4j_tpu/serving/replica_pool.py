"""Replicated serving pool: health-probed replicas, failover routing,
hedged predicts, and zero-downtime rolling reload.

PRs 4–6 made ONE `ModelServer` robust — but one server is still one
failure domain: one breaker-open window, one wedged reload, or one
poisoned replica takes the whole service down. The reference stack's
answer is the `ParallelInference` scaleout tier (many model replicas
behind one dispatch point); `ReplicaPool` is that tier with the
robustness ladders of PRs 1–4 built in:

- **least-loaded routing** — every request goes to the healthy replica
  with the smallest queued+in-flight load (`ModelServer.pending()`),
  ties broken round-robin so equal replicas share evenly.
- **health probing + passive eviction** — a daemon probe loop serves a
  canary batch through every replica each `probe_interval` (a
  generation-only pool auto-arms a one-token generation canary from
  its first served `generate` instead — see `_probe_generate`). A
  replica
  is EVICTED (no new traffic) when its probe fails, its breaker is
  open, it hangs past `watchdog_timeout` (the probe runs under a
  watchdog — a wedged device step cannot wedge the probe loop), or
  passive error tracking sees `evict_threshold` consecutive request
  failures (SICKNESS only — queue-full and deadline sheds are load and
  time signals, and must not evict a healthy-but-busy replica into a
  pool-wide cascade). An evicted replica is re-admitted only after
  `readmit_successes` CONSECUTIVE probe passes — flapping replicas
  stay out.
- **request failover** — a retryable typed failure
  (`ServiceUnavailableError`, `InferenceFailedError`, a replica-level
  queue-full, `ReplicaEvictedError`) is transparently re-routed to
  another healthy replica, up to `max_failovers` re-routes per
  request. Non-retryable give-ups propagate typed:
  `DeadlineExceededError` (the request ran out of time — another
  replica cannot give it back) and the POOL-level
  `ServerOverloadedError` from the shared admission budget
  (`admission_budget` bounds total in-flight across the pool, so N
  replicas cannot hoard N full queues of doomed work).
- **hedged predicts** (`hedge=True`) — when the primary replica has
  not answered within the hedge delay (an EWMA-tracked p95-style
  latency bound, or an explicit `hedge_delay`), the request is FIRED
  AGAIN on a second healthy replica; the first finite result wins and
  the loser is absorbed (its result discarded, its failure noted).
  A single slow or silently-wedged replica costs one hedge, not one
  ruined tail latency.
- **rolling reload** — `rolling_reload(source)` swaps new weights in
  replica-at-a-time: drain (stop routing, wait for pending work) →
  reload through the PR-4 canary ladder (manifest verify + canary
  validation, old weights keep serving on rejection) → serve a probe
  successfully → re-admit, and only then the next replica. The other
  replicas carry the traffic, so a deploy is zero-downtime. If ANY
  replica's canary or post-reload probe fails, the WHOLE pool rolls
  back to the old weights (`ModelServer.restore_model`) — a bad
  checkpoint never takes traffic, not even on the replicas that
  individually accepted it. Quantized serving rides this ladder
  unchanged: replicas built with `quantize={"weights": ...}` quantize
  each reload candidate BEFORE canary validation and score it against
  the candidate's own full-precision outputs via the drift gates
  (`drift_gate={...}`, serving/quantize.py) — a quantization-broken
  candidate (clipped scales, outlier channels) is rejected exactly
  like a corrupt checkpoint and the pool rolls back free, with zero
  failed requests under live traffic (tests/test_quantize.py drill).
- **degraded mode** — with every replica evicted the pool serves the
  typed `ServiceUnavailableError` with `retry_after=probe_interval`
  and KEEPS PROBING: the moment replicas pass `readmit_successes`
  probes they rejoin and the pool recovers by itself.

`generate()` routes autoregressive generation (each replica's
lazily-built `DecodeEngine`) with the same least-loaded + failover
discipline — a generation request is seeded, so a failover re-send
recomputes identical tokens.

`stats()` aggregates per-replica `ModelServer.stats()` plus the pool
counters (`failovers`, `hedges_fired`, `hedge_wins`, `evictions`,
`readmissions`, `rolling_reloads`, `rollbacks`, `shed_overload`,
`shed_unavailable`) — the schema the gateway's `pool_stats` RPC
exposes and `tests/test_replica_pool.py` pins.

Chaos seams: `serving.chaos.ReplicaCrashInjector` (every step on one
replica raises — a dead process) and `ReplicaHangInjector` (steps
block — a wedged device) plug into a single replica's `infer_hooks`;
`ReloadCorruptionInjector` damages rolling-reload candidates per
replica. `tests/test_replica_pool.py` drives the ladders end to end.
"""
from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.serving import observability
from deeplearning4j_tpu.serving.kv_transfer import (
    KVTransferError,
    SlotMigratedError,
)
from deeplearning4j_tpu.serving.model_server import (
    AutoscaleError,
    DeadlineExceededError,
    InferenceFailedError,
    ModelServer,
    ServerClosedError,
    ServerOverloadedError,
    ServiceUnavailableError,
    ServingError,
)
from deeplearning4j_tpu.util.concurrency import assert_owned

logger = logging.getLogger("deeplearning4j_tpu")


class ReplicaEvictedError(ServingError):
    """The chosen replica was evicted between routing and dispatch (or
    found evicted mid-flight). Retryable: the pool re-routes it to
    another healthy replica under the request's failover budget."""


# transport-level faults a KV handoff edge can surface when the victim
# is a remote replica whose adapter is gone (RemoteReplica maps live
# wire failures into the ServingError taxonomy; these cover a torn-down
# client): the fallback ladder treats them exactly like typed failures
_TRANSFER_FAULTS = (ConnectionError, TimeoutError, OSError)


def _tag(err: BaseException, replica_id: int) -> BaseException:
    """Stamp the originating replica on a typed error so failover
    accounting — and the gateway error payload — can name it."""
    err.replica_id = replica_id
    return err


class _Replica:
    """Pool-side bookkeeping around one `ModelServer`."""

    __slots__ = ("id", "server", "state", "consecutive_failures",
                 "probe_successes", "evictions", "stale")

    def __init__(self, replica_id: int, server):
        self.id = replica_id
        self.server = server
        self.state = "healthy"  # healthy | evicted | draining
        self.consecutive_failures = 0  # passive error tracking
        self.probe_successes = 0       # consecutive, while evicted
        self.evictions = 0
        # weights behind the pool's (a best-effort reload of this
        # evicted replica failed during a rolling deploy): probes must
        # NOT re-admit it, or the pool would split between versions
        self.stale = False

    def load(self) -> int:
        return self.server.pending()


class ReplicaPool:
    """N `ModelServer` replicas behind one dispatch point (see module
    docstring). Construct from ready servers, or `ReplicaPool.from_net`
    to clone one fitted net across N fresh servers."""

    _RETRYABLE = (ServiceUnavailableError, InferenceFailedError,
                  ReplicaEvictedError)

    def __init__(self, replicas: Sequence, *,
                 probe_batch: Optional[np.ndarray] = None,
                 probe_interval: float = 1.0,
                 probe_timeout: Optional[float] = 5.0,
                 watchdog_timeout: float = 10.0,
                 evict_threshold: int = 3,
                 readmit_successes: int = 2,
                 max_failovers: int = 2,
                 admission_budget: Optional[int] = None,
                 hedge: bool = False,
                 hedge_delay: Optional[float] = None,
                 default_timeout: Optional[float] = None,
                 prefix_directory=None,
                 affinity_margin: int = 2):
        replicas = list(replicas)
        if not replicas:
            raise ValueError("a replica pool needs at least one replica")
        if probe_interval <= 0:
            raise ValueError("probe_interval must be > 0")
        if watchdog_timeout <= 0:
            raise ValueError("watchdog_timeout must be > 0")
        if evict_threshold < 1:
            raise ValueError("evict_threshold must be >= 1")
        if readmit_successes < 1:
            raise ValueError("readmit_successes must be >= 1")
        if max_failovers < 0:
            raise ValueError("max_failovers must be >= 0")
        self._replicas: List[_Replica] = [
            _Replica(i, srv) for i, srv in enumerate(replicas)]
        self._probe_batch = None if probe_batch is None \
            else np.asarray(probe_batch)  # guarded by: _lock
        self._probe_gen = None  # generation canary prompt; guarded by: _lock
        self.probe_interval = probe_interval
        self.probe_timeout = probe_timeout
        self.watchdog_timeout = watchdog_timeout
        self.evict_threshold = evict_threshold
        self.readmit_successes = readmit_successes
        self.max_failovers = max_failovers
        # shared admission budget: total in-flight requests across the
        # POOL. Default = the sum of replica queue capacities — the work
        # the pool could genuinely absorb with every replica healthy;
        # with replicas evicted the budget does NOT grow, so overload is
        # shed at the pool door instead of N queues' worth piling onto
        # the survivors
        self.admission_budget = (
            sum(getattr(r, "max_queue", 64) for r in replicas)
            if admission_budget is None else admission_budget)
        if self.admission_budget < 1:
            raise ValueError("admission_budget must be >= 1")
        self.hedge = hedge
        self.hedge_delay = hedge_delay
        self.default_timeout = default_timeout
        self._lock = threading.Lock()
        self._rr = itertools.count()  # round-robin tiebreak
        self._in_flight = 0  # guarded by: _lock
        self._closed = False  # guarded by: _lock
        # EWMA of successful predict latency + its absolute deviation:
        # the auto hedge delay is ewma + 4·dev, a cheap p95-style upper
        # bound that adapts to the model without a histogram
        self._lat_ewma = 0.05  # guarded by: _lock
        self._lat_dev = 0.025  # guarded by: _lock
        # pool counters (the stats()/gateway contract)
        self.served = 0  # guarded by: _lock
        self.failovers = 0  # guarded by: _lock
        self.hedges_fired = 0  # guarded by: _lock
        self.hedge_wins = 0  # guarded by: _lock
        self.evictions = 0  # guarded by: _lock
        self.readmissions = 0  # guarded by: _lock
        self.rolling_reloads = 0  # guarded by: _lock
        self.rollbacks = 0  # guarded by: _lock
        self.shed_overload = 0  # guarded by: _lock
        self.shed_unavailable = 0  # guarded by: _lock
        self.replicas_added = 0  # guarded by: _lock
        self.replicas_removed = 0  # guarded by: _lock
        self.migrations = 0  # guarded by: _lock
        self.migration_fallbacks = 0  # guarded by: _lock
        # cluster-global prefix cache: a shared PrefixDirectory makes a
        # prompt prefix prefilled on ANY replica fetchable (in-process
        # servers bind their engines as publishers+fetchers; remote
        # replicas publish via `refresh_prefix_directory` pull) and
        # steers dispatch toward holders within `affinity_margin`
        # pending requests of the least-loaded replica
        self._prefix_directory = prefix_directory
        self._affinity_margin = int(affinity_margin)
        self.affinity_routes = 0  # guarded by: _lock
        # observability: the pool keeps its own registry + recorder for
        # routing-layer views (failovers, hedges, probe verdicts,
        # evictions, reloads); each replica's ModelServer keeps its own
        # pair — `flight_record()` / `metrics_text()` merge both levels
        self.metrics = observability.MetricsRegistry()
        self.recorder = observability.FlightRecorder()
        self.metrics.register_stats("replica_pool", self.stats)
        self._pool_latency_hist = self.metrics.histogram(
            "replica_pool_predict_latency_ms")
        self.metrics.gauge("replica_pool_in_flight",
                           lambda: self._in_flight)
        self.metrics.gauge("replica_pool_healthy_replicas",
                           self.healthy_replicas)
        for rep in self._replicas:
            self._bind_prefix(rep)
        self._reload_lock = threading.Lock()
        self._probe_wake = threading.Event()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, daemon=True, name="replica-pool-probe")
        self._probe_thread.start()

    @classmethod
    def from_net(cls, net, n_replicas: int, *,
                 server_kwargs: Optional[dict] = None,
                 **pool_kwargs) -> "ReplicaPool":
        """Clone `net` across `n_replicas` fresh `ModelServer`s (each
        replica owns its own parameters, so a poisoned or hot-reloaded
        replica never aliases another's weights) and pool them."""
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        kw = dict(server_kwargs or {})
        nets = [net] + [net.clone() for _ in range(n_replicas - 1)]
        return cls([ModelServer(n, **kw) for n in nets], **pool_kwargs)

    # -- observability -----------------------------------------------------
    @property
    def net(self):
        """The first healthy replica's live model (read-only peek — the
        gateway keeps its model registry pointed at served weights)."""
        for rep in self._replicas:
            if rep.state == "healthy":
                return rep.server.net
        return self._replicas[0].server.net

    @property
    def n_replicas(self) -> int:
        return len(self._replicas)

    def healthy_replicas(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas if r.state == "healthy")

    def stats(self) -> dict:
        with self._lock:
            per_replica = {}
            healthy = 0
            for rep in self._replicas:
                healthy += rep.state == "healthy"
                s = rep.server.stats()
                s["state"] = rep.state
                s["consecutive_failures"] = rep.consecutive_failures
                s["evictions"] = rep.evictions
                s["stale"] = rep.stale
                # string keys: JSON object keys are strings, so the
                # in-process contract and the gateway `pool_stats` RPC
                # must agree — int keys would silently become "0"/"1"
                # over the wire
                per_replica[str(rep.id)] = s
            return {
                "n_replicas": len(self._replicas),
                "healthy_replicas": healthy,
                "pool_in_flight": self._in_flight,
                "admission_budget": self.admission_budget,
                "served": self.served,
                "failovers": self.failovers,
                "hedges_fired": self.hedges_fired,
                "hedge_wins": self.hedge_wins,
                "evictions": self.evictions,
                "readmissions": self.readmissions,
                "rolling_reloads": self.rolling_reloads,
                "rollbacks": self.rollbacks,
                "shed_overload": self.shed_overload,
                "shed_unavailable": self.shed_unavailable,
                "replicas_added": self.replicas_added,
                "replicas_removed": self.replicas_removed,
                "migrations": self.migrations,
                "migration_fallbacks": self.migration_fallbacks,
                "affinity_routes": self.affinity_routes,
                "directory_entries": (
                    0 if self._prefix_directory is None else
                    self._prefix_directory.stats()["directory_entries"]),
                "ewma_latency_ms": round(1e3 * self._lat_ewma, 3),
                "replicas": per_replica,
            }

    def flight_record(self) -> dict:
        """Two-level dump: the pool's own ring (routing decisions,
        failovers, hedges, probe verdicts, evictions, reload events)
        plus every replica's `ModelServer.flight_record()` (string
        replica-id keys — the same JSON-safe contract as `stats`)."""
        return {
            "pool": self.recorder.dump(),
            "replicas": {str(rep.id): rep.server.flight_record()
                         for rep in self._replicas},
        }

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    def metrics_text(self, labels=None) -> str:
        """One Prometheus text page for the whole pool: the pool's own
        instruments plus each replica's exposition labeled
        ``{replica="<id>"}`` (merged with caller `labels`, e.g. the
        gateway's ``{"model": name}``)."""
        parts = [self.metrics.exposition(labels=labels)]
        for rep in self._replicas:
            parts.append(rep.server.metrics.exposition(
                labels=dict(labels or {}, replica=str(rep.id))))
        return "".join(parts)

    def _shed_obs(self, trace, err: BaseException,
                  kind: str = "predict") -> None:
        """Pool-door shed / terminal failure: stamp the timeline, attach
        it to the typed error, pin it in the pool's failures ring."""
        decision = type(err).__name__
        trace.finish(decision)
        observability.attach_trace(err, trace)
        self.recorder.record(trace, decision, kind=kind)

    # -- cluster prefix cache ----------------------------------------------
    def _bind_prefix(self, rep: _Replica) -> None:
        """Join `rep`'s engine to the pool's prefix directory (no-op
        without a directory, or for adapters — remote replicas — that
        cannot bind an in-process object; those publish via
        `refresh_prefix_directory` instead)."""
        if self._prefix_directory is None:
            return
        bind = getattr(rep.server, "bind_prefix_directory", None)
        if bind is None:
            return
        bind(self._prefix_directory, f"replica-{rep.id}",
             peers=self._holder_peer)

    def _holder_peer(self, holder_id: str):
        """Resolve a directory holder id back to a live server — the
        peers hook engines use to fetch prefix pages. Only healthy
        replicas resolve: a fetch must not land on an evicted host."""
        try:
            rid = int(str(holder_id).rsplit("-", 1)[1])
        except (IndexError, ValueError):
            return None
        with self._lock:
            for rep in self._replicas:
                if rep.id == rid and rep.state == "healthy":
                    return rep.server
        return None

    def refresh_prefix_directory(self) -> int:
        """Pull-mode publication for replicas whose engines cannot push
        into the shared directory (remote processes behind the RPC
        adapter): snapshot each healthy replica's resident chains and
        publish them under its holder id, refreshing TTLs. Returns the
        number of chain keys published. In-process replicas publish
        synchronously on promotion; calling this for them is a harmless
        TTL refresh."""
        if self._prefix_directory is None:
            return 0
        with self._lock:
            reps = [(rep.id, rep.server) for rep in self._replicas
                    if rep.state == "healthy"]
        published = 0
        for rid, srv in reps:
            fn = getattr(srv, "prefix_chains", None)
            if fn is None:
                continue
            try:
                snap = fn()
            except ServingError:
                continue  # unreachable replica: its entries age out
            if not snap or not snap.get("chains"):
                continue
            self._prefix_directory.publish(
                snap["weight_version"], snap["page_size"],
                snap["chains"], f"replica-{rid}")
            published += len(snap["chains"])
        return published

    # -- routing -----------------------------------------------------------
    def _pick(self, exclude=(), prompt=None,
              tenant=None) -> Optional[_Replica]:
        """Least-loaded healthy replica, preferring ones not in
        `exclude` (already failed this request); when every healthy
        replica has been tried, re-allow them — a half-open breaker may
        admit the retry. None = no healthy replica at all. With a
        prefix directory bound and a `prompt` given, a replica holding
        the prompt's deepest cached chain wins the pick when its load
        is within `affinity_margin` of the least-loaded candidate —
        hot prefixes concentrate instead of replicating pool-wide."""
        with self._lock:
            healthy = [r for r in self._replicas if r.state == "healthy"]
            if not healthy:
                return None
            fresh = [r for r in healthy if r.id not in exclude]
            pool = fresh or healthy
        affine = self._affine(pool, prompt, tenant)
        if affine is not None:
            return affine
        # tiebreak on the INDEX within the candidate list (an id-based
        # key collapses to a constant when the surviving ids are
        # congruent mod the pool size, pinning tied traffic to one
        # replica)
        rr = next(self._rr)
        best = min(range(len(pool)),
                   key=lambda i: (pool[i].load(), (i - rr) % len(pool)))
        return pool[best]

    def _affine(self, pool, prompt, tenant) -> Optional[_Replica]:
        if self._prefix_directory is None or prompt is None:
            return None
        hit = self._prefix_directory.best_holder(
            np.asarray(prompt), tenant)
        if hit is None:
            return None
        ids = set()
        for holder in hit["holders"]:
            try:
                ids.add(int(str(holder).rsplit("-", 1)[1]))
            except (IndexError, ValueError):
                continue
        holders = [r for r in pool if r.id in ids]
        if not holders:
            return None
        loads = {r.id: r.load() for r in pool}
        floor = min(loads.values())
        best = min((r for r in holders
                    if loads[r.id] <= floor + self._affinity_margin),
                   key=lambda r: loads[r.id], default=None)
        if best is None:
            return None  # holder too busy: load beats affinity
        with self._lock:
            self.affinity_routes += 1
        self.recorder.event("affinity-route", replica=best.id,
                            depth_pages=hit["depth"],
                            pending=loads[best.id])
        return best

    def _degraded(self) -> ServiceUnavailableError:
        with self._lock:
            self.shed_unavailable += 1
        return ServiceUnavailableError(
            "no healthy replica in the pool (all evicted); probing "
            f"continues — retry in {self.probe_interval:.3f}s",
            retry_after=self.probe_interval)

    def _note_failure(self, rep: _Replica, err: BaseException) -> None:
        """Passive error tracking: consecutive request failures evict —
        the probe loop is not the only path off a sick replica."""
        with self._lock:
            rep.consecutive_failures += 1
            if rep.state == "healthy" and \
                    rep.consecutive_failures >= self.evict_threshold:
                self._evict_locked(rep, f"{type(err).__name__} x"
                                        f"{rep.consecutive_failures}")

    def _note_success(self, rep: _Replica,
                      latency: Optional[float] = None) -> None:
        """Reset the replica's failure streak; fold `latency` into the
        PREDICT latency EWMA when given. Generation successes pass None
        — a multi-second generate folded into the predict EWMA would
        blow up the auto hedge delay and the admission retry_after
        hints for millisecond predicts."""
        with self._lock:
            rep.consecutive_failures = 0
            if latency is not None:
                err = abs(latency - self._lat_ewma)
                self._lat_ewma = 0.8 * self._lat_ewma + 0.2 * latency
                self._lat_dev = 0.8 * self._lat_dev + 0.2 * err

    def _evict_locked(self, rep: _Replica, reason: str) -> None:
        assert_owned(self._lock, "ReplicaPool._evict_locked")
        if rep.state != "healthy":
            return
        rep.state = "evicted"
        rep.probe_successes = 0
        rep.evictions += 1
        self.evictions += 1
        if self._prefix_directory is not None:
            # an evicted host must stop attracting affinity routes and
            # fetches NOW, not a TTL later (directory has its own leaf
            # lock; it never calls back into the pool)
            self._prefix_directory.drop_holder(f"replica-{rep.id}")
        self.recorder.event("evict", replica=rep.id, reason=reason)
        logger.warning("replica pool: evicted replica %d (%s)",
                       rep.id, reason)

    def _restore_or_evict(self, rep: _Replica, old_net) -> bool:
        """Restore `rep` to `old_net`, treating a failed restore as a
        dead replica rather than a failed deploy: evict it (whatever
        state it is in — rollback reaches replicas mid-"draining") and
        mark it stale, because its weights are now UNKNOWN (the swap
        may have landed while the restore did not) and re-admitting it
        unreloaded could split the pool between versions. Remote
        replicas make this path real — a peer process can die between
        its reload and the pool-wide unwind. Returns True when the
        restore landed."""
        try:
            rep.server.restore_model(old_net)
            return True
        # graftlint: disable=typed-error  rollback edge: the restore's
        # own failure has no caller to type for — the recovery IS
        # evict+stale, and the deploy error already propagating must
        # not be displaced by this secondary one
        except BaseException as e:
            with self._lock:
                if rep.state != "evicted":
                    rep.state = "evicted"
                    rep.probe_successes = 0
                    rep.evictions += 1
                    self.evictions += 1
                    self.recorder.event(
                        "evict", replica=rep.id,
                        reason=f"rollback restore failed: "
                               f"{type(e).__name__}")
                rep.stale = True
            logger.warning(
                "replica pool: rollback restore on replica %d failed "
                "(%s) — evicted + stale until a later reload lands",
                rep.id, type(e).__name__)
            return False

    # -- admission ---------------------------------------------------------
    def _admit(self):
        with self._lock:
            if self._closed:
                raise ServerClosedError("replica pool is shut down")
            if self._in_flight >= self.admission_budget:
                self.shed_overload += 1
                retry = max(0.001, self._lat_ewma)
                raise ServerOverloadedError(
                    f"pool admission budget exhausted "
                    f"({self.admission_budget} in flight across "
                    f"{len(self._replicas)} replicas); retry in "
                    f"{retry:.3f}s", retry_after=retry)
            self._in_flight += 1

    def _release(self):
        with self._lock:
            self._in_flight -= 1

    # -- predict (failover + hedging) --------------------------------------
    def predict(self, x, timeout: Optional[float] = None) -> np.ndarray:
        """Serve one request through the pool: least-loaded routing,
        transparent failover on retryable typed failures (up to
        `max_failovers` re-routes), optional hedging. Raises the same
        typed `ServingError` family as `ModelServer.predict`; every
        replica-originated error carries `.replica_id` — and, with
        tracing on, `.trace_id`/`.trace`: the request's span timeline
        across pool routing and the replica's server/engine."""
        timeout = self.default_timeout if timeout is None else timeout
        deadline = None if timeout is None else time.monotonic() + timeout
        trace = observability.maybe_trace()
        t0 = time.monotonic()
        try:
            self._admit()
        except ServingError as e:
            self._shed_obs(trace, e)
            raise
        try:
            # bind the trace to this thread: the replica's ModelServer
            # (same synchronous chain) joins it instead of minting one
            with observability.use_trace(trace):
                out = self._predict_failover(np.asarray(x), deadline)
        except ServingError as e:
            self._shed_obs(trace, e)
            raise
        finally:
            self._release()
        trace.finish("served")
        self.recorder.record(trace, "served", kind="predict")
        self._pool_latency_hist.observe(1e3 * (time.monotonic() - t0))
        # auto-arm the probe batch from the first served predict (the
        # pool-level mirror of ModelServer's auto_canary): without it, a
        # replica evicted before ANY canary armed anywhere could never
        # prove recovery — probes would stay inconclusive forever and
        # degraded mode would need an operator after all
        if self._probe_batch is None:
            # copy outside the lock; first publication under it wins
            armed = np.array(np.asarray(x)[:1])
            with self._lock:
                if self._probe_batch is None:
                    self._probe_batch = armed
        return out

    def __call__(self, x, timeout: Optional[float] = None) -> np.ndarray:
        return self.predict(x, timeout=timeout)

    def _remaining(self, deadline: Optional[float]) -> Optional[float]:
        if deadline is None:
            return None
        rem = deadline - time.monotonic()
        if rem <= 0:
            raise DeadlineExceededError(
                "deadline expired while the pool was routing/failing "
                "over; request shed")
        return rem

    def _route_with_failover(self, attempt, prompt=None, tenant=None):
        """The one failover loop `predict` and `generate` share: pick a
        healthy replica, run `attempt(replica, tried)`, and on a
        retryable typed failure — `_RETRYABLE` sickness, or a
        REPLICA-level `ServerOverloadedError` (another replica may have
        room; the POOL-level budget shed happens in `_admit`, before
        this loop, and is terminal) — re-route to another replica up to
        `max_failovers` times. After exhaustion the ORIGINAL typed
        error propagates (an overloaded replica's `retry_after` hint
        survives to the client). `DeadlineExceededError` is terminal:
        another replica cannot give the time back."""
        tried: set = set()
        reroutes = 0
        while True:
            rep = self._pick(exclude=tried, prompt=prompt, tenant=tenant)
            if rep is None:
                raise self._degraded()
            try:
                return attempt(rep, tried)
            except (ServerOverloadedError, *self._RETRYABLE) as e:
                rid = getattr(e, "replica_id", rep.id)
                tried.add(rid)
                if reroutes >= self.max_failovers:
                    raise
                reroutes += 1
                with self._lock:
                    self.failovers += 1
                trace = observability.current_trace()
                if trace:
                    trace.event("failover", hop=reroutes, replica=rid,
                                error=type(e).__name__)
                self.recorder.event("failover", replica=rid,
                                    hop=reroutes,
                                    error=type(e).__name__)
                logger.warning(
                    "replica pool: failover %d/%d after %s on replica %d",
                    reroutes, self.max_failovers, type(e).__name__, rid)

    def _predict_failover(self, x, deadline) -> np.ndarray:
        def attempt(rep, tried):
            rem = self._remaining(deadline)
            if self.hedge:
                return self._hedged_dispatch(rep, x, rem, tried)
            return self._dispatch(rep, x, rem)

        return self._route_with_failover(attempt)

    def _call_replica(self, rep: _Replica, call, *,
                      track_latency: bool = True):
        """The per-attempt policy every routed call shares (predict,
        generate): health re-check at dispatch, typed error tagging,
        sickness-vs-load accounting, served counter. A policy change
        here changes every entry point at once. `track_latency=False`
        keeps generation out of the predict latency EWMA."""
        if rep.state != "healthy":  # evicted between pick and dispatch
            raise _tag(ReplicaEvictedError(
                f"replica {rep.id} evicted before dispatch"), rep.id)
        trace = observability.current_trace()
        if trace:
            trace.event("route", replica=rep.id, load=rep.load())
        t0 = time.monotonic()
        try:
            out = call()
        except self._RETRYABLE as e:
            # sickness: feeds passive eviction tracking
            self._note_failure(rep, e)
            raise _tag(e, rep.id)
        except ServingError as e:
            # queue-full / deadline: load and time signals, NOT
            # sickness — they must not evict a healthy-but-busy replica
            raise _tag(e, rep.id)
        self._note_success(rep, (time.monotonic() - t0) if track_latency
                           else None)
        with self._lock:
            self.served += 1
        return out

    def _dispatch(self, rep: _Replica, x, timeout) -> np.ndarray:
        return self._call_replica(
            rep, lambda: rep.server.predict(x, timeout=timeout))

    # -- hedging -----------------------------------------------------------
    def _auto_hedge_delay(self) -> float:
        if self.hedge_delay is not None:
            return self.hedge_delay
        with self._lock:
            return self._lat_ewma + 4.0 * self._lat_dev

    def _hedged_dispatch(self, primary: _Replica, x, timeout,
                         tried: set) -> np.ndarray:
        """Fire `primary`; if it has not answered within the hedge
        delay, fire one more healthy replica. First finite result wins
        (results are already non-finite-screened by the replica's
        `ModelServer`); the loser keeps running and is absorbed — its
        outcome is noted by passive tracking AT COMPLETION, inside the
        worker thread, so a replica that consistently loses hedges by
        failing slowly still accumulates toward eviction even though no
        waiter is left. Raises the PRIMARY's typed error when both
        fail."""
        if primary.state != "healthy":
            raise _tag(ReplicaEvictedError(
                f"replica {primary.id} evicted before dispatch"),
                primary.id)
        cond = threading.Condition()
        outcomes: List[tuple] = []  # (tag, replica, result, error, dt)
        # the caller's trace, re-bound inside each hedge lane's worker
        # thread (thread-locals do not cross the spawn) so both lanes'
        # server spans land on the ONE request timeline
        trace = observability.current_trace() or observability.NULL_TRACE

        def run(rep: _Replica, tag: str) -> None:
            t0 = time.monotonic()
            trace.event(f"{tag}-dispatch", replica=rep.id)
            try:
                with observability.use_trace(trace):
                    out = rep.server.predict(x, timeout=timeout)
            # graftlint: disable=typed-error  hedge worker: the failure
            # becomes this lane's outcome (classified retryable/fatal by
            # the racer below), never an unhandled thread death
            except BaseException as e:
                # note here, win or lose the race: sickness counts
                # toward eviction, queue-full/deadline are load/time
                # signals and do not
                if isinstance(e, self._RETRYABLE):
                    self._note_failure(rep, e)
                with cond:
                    outcomes.append((tag, rep, None, _tag(e, rep.id),
                                     time.monotonic() - t0))
                    cond.notify_all()
                return
            # failure-streak reset only — the WINNER's latency is folded
            # into the EWMA by the waiter; a loser that finally returns
            # after a 60 s wedge (the tail hedging exists to mask) must
            # not inflate the hedge delay / retry_after hints
            self._note_success(rep, None)
            with cond:
                outcomes.append((tag, rep, out, None,
                                 time.monotonic() - t0))
                cond.notify_all()

        threading.Thread(target=run, args=(primary, "primary"),
                         daemon=True).start()
        hedge_rep: Optional[_Replica] = None
        deadline = None if timeout is None else time.monotonic() + timeout
        hedge_at = time.monotonic() + max(0.0, self._auto_hedge_delay())
        with cond:
            while True:
                for tag, rep, out, err, dt in outcomes:
                    if err is None:
                        self._note_success(rep, dt)  # winner's latency
                        with self._lock:
                            self.served += 1
                            if tag == "hedge":
                                self.hedge_wins += 1
                        if tag == "hedge":
                            trace.event("hedge-win", replica=rep.id)
                            self.recorder.event("hedge-win",
                                                replica=rep.id)
                        return out
                errors = {tag: err
                          for tag, rep, out, err, dt in outcomes
                          if err is not None}
                if "primary" in errors and hedge_rep is None:
                    # primary failed before the hedge fired: plain
                    # failover handles it (cheaper than hedging a
                    # replica we know is sick)
                    raise errors["primary"]
                if "primary" in errors and hedge_rep is not None:
                    if "hedge" in errors:
                        # both down: raise the primary's error (the
                        # failover loop excludes both — tried grows by
                        # the hedge id)
                        tried.add(hedge_rep.id)
                        raise errors["primary"]
                    # primary failed while the hedge is still in
                    # flight: if an UNTRIED healthy replica exists,
                    # fail over to it now rather than block on the
                    # hedge — the hedge replica may itself be wedged
                    # (slowness is WHY it got hedged). The running
                    # hedge is absorbed at completion like any loser.
                    # With no fresh alternative the hedge is the
                    # request's best remaining shot — keep waiting
                    used = tried | {primary.id, hedge_rep.id}
                    with self._lock:
                        alt = any(r.state == "healthy"
                                  and r.id not in used
                                  for r in self._replicas)
                    if alt:
                        tried.add(hedge_rep.id)
                        raise errors["primary"]
                now = time.monotonic()
                if deadline is not None and now >= deadline:
                    raise _tag(DeadlineExceededError(
                        "deadline expired waiting on hedged replicas"),
                        primary.id)
                if hedge_rep is None and now >= hedge_at:
                    hedge_rep = self._pick(
                        exclude=tried | {primary.id})
                    if hedge_rep is not None \
                            and hedge_rep.id != primary.id:
                        with self._lock:
                            self.hedges_fired += 1
                        trace.event("hedge-fire", replica=hedge_rep.id,
                                    primary=primary.id)
                        self.recorder.event("hedge-fire",
                                            replica=hedge_rep.id,
                                            primary=primary.id)
                        threading.Thread(target=run,
                                         args=(hedge_rep, "hedge"),
                                         daemon=True).start()
                    else:
                        hedge_rep = None
                        hedge_at = now + self.probe_interval  # re-try later
                waits = [0.05]
                if deadline is not None:
                    waits.append(deadline - now)
                if hedge_rep is None:
                    waits.append(max(0.0, hedge_at - now) + 1e-4)
                cond.wait(max(1e-4, min(waits)))

    # -- generation --------------------------------------------------------
    # in-process replicas accept a streaming sink; the remote pool
    # overrides this False (a callable cannot cross the wire)
    supports_stream_sink = True

    def generate(self, prompt_ids, n_tokens: int, *,
                 temperature: float = 0.0, seed: int = 0,
                 timeout: Optional[float] = None,
                 tenant: Optional[str] = None,
                 priority: str = "interactive",
                 logprobs: int = 0,
                 on_token: Optional[Callable] = None):
        """Route one generation request (each replica's lazily-built
        `DecodeEngine`) with least-loaded routing + failover. Safe to
        re-route: generation is seeded, so a failover re-send
        recomputes identical tokens — and with an `on_token` stream
        sink attached, a re-send republishes cursors 1..k into the same
        ring where they deduplicate, so the consumer-visible stream
        stays append-only across failovers. Shares the pool admission
        budget with `predict`. `tenant`/`priority` ride through to the
        chosen replica's engine-level QoS doors."""
        timeout = self.default_timeout if timeout is None else timeout
        deadline = None if timeout is None else time.monotonic() + timeout
        # passed conditionally so adapters with the narrower pre-logprobs
        # signature keep working untouched
        genkw = {}
        if logprobs:
            genkw["logprobs"] = int(logprobs)
        if on_token is not None:
            genkw["on_token"] = on_token
        trace = observability.maybe_trace()
        try:
            self._admit()
        except ServingError as e:
            self._shed_obs(trace, e, kind="generate")
            raise
        try:
            def attempt(rep, tried):
                rem = self._remaining(deadline)
                try:
                    return self._call_replica(
                        rep, lambda: rep.server.generate(
                            prompt_ids, n_tokens, temperature=temperature,
                            seed=seed, timeout=rem, tenant=tenant,
                            priority=priority, **genkw),
                        track_latency=False)
                except SlotMigratedError as e:
                    # a redirect, not a failure: the replica exported
                    # this request's decode state under a lease (drain,
                    # scale-down) — fetch it and resume on a peer. A
                    # failed resume raises the retryable
                    # InferenceFailedError so THIS loop re-routes the
                    # full seeded generate (identical output, just the
                    # re-prefill cost)
                    return self._resume_migrated(rep, e, deadline, tried,
                                                 on_token=on_token)

            with observability.use_trace(trace):
                out = self._route_with_failover(attempt, prompt=prompt_ids,
                                                tenant=tenant)
        except ServingError as e:
            self._shed_obs(trace, e, kind="generate")
            raise
        finally:
            self._release()
        trace.finish("served")
        self.recorder.record(trace, "served", kind="generate")
        # auto-arm the generation probe from the first served generate
        # (the generation mirror of predict's probe_batch auto-arm): a
        # generation-ONLY pool never arms a predict canary at any layer,
        # so without this an evicted replica — e.g. one respawned by the
        # supervisor after a crash — could never prove recovery; probes
        # would stay inconclusive forever and the pool would sit in
        # degraded mode until an operator intervened
        if self._probe_gen is None:
            armed = np.array(np.asarray(prompt_ids))
            with self._lock:
                if self._probe_gen is None:
                    self._probe_gen = armed
        return out

    def _resume_migrated(self, victim: _Replica,
                         redirect: SlotMigratedError, deadline,
                         tried: set, on_token: Optional[Callable] = None):
        """Finish one migrated generation: fetch the leased KV payload
        from the exporting `victim`, resume it on a healthy peer, splice
        the victim's already-emitted tokens in front of the peer's tail.
        Any transfer/resume failure aborts the lease (the victim
        reclaims its pages immediately) and raises the retryable
        `InferenceFailedError` so the failover loop re-runs the full
        seeded generate — the degradation ladder's last rung, which
        reproduces the exact same output."""
        trace = observability.current_trace()
        handoff_id = redirect.handoff_id
        if trace:
            trace.event("migrate-redirect", replica=victim.id,
                        handoff_id=handoff_id,
                        emitted=len(redirect.tokens))
        self.recorder.event("migrate-redirect", replica=victim.id,
                            handoff_id=handoff_id)
        try:
            rem = self._remaining(deadline)
            payload = victim.server.fetch_handoff(handoff_id)
            peer = self._pick(exclude=tried | {victim.id})
            if peer is None or peer.id == victim.id:
                raise KVTransferError(
                    "no healthy peer to resume the migrated slot on")
            if trace:
                trace.event("migrate-resume", replica=peer.id,
                            handoff_id=handoff_id)
            reskw = {} if on_token is None else {"on_token": on_token}
            tail = self._call_replica(
                peer, lambda: peer.server.resume_generate(
                    payload, timeout=rem, **reskw),
                track_latency=False)
        except DeadlineExceededError:
            raise  # terminal: a peer cannot give the time back
        except (ServingError, *_TRANSFER_FAULTS) as e:
            # best-effort early reclaim: without it the victim's pages
            # stay leased until the TTL sweep
            try:
                victim.server.abort_handoff(handoff_id)
            except (ServingError, *_TRANSFER_FAULTS):
                logger.info(
                    "replica pool: abort_handoff %s unreachable after "
                    "failed resume; victim's lease sweep reclaims it",
                    handoff_id)
            with self._lock:
                self.migration_fallbacks += 1
            if trace:
                trace.event("migrate-fallback", replica=victim.id,
                            error=type(e).__name__)
            self.recorder.event("migrate-fallback", replica=victim.id,
                                handoff_id=handoff_id,
                                error=type(e).__name__)
            raise _tag(InferenceFailedError(
                f"migrated slot {handoff_id} could not be resumed "
                f"({type(e).__name__}: {e}); falling back to a full "
                "re-prefill on another replica"), victim.id) from e
        # success: resolve the lease so the victim frees the shipped
        # pages now instead of at TTL expiry (best-effort — expiry is
        # the backstop)
        try:
            victim.server.commit_handoff(handoff_id)
        except (ServingError, *_TRANSFER_FAULTS):
            logger.info(
                "replica pool: commit_handoff %s unreachable after "
                "successful resume; victim's lease sweep reclaims it",
                handoff_id)
        with self._lock:
            self.migrations += 1
        if trace:
            trace.event("migrate-done", handoff_id=handoff_id,
                        spliced=len(redirect.tokens))
        self.recorder.event("migrate-done", handoff_id=handoff_id)
        head = np.asarray(redirect.tokens, np.int32)
        if isinstance(tail, dict):
            # logprobs rode the handoff: splice the victim's per-step
            # entries in front of the peer's tail, mirroring the tokens
            head_lps = list(payload.get("logprob_values")
                            or [])[:len(redirect.tokens)]
            return {"tokens": np.concatenate(
                        [head, np.asarray(tail["tokens"],
                                          np.int32).reshape(-1)]),
                    "logprobs": head_lps + list(tail["logprobs"])}
        return np.concatenate([head, np.asarray(tail, np.int32).reshape(-1)])

    # -- health probing ----------------------------------------------------
    def _probe_input(self) -> Optional[np.ndarray]:
        """The batch probes serve: the configured/auto-armed
        `probe_batch`, else a canary BORROWED from any replica that
        armed one (all replicas serve the same model contract, so one
        replica's canary proves another's health) — an evicted replica
        gets no traffic to arm its own."""
        if self._probe_batch is not None:
            return self._probe_batch
        for rep in self._replicas:
            canary = getattr(rep.server, "_canary", None)
            if canary is not None:
                return canary
        return None

    def _probe_generate(self, rep: _Replica, prompt: np.ndarray,
                        timeout: Optional[float]) -> Optional[bool]:
        """Generation-canary probe: serve ONE greedy token through the
        replica's full generate path (admission, engine, non-finite
        screen — and, for a remote replica, the wire). Same
        three-valued contract as `ModelServer.probe`: a load/time shed
        is inconclusive, typed sickness is False, a served token is
        True. Used when no predict canary exists anywhere — a
        generation-only pool's replicas serve no predict traffic to
        arm one."""
        try:
            rep.server.generate(prompt, 1, temperature=0.0, seed=0,
                                timeout=timeout)
        except (ServerOverloadedError, DeadlineExceededError):
            return None  # load/time shed: not evidence of sickness
        except ServingError:
            return False
        return True

    def _probe_async(self, rep: _Replica):
        """Start one probe on a helper thread; returns (event, verdict)
        where verdict[0] lands as True (healthy), False (sick — incl.
        an exception out of the probe), or None (inconclusive: the
        probe was shed on load/time; see `ModelServer.probe`)."""
        verdict: List[Optional[bool]] = [False]
        done = threading.Event()
        batch = self._probe_input()
        with self._lock:
            gen_prompt = self._probe_gen if batch is None else None

        # a probe must ALWAYS carry a deadline: with timeout=None a
        # probe of a wedged replica would block its helper thread (and
        # hold its queue slot) forever — one leaked thread per cycle.
        # The watchdog window bounds how long a verdict is waited on,
        # so it is the natural fallback bound
        probe_timeout = self.probe_timeout \
            if self.probe_timeout is not None else self.watchdog_timeout

        def run():
            try:
                if batch is None and gen_prompt is not None:
                    verdict[0] = self._probe_generate(rep, gen_prompt,
                                                      probe_timeout)
                else:
                    verdict[0] = rep.server.probe(batch,
                                                  timeout=probe_timeout)
            # graftlint: disable=typed-error  probe worker: any failure
            # (hang, crash, typed shed) means one thing — unhealthy; the
            # verdict is the only channel out of this watchdog thread
            except BaseException:
                verdict[0] = False
            done.set()

        threading.Thread(target=run, daemon=True).start()
        return done, verdict

    def _probe(self, rep: _Replica) -> Optional[bool]:
        """One watchdogged probe: sick (False) if no verdict lands
        within `watchdog_timeout` — a replica wedged INSIDE a device
        step (where deadlines cannot reach) reads as hung, not slow."""
        done, verdict = self._probe_async(rep)
        if not done.wait(self.watchdog_timeout):
            logger.warning("replica pool: probe of replica %d hung past "
                           "watchdog_timeout=%.3fs", rep.id,
                           self.watchdog_timeout)
            return False
        return verdict[0]

    def _apply_probe_verdict(self, rep: _Replica,
                             ok: Optional[bool]) -> None:
        """Three-valued: True counts toward re-admission, False evicts
        (or resets the re-admission streak), None — the probe was shed
        on load — changes NOTHING: a busy replica proves nothing, and
        treating busyness as sickness would let a saturating burst
        evict healthy replicas and cascade the pool into degraded
        mode."""
        self.recorder.event("probe", replica=rep.id, state=rep.state,
                            verdict="inconclusive" if ok is None
                            else bool(ok))
        with self._lock:
            if rep.state == "draining" or ok is None:
                return
            if rep.state == "evicted":
                if ok:
                    if rep.stale:
                        # recovered, but on weights behind the pool's (a
                        # best-effort deploy reload failed on it):
                        # re-admitting would split the pool between
                        # versions — it stays out until reloaded
                        return
                    rep.probe_successes += 1
                    if rep.probe_successes >= self.readmit_successes:
                        rep.state = "healthy"
                        rep.consecutive_failures = 0
                        rep.probe_successes = 0
                        self.readmissions += 1
                        self.recorder.event("readmit", replica=rep.id)
                        logger.warning(
                            "replica pool: re-admitted replica %d after "
                            "%d consecutive probe successes", rep.id,
                            self.readmit_successes)
                else:
                    rep.probe_successes = 0
            elif not ok:
                self._evict_locked(rep, "probe failed")

    def _probe_loop(self) -> None:
        while True:
            self._probe_wake.wait(self.probe_interval)
            self._probe_wake.clear()
            with self._lock:
                if self._closed:
                    return
                targets = [r for r in self._replicas
                           if r.state != "draining"]
            probing = []
            for rep in targets:
                # breaker-open is sickness the pool need not probe to see
                if rep.state == "healthy" \
                        and rep.server.breaker.state == "open":
                    with self._lock:
                        self._evict_locked(rep, "breaker open")
                    continue
                probing.append((rep,) + self._probe_async(rep))
            # ONE shared watchdog window for the whole cycle: probes run
            # concurrently, so a single hung replica costs the cycle one
            # watchdog_timeout — not one per hung replica — and cannot
            # starve the other replicas' eviction/re-admission decisions
            cycle_deadline = time.monotonic() + self.watchdog_timeout
            for rep, done, verdict in probing:
                if not done.wait(max(0.0,
                                     cycle_deadline - time.monotonic())):
                    logger.warning(
                        "replica pool: probe of replica %d hung past "
                        "watchdog_timeout=%.3fs", rep.id,
                        self.watchdog_timeout)
                    self._apply_probe_verdict(rep, False)
                else:
                    self._apply_probe_verdict(rep, verdict[0])
            with self._lock:
                if self._closed:
                    return

    # -- rolling reload ----------------------------------------------------
    def rolling_reload(self, source, step: Optional[int] = None,
                       drain_timeout: float = 30.0) -> List[int]:
        """Replica-at-a-time canary-gated weight swap under live
        traffic. Per replica: DRAIN (routing stops, pending work
        finishes, bounded by `drain_timeout`) → `ModelServer.reload`
        (manifest verify + canary ladder) → serve a watchdogged probe
        successfully → re-admit; only then the next replica. The rest
        of the pool carries traffic throughout, so the deploy is
        zero-downtime.

        If any HEALTHY replica's reload or post-reload probe fails, the
        WHOLE pool rolls back to the old weights (every
        already-reloaded replica gets its old model restored via
        `ModelServer.restore_model`) and the typed error propagates —
        a bad checkpoint never splits the pool between versions.

        EVICTED replicas are not deploy gates — the pool serves without
        them, so a dead replica must not block deploying a good
        checkpoint. They get a BEST-EFFORT reload (no drain, no probe
        gate — they take no traffic): on success they carry the new
        weights into their eventual re-admission; on failure they are
        marked `stale` and the probe loop refuses to re-admit them
        until a later reload lands, so a replica recovering on old
        weights can never split the pool either. Returns the
        per-replica new model versions (healthy replicas only)."""
        with self._reload_lock:
            self.recorder.event("rolling-reload", decision="start")
            done: List[tuple] = []  # (replica, old_net, was_stale)
            newly_stale: List[_Replica] = []
            versions: List[int] = []
            try:
                for rep in list(self._replicas):
                    with self._lock:
                        evicted = rep.state == "evicted"
                        was_stale = rep.stale
                    if evicted:
                        try:
                            # .net inside the try: on a REMOTE replica it
                            # is a snapshot RPC, and a dead evicted
                            # replica failing to answer must stay
                            # best-effort, not abort the deploy
                            old_net = rep.server.net
                            rep.server.reload(source, step=step)
                        # graftlint: disable=typed-error  best-effort
                        # catch-up reload of an evicted replica: failure
                        # marks it stale for the next readmission probe
                        except BaseException as e:
                            with self._lock:
                                if not rep.stale:
                                    newly_stale.append(rep)
                                rep.stale = True
                            logger.warning(
                                "replica pool: best-effort reload of "
                                "evicted replica %d failed (%s) — "
                                "marked stale, barred from "
                                "re-admission until reloaded",
                                rep.id, type(e).__name__)
                            continue
                        with self._lock:
                            rep.stale = False
                        done.append((rep, old_net, was_stale))
                        continue
                    self._drain_replica(rep, drain_timeout)
                    swapped = False
                    try:
                        # .net inside the try: a remote replica answers
                        # the pre-deploy snapshot over the wire, and a
                        # wire failure here must release the drain (the
                        # finally below) instead of wedging the replica
                        # in "draining" forever
                        old_net = rep.server.net
                        versions.append(rep.server.reload(source,
                                                          step=step))
                        swapped = True
                        # three-valued: only a SICK verdict (False)
                        # fails the deploy — an inconclusive probe
                        # (None: shed on load, or no probe batch armed)
                        # matches reload()'s own canary-optional
                        # behavior
                        if self._probe(rep) is False:
                            raise InferenceFailedError(
                                f"replica {rep.id} failed its "
                                "post-reload probe on the candidate")
                    except BaseException as e:
                        if swapped:
                            self._restore_or_evict(rep, old_net)
                        raise _tag(e, rep.id)
                    finally:
                        # back on known weights either way: old on
                        # failure, probed candidate on success. Reset
                        # the passive failure streak like probe-loop
                        # re-admission does — failures noted against
                        # the PRE-deploy weights during the drain
                        # window must not count against the fresh ones
                        with self._lock:
                            if rep.state == "draining":
                                rep.state = "healthy"
                                rep.consecutive_failures = 0
                    done.append((rep, old_net, False))
            except BaseException:
                for rep, old_net, was_stale in reversed(done):
                    # per-replica: one replica dying mid-rollback (a
                    # remote peer can vanish between its reload and the
                    # pool-wide unwind) must not strand the OTHER
                    # already-reloaded replicas on the new weights —
                    # that would be the exact version split the
                    # rollback exists to prevent
                    if not self._restore_or_evict(rep, old_net):
                        continue
                    with self._lock:
                        # back on its PRE-deploy weights: for a replica
                        # that was already stale coming in, those are
                        # still behind the pool's — the bar stays
                        rep.stale = was_stale
                for rep in newly_stale:
                    with self._lock:
                        # its best-effort reload failed, but the whole
                        # pool just rolled back to the very weights it
                        # still holds — no version split, no bar
                        rep.stale = False
                with self._lock:
                    self.rollbacks += 1
                self.recorder.event("rolling-reload",
                                    decision="rolled-back",
                                    completed=len(done))
                logger.warning(
                    "replica pool: rolling reload FAILED after %d/%d "
                    "replicas — whole pool rolled back to old weights",
                    len(done), len(self._replicas))
                raise
            with self._lock:
                self.rolling_reloads += 1
            self.recorder.event("rolling-reload", decision="complete",
                                replicas=len(done))
            logger.warning("replica pool: rolling reload complete "
                           "across %d replicas", len(done))
            return versions

    def sync_net(self, net) -> None:
        """Propagate `net`'s weights to every replica that does not
        already serve that exact object (each gets its own clone —
        replicas never alias each other's parameters). The seam the
        gateway's `fit` RPC uses after training the installed net in
        place: replica 0 aliases it and sees the new weights, but the
        clones would keep serving pre-fit parameters and silently
        version-split the pool. Replicas synced here are on the pool's
        weights by construction, so any stale bar is lifted."""
        with self._reload_lock:
            for rep in self._replicas:
                if rep.server.net is not net:
                    rep.server.restore_model(net.clone())
                with self._lock:
                    rep.stale = False

    def _drain_replica(self, rep: _Replica, drain_timeout: float,
                       reason: str = "rolling-reload") -> None:
        """Stop routing to `rep` and wait (bounded) for its pending
        work to finish so the reload's canary/swap does not contend
        with live traffic. A drain timeout is not fatal — `reload`'s
        write lock still guarantees in-flight work finishes on the old
        model; the bound just caps how long a deploy can stall.

        Migrate-then-drain: when the victim can export decode state
        (`migrate_slots`) AND a healthy peer exists to resume on, its
        in-flight generations are exported as leased KV handoffs first —
        their waiters get the `SlotMigratedError` redirect and finish on
        a peer mid-sequence instead of holding the drain for their full
        tails. With no peer the export is skipped: a redirect nobody can
        resume would turn a finishable request into a fallback."""
        with self._lock:
            if rep.state == "healthy":
                rep.state = "draining"
            peers = sum(1 for r in self._replicas
                        if r.id != rep.id and r.state == "healthy")
        self.recorder.event("drain", replica=rep.id, reason=reason)
        moved = 0
        if peers >= 1 and hasattr(rep.server, "migrate_slots"):
            try:
                moved = rep.server.migrate_slots(wait=drain_timeout)
            except (ServingError, *_TRANSFER_FAULTS) as e:
                # the export is an optimization — a victim that cannot
                # export still drains the classic way (bounded wait)
                logger.info(
                    "replica pool: migrate-then-drain export failed on "
                    "replica %d (%s); draining without migration",
                    rep.id, type(e).__name__)
            else:
                if moved:
                    self.recorder.event("migrate-drain", replica=rep.id,
                                        slots=moved, reason=reason)
                    logger.info(
                        "replica pool: migrated %d in-flight slot(s) "
                        "off replica %d before drain (%s)",
                        moved, rep.id, reason)
        deadline = time.monotonic() + drain_timeout
        while rep.server.pending() and time.monotonic() < deadline:
            time.sleep(0.005)
        if moved:
            # the exported payloads live ON the victim until their
            # receivers fetch them; letting the caller dispose the
            # victim before that would turn every migration into a
            # fallback re-prefill. Wait (same bounded budget) until no
            # lease is unfetched — commit/abort can land after disposal
            # (the resume already holds the bytes; expiry is moot on a
            # dead sender), so fetched is the bar, not resolved
            while time.monotonic() < deadline:
                try:
                    gen = rep.server.stats().get("generation", {})
                except (ServingError, *_TRANSFER_FAULTS):
                    break  # victim unreachable: nothing left to wait on
                if not gen.get("handoffs_unfetched", 0):
                    break
                time.sleep(0.01)

    # -- elasticity (the autoscaler's seam) --------------------------------
    def add_replica(self, server, *, healthy: bool = False) -> int:
        """Attach one more replica to the live pool and return its id.

        The new replica enters EVICTED by default: it serves no traffic
        until the probe ladder re-admits it (`readmit_successes`
        consecutive probe passes) — scale-up never routes requests to a
        replica that has not proven itself. `healthy=True` skips the
        ladder for callers that already validated the server (tests,
        pre-warmed spares). The admission budget grows by the new
        replica's queue capacity, and the replica list is replaced
        copy-on-write so unlocked snapshot readers never see a
        half-mutated list."""
        with self._lock:
            if self._closed:
                raise ServerClosedError("replica pool is shut down")
            new_id = max((r.id for r in self._replicas), default=-1) + 1
            rep = _Replica(new_id, server)
            if not healthy:
                rep.state = "evicted"
            self._replicas = self._replicas + [rep]
            self.admission_budget += getattr(server, "max_queue", 64)
            self.replicas_added += 1
            self.recorder.event("add-replica", replica=new_id,
                                state=rep.state,
                                n_replicas=len(self._replicas))
        self._bind_prefix(rep)
        logger.info("replica pool: added replica %d (%s)", new_id,
                    rep.state)
        self._probe_wake.set()  # start the ladder immediately
        return new_id

    def remove_replica(self, replica_id: int, *,
                       drain_timeout: float = 30.0):
        """Detach one replica with the zero-failed-requests drain
        discipline and return its (still running) server: routing stops
        first, in-flight work on the victim finishes, THEN the replica
        leaves the pool. If the drain does not complete inside
        `drain_timeout` the removal is aborted — the replica is
        restored to rotation and `AutoscaleError` raised, because
        completing the removal would fail its in-flight requests. The
        caller owns the returned server's shutdown."""
        with self._lock:
            rep = next((r for r in self._replicas if r.id == replica_id),
                       None)
            if rep is None:
                raise ValueError(f"no replica with id {replica_id}")
            if len(self._replicas) <= 1:
                raise ValueError("cannot remove the last replica")
            prior_state = rep.state
        self._drain_replica(rep, drain_timeout, reason="scale-down")
        if rep.server.pending():
            with self._lock:
                if rep.state == "draining":
                    rep.state = prior_state
            raise AutoscaleError(
                f"replica {replica_id} still has {rep.server.pending()} "
                f"in-flight requests after a {drain_timeout:.1f}s drain; "
                "removal aborted (completing it would fail them)")
        with self._lock:
            self._replicas = [r for r in self._replicas
                              if r.id != replica_id]
            self.admission_budget = max(
                1, self.admission_budget
                - getattr(rep.server, "max_queue", 64))
            self.replicas_removed += 1
            self.recorder.event("remove-replica", replica=replica_id,
                                n_replicas=len(self._replicas))
        if self._prefix_directory is not None:
            self._prefix_directory.drop_holder(f"replica-{replica_id}")
        logger.info("replica pool: removed replica %d (drained clean)",
                    replica_id)
        return rep.server

    def set_tenant_quota(self, tenant: str, rate=None, burst=None,
                         max_pages=None, weight=None) -> None:
        """Fan one tenant's token-rate quota, KV page ceiling, and
        batch-lane fair-queueing weight out to every replica (the quota
        is enforced per decode engine; a pool-level budget would need
        cross-replica accounting the wire does not carry)."""
        with self._lock:
            replicas = list(self._replicas)
        for rep in replicas:
            rep.server.set_tenant_quota(tenant, rate=rate, burst=burst,
                                        max_pages=max_pages,
                                        weight=weight)

    # -- shutdown ----------------------------------------------------------
    def shutdown(self, drain_timeout: float = 10.0) -> bool:
        """Stop admission + probing, drain every replica concurrently
        against one shared `drain_timeout` budget. Returns True when
        every replica drained clean. Idempotent."""
        with self._lock:
            self._closed = True
        self._probe_wake.set()
        self._probe_thread.join(self.watchdog_timeout
                                + self.probe_interval + 5.0)
        results = {}
        threads = [
            threading.Thread(
                target=lambda r=rep: results.__setitem__(
                    r.id, r.server.shutdown(drain_timeout=drain_timeout)),
                daemon=True)
            for rep in self._replicas]
        for t in threads:
            t.start()
        for t in threads:
            t.join(drain_timeout + 10.0)
        return all(results.get(rep.id, False) for rep in self._replicas)
