"""Serving-tier observability: request tracing, metrics registry, and
flight recorder for the gateway → ReplicaPool → ModelServer →
DecodeEngine stack.

The serving layers (PRs 4-9) expose only point-in-time ``stats()``
counters — when a request sheds, fails over, hedges, or takes a p99
excursion there is no record of *where the time went* or *which layer
decided what*. This module closes that gap with three pieces, in the
spirit of Dapper-style always-on tracing:

- **Request tracing** (`Trace`/`Span`): a ``trace_id`` minted at the
  gateway (or at `ReplicaPool`/`ModelServer`/`DecodeEngine` entry for
  in-process callers) and threaded through every layer. Each layer
  records typed spans — queue-wait, admission, prefix-bind, per-chunk
  prefill, decode, speculative verify rounds, failover hops, hedge
  fire/win, reload drain — with `time.monotonic()` timestamps and the
  decision that ended them (``served`` / a typed-error class name /
  ``evicted`` / ``rolled-back``). Propagation is by thread-local
  (`use_trace`/`current_trace`) across the synchronous gateway → pool
  → server call chain, and by the request object (`_Request.trace`,
  `_GenRequest.trace`) across the executor/scheduler thread hop. The
  timeline rides responses and every `ServingError` (`attach_trace`),
  so `GatewayError` payloads carry it over the wire.

- **Metrics registry** (`MetricsRegistry`): lock-cheap counters,
  gauges (bindable to a callable) and bounded-bucket histograms, plus
  `register_stats` adapters that pull today's ad-hoc ``stats()`` dicts
  into one `snapshot()` and a Prometheus-style `exposition()` text
  format served by the gateway ``metrics`` RPC.

- **Flight recorder** (`FlightRecorder`): fixed-size rings of completed
  request timelines and scheduler events (admissions, retirements,
  page reclaims, probe verdicts, breaker transitions). Timelines that
  end in a typed failure are additionally pinned in a separate
  ``failures`` ring (the auto-snapshot: a burst of successes cannot
  push a postmortem out), dumpable via the gateway ``flight_record``
  RPC.

Hot-path discipline: every recording call is pure host-side arithmetic
(monotonic reads, int/str attrs, deque appends). Nothing here may
receive a device array — formatting one would block the scheduler
thread on the device stream, which is exactly the hazard the graftlint
``host-sync`` rule now also flags for recorder calls inside
``# graftlint: hot-loop`` scopes. The whole subsystem is kill-switched
by ``DL4J_TPU_NO_TRACING=1`` (spans become no-ops on the shared
`NULL_TRACE`, the recorder drops writes); `bench.py serve_generate`
prices the on-vs-off goodput delta as ``tracing_overhead_pct``.

Spans also name host phases in XLA/Perfetto traces: when
``DL4J_TPU_XLA_SPAN_ANNOTATIONS=1``, `Trace.span` wraps
`profiler.trace_annotation`, so a `jax.profiler` capture (e.g.
``bench.py --trace``) shows ``serve:prefill-chunk`` etc. interleaved
with the XLA op timeline. Off by default: annotations cost a context
manager per span even with no profiler attached.
"""
from __future__ import annotations

import os
import threading
import time
from bisect import bisect_right
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Sequence

__all__ = [
    "Counter", "FlightRecorder", "Gauge", "Histogram", "MetricsRegistry",
    "NULL_TRACE", "Span", "Trace", "attach_trace", "current_trace",
    "graft_remote_trace", "maybe_trace", "new_trace_id", "tracing_enabled",
    "use_trace", "wire_trace_context",
]

_KILL_ENV = "DL4J_TPU_NO_TRACING"
_XLA_ANNOTATE_ENV = "DL4J_TPU_XLA_SPAN_ANNOTATIONS"


def tracing_enabled() -> bool:
    """The kill switch: ``DL4J_TPU_NO_TRACING=1`` turns every trace
    into `NULL_TRACE` and every recorder write into a no-op — the
    baseline side of the in-bench ``tracing_overhead_pct`` A/B."""
    return os.environ.get(_KILL_ENV, "") not in ("1", "true", "yes")


def _xla_annotations_enabled() -> bool:
    return os.environ.get(_XLA_ANNOTATE_ENV, "") in ("1", "true", "yes")


def annotation(name: str):
    """A ``serve:<name>`` `profiler.trace_annotation` context when
    ``DL4J_TPU_XLA_SPAN_ANNOTATIONS=1``, else a free no-op — lets
    serving internals (draft mirrors, verify drivers) name themselves
    in a `jax.profiler` capture without paying for the context manager
    when no one is looking."""
    if _xla_annotations_enabled():
        from deeplearning4j_tpu.profiler import trace_annotation

        return trace_annotation(f"serve:{name}")
    return _NullContext()


def new_trace_id() -> str:
    return os.urandom(8).hex()


# -- spans / traces --------------------------------------------------------

class Span:
    """One typed interval on a request timeline. ``decision`` is how it
    ended: None (still open / informational event), ``"ok"``, or the
    layer's verdict (``"served"``, a typed-error class name,
    ``"evicted"``, ``"rolled-back"``)."""

    __slots__ = ("name", "t0", "t1", "decision", "attrs")

    def __init__(self, name: str, t0: float, t1: Optional[float] = None,
                 decision: Optional[str] = None,
                 attrs: Optional[dict] = None):
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.decision = decision
        self.attrs = attrs

    def to_dict(self) -> dict:
        d = {"name": self.name, "t0": self.t0,
             "t1": self.t1 if self.t1 is not None else self.t0}
        if self.decision is not None:
            d["decision"] = self.decision
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d


class Trace:
    """A request's causal timeline: a ``trace_id`` plus a bounded,
    thread-safe list of `Span`s (monotonic-clock timestamps — compare
    within a process, not across hosts). Spans are appended from
    several threads (gateway handler, pool hedges, server executor,
    engine scheduler); `to_dict` orders them by start time, which is
    causal order for the single request they all describe."""

    MAX_SPANS = 512

    __slots__ = ("trace_id", "decision", "_spans", "_lock", "_dropped",
                 "created_at", "created_mono")

    def __init__(self, trace_id: Optional[str] = None):
        self.trace_id = trace_id or new_trace_id()
        self.decision: Optional[str] = None
        # the trace's WALL-CLOCK ANCHOR: the same instant read on both
        # clocks. Span timestamps stay monotonic (immune to NTP steps),
        # and the (mono, wall) pair lets another process convert them —
        # remote spans are grafted into a local timeline by going
        # remote-monotonic → wall → local-monotonic through the two
        # anchors (`graft_remote_trace`)
        self.created_at = time.time()
        self.created_mono = time.monotonic()
        self._spans: List[Span] = []
        self._lock = threading.Lock()
        self._dropped = 0

    def _append(self, span: Span) -> None:
        with self._lock:
            if len(self._spans) >= self.MAX_SPANS:
                self._dropped += 1
                return
            self._spans.append(span)

    @contextmanager
    def span(self, name: str, **attrs):
        """Record ``name`` over the with-block. An escaping exception
        stamps the span's decision with the exception class name and
        re-raises; otherwise the decision is ``"ok"`` (callers may
        overwrite via the yielded span). With
        ``DL4J_TPU_XLA_SPAN_ANNOTATIONS=1`` the block is also wrapped
        in `profiler.trace_annotation`, naming the phase in any active
        `jax.profiler` capture."""
        sp = Span(name, time.monotonic(), attrs=attrs or None)
        self._append(sp)
        try:
            if _xla_annotations_enabled():
                from deeplearning4j_tpu.profiler import trace_annotation

                with trace_annotation(f"serve:{name}"):
                    yield sp
            else:
                yield sp
        except BaseException as e:
            sp.t1 = time.monotonic()
            sp.decision = type(e).__name__
            raise
        sp.t1 = time.monotonic()
        if sp.decision is None:
            sp.decision = "ok"

    def event(self, name: str, **attrs) -> None:
        """A point-in-time mark (zero-width span)."""
        self._append(Span(name, time.monotonic(), attrs=attrs or None))

    def add_timed(self, name: str, t0: float, t1: float,
                  decision: Optional[str] = None, **attrs) -> None:
        """Record an interval measured by the caller (e.g. queue-wait
        from a request's ``enqueued_at`` to its admission)."""
        self._append(Span(name, t0, t1, decision, attrs or None))

    def finish(self, decision: str) -> None:
        self.decision = decision

    def to_dict(self) -> dict:
        with self._lock:
            spans = sorted(self._spans, key=lambda s: s.t0)
            out = {"trace_id": self.trace_id,
                   "anchor": {"mono": self.created_mono,
                              "wall": self.created_at},
                   "spans": [s.to_dict() for s in spans]}
            if self.decision is not None:
                out["decision"] = self.decision
            if self._dropped:
                out["dropped_spans"] = self._dropped
            return out

    def __bool__(self) -> bool:
        return True


class _NullContext:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


class _NullTrace:
    """Shared no-op trace returned by `maybe_trace` when the kill
    switch is set — callers record unconditionally and pay one falsy
    method call instead of branching everywhere."""

    __slots__ = ()
    trace_id = None
    decision = None
    _null_ctx = _NullContext()

    def span(self, name, **attrs):
        return self._null_ctx

    def event(self, name, **attrs):
        pass

    def add_timed(self, name, t0, t1, decision=None, **attrs):
        pass

    def finish(self, decision):
        pass

    def to_dict(self):
        return None

    def __bool__(self):
        return False


NULL_TRACE = _NullTrace()

_tls = threading.local()


def current_trace() -> Optional[Trace]:
    """The trace bound to this thread by `use_trace` (None outside)."""
    return getattr(_tls, "trace", None)


@contextmanager
def use_trace(trace):
    """Bind `trace` to the current thread so downstream layers on the
    same synchronous call chain (`maybe_trace`) join it instead of
    minting their own — how the gateway's trace_id reaches the engine
    without threading a parameter through every signature."""
    prev = getattr(_tls, "trace", None)
    _tls.trace = trace
    try:
        yield trace
    finally:
        _tls.trace = prev


def maybe_trace(trace=None):
    """Resolve the trace for a request entering a serving layer: an
    explicit one wins, else the thread-local one (an upstream layer's),
    else mint a fresh `Trace` — or `NULL_TRACE` when kill-switched."""
    t = trace if trace is not None else current_trace()
    if t is not None:
        return t
    return Trace() if tracing_enabled() else NULL_TRACE


def attach_trace(err: BaseException, trace) -> None:
    """Stamp ``trace_id`` and the serialized timeline onto a
    `ServingError` (best-effort — same idiom as the pool's replica_id
    tagging) so in-process callers and the gateway error payload both
    carry the timeline. A batch-shared exception instance can be
    stamped by several waiter threads; last writer wins, and each
    writer's timeline names the same batch, so any of them serves the
    postmortem."""
    if not trace:
        return
    try:
        err.trace_id = trace.trace_id
        err.trace = trace.to_dict()
    # graftlint: disable=typed-error  best-effort attachment: a slotted
    # or exotic exception type that rejects new attributes must not turn
    # error delivery itself into a second failure
    except Exception:
        pass


# -- cross-process trace propagation ---------------------------------------

def wire_trace_context(trace) -> Optional[dict]:
    """The trace context a gateway client sends alongside a request so
    the remote server JOINS the caller's trace instead of minting its
    own: the trace_id plus the LOCAL wall-clock anchor (informational —
    the remote side answers with its own anchor, which is what the
    caller grafts by). None for no/null traces: the request travels
    context-free and the remote side keeps its historical minting."""
    if not trace or getattr(trace, "trace_id", None) is None:
        return None
    ctx = {"trace_id": trace.trace_id}
    mono = getattr(trace, "created_mono", None)
    wall = getattr(trace, "created_at", None)
    if mono is not None and wall is not None:
        ctx["anchor"] = {"mono": mono, "wall": wall}
    return ctx


def graft_remote_trace(trace, remote: Optional[dict], **attrs) -> int:
    """Splice a REMOTE process's serialized trace (`Trace.to_dict()`
    shipped over the gateway wire) into the local `trace` as spans on
    the local monotonic clock, so a cross-process request still reads
    as ONE causally-ordered timeline in the flight recorder.

    Clock conversion rides the wall-clock anchors both traces carry:
    ``local_t = remote_t + ((r_wall - r_mono) - (l_wall - l_mono))`` —
    remote-monotonic → shared wall time → local-monotonic. Accurate to
    the hosts' wall-clock skew (NTP-level; fine for ms-scale serving
    spans — docs/observability.md states the caveat). Every grafted
    span carries ``remote=True`` plus caller `attrs` (e.g. the replica
    endpoint), and the remote decision lands as a zero-width
    ``remote-decision`` event. Returns the number of spans grafted;
    anchorless remote payloads graft 0 spans but still record one
    ``remote-trace`` marker naming the remote trace_id."""
    if not trace or not isinstance(remote, dict):
        return 0
    r_anchor = remote.get("anchor") or {}
    l_mono = getattr(trace, "created_mono", None)
    l_wall = getattr(trace, "created_at", None)
    r_mono, r_wall = r_anchor.get("mono"), r_anchor.get("wall")
    if None in (l_mono, l_wall, r_mono, r_wall):
        trace.event("remote-trace", remote_trace_id=remote.get("trace_id"),
                    anchorless=True, **attrs)
        return 0
    offset = (r_wall - r_mono) - (l_wall - l_mono)
    grafted = 0
    for sp in remote.get("spans", ()):
        if not isinstance(sp, dict) or "t0" not in sp:
            continue
        sp_attrs = dict(sp.get("attrs") or {})
        sp_attrs.update(attrs)
        sp_attrs["remote"] = True
        trace.add_timed(sp.get("name", "remote"),
                        sp["t0"] + offset,
                        sp.get("t1", sp["t0"]) + offset,
                        sp.get("decision"), **sp_attrs)
        grafted += 1
    decision = remote.get("decision")
    if decision is not None:
        trace.event("remote-decision", decision=decision,
                    remote_trace_id=remote.get("trace_id"), **attrs)
    return grafted


# -- metrics registry ------------------------------------------------------

class Counter:
    """Monotonic counter. One uncontended lock per `inc` — cheap
    against the ~ms-scale operations it counts."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self):
        return self._v


class Gauge:
    """Point-in-time value: either `set()` by the owner or bound to a
    zero-argument callable sampled at snapshot time (how queue depth /
    pages-in-use track the live scheduler state without a write on
    every transition)."""

    __slots__ = ("name", "_v", "_fn")

    def __init__(self, name: str, fn: Optional[Callable[[], float]] = None):
        self.name = name
        self._v = 0.0
        self._fn = fn

    def set(self, v: float) -> None:
        self._v = v

    @property
    def value(self):
        if self._fn is not None:
            try:
                return self._fn()
            # graftlint: disable=typed-error  a gauge reads live
            # component state that may be mid-teardown; a scrape must
            # report None, never propagate the component's failure
            except Exception:
                return None
        return self._v


#: upper bounds (ms) for latency histograms — bounded cardinality by
#: construction, wide enough for queue-wait through whole-generate.
DEFAULT_LATENCY_BUCKETS_MS = (
    1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0)


class Histogram:
    """Fixed-bucket histogram (`buckets` are inclusive upper bounds;
    one implicit +Inf bucket). `observe` is a bisect plus two adds
    under an uncontended lock.

    **p99-excursion auto-dump** (`enable_excursion`): an observation
    landing past the histogram's own live `quantile` bound fires the
    configured hook OUTSIDE the lock with ``(value, bound, trace)`` —
    the engine wires this to `FlightRecorder.pin`, so the excursion
    request's full timeline lands in the failures ring the moment the
    tail event happens, instead of being reconstructed from counters
    after the fact. The bound is computed from the bucket counts
    BEFORE the new observation (an excursion cannot raise the bar it
    is judged against) and only once `min_count` observations exist
    (a cold histogram's 'p99' is noise)."""

    __slots__ = ("name", "buckets", "_counts", "_count", "_sum", "_lock",
                 "_exc_quantile", "_exc_min_count", "_exc_hook")

    def __init__(self, name: str,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS):
        self.name = name
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.buckets) + 1)
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()
        self._exc_quantile = 0.99
        self._exc_min_count = 50
        self._exc_hook: Optional[Callable] = None

    def enable_excursion(self, quantile: float = 0.99,
                         min_count: int = 50,
                         hook: Optional[Callable] = None) -> None:
        """Arm the excursion hook: observations past the live
        `quantile` bound (once `min_count` observations exist) call
        ``hook(value, bound, trace)`` outside the histogram lock."""
        if not 0.0 < quantile < 1.0:
            raise ValueError("excursion quantile must be in (0, 1)")
        if min_count < 1:
            raise ValueError("excursion min_count must be >= 1")
        self._exc_quantile = float(quantile)
        self._exc_min_count = int(min_count)
        self._exc_hook = hook

    def _quantile_bound_locked(self, q: float) -> Optional[float]:
        """Smallest bucket upper bound covering quantile `q` of the
        recorded observations — None when the quantile falls in the
        implicit +Inf bucket (no finite bar to judge against)."""
        if not self._count:
            return None
        target = q * self._count
        cum = 0
        for bound, cnt in zip(self.buckets, self._counts):
            cum += cnt
            if cum >= target:
                return bound
        return None

    def quantile_bound(self, q: float) -> Optional[float]:
        """Public read of the live bucket-quantile bound (telemetry,
        tests, the bench's excursion line)."""
        with self._lock:
            return self._quantile_bound_locked(q)

    def observe(self, v: float, trace=None) -> None:
        i = bisect_right(self.buckets, v)
        fire_bound = None
        with self._lock:
            if self._exc_hook is not None \
                    and self._count >= self._exc_min_count:
                bound = self._quantile_bound_locked(self._exc_quantile)
                if bound is not None and v > bound:
                    fire_bound = bound
            self._counts[i] += 1
            self._count += 1
            self._sum += v
        if fire_bound is not None:
            # outside the lock: the hook appends to recorder rings and
            # must not serialize every concurrent observe behind it
            self._exc_hook(v, fire_bound, trace)

    def snapshot(self) -> dict:
        with self._lock:
            return {"buckets": list(self.buckets),
                    "counts": list(self._counts),
                    "count": self._count,
                    "sum": round(self._sum, 3)}


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _flatten_numeric(prefix: str, obj, out: List) -> None:
    if isinstance(obj, bool):
        out.append((prefix, int(obj)))
    elif isinstance(obj, (int, float)):
        out.append((prefix, obj))
    elif isinstance(obj, dict):
        for k, v in obj.items():
            _flatten_numeric(f"{prefix}_{_sanitize(str(k))}", v, out)
    # strings / lists / None are identity or timeline data, not metrics


class MetricsRegistry:
    """Named counters/gauges/histograms plus ``stats()`` adapters.

    `snapshot()` is the one structured view — first-class instruments
    under ``counters``/``gauges``/``histograms`` and every registered
    component's ad-hoc ``stats()`` dict under ``components`` (the
    schema the contract test in `tests/test_observability.py` pins).
    `exposition()` renders the same data as Prometheus text; numeric
    leaves of component stats become gauges with underscore-joined
    paths, so today's counters are scrapeable without re-plumbing each
    one as a first-class instrument."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._stats_fns: Dict[str, Callable[[], dict]] = {}

    # get-or-create: layers can share one registry without coordinating
    # construction order
    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str,
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, fn)
            elif fn is not None:
                g._fn = fn
            return g

    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS
                  ) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, buckets)
            return h

    def register_stats(self, name: str, fn: Callable[[], dict]) -> None:
        """Adopt a component's existing ``stats()`` provider under
        ``components[name]`` in the snapshot."""
        with self._lock:
            self._stats_fns[name] = fn

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._histograms)
            stats_fns = dict(self._stats_fns)
        components = {}
        for name, fn in stats_fns.items():
            try:
                components[name] = fn()
            # graftlint: disable=typed-error  a dying component must
            # not take the whole metrics snapshot down; its slot names
            # the failure instead
            except Exception as e:
                components[name] = {"error": type(e).__name__}
        return {
            "counters": {n: c.value for n, c in counters.items()},
            "gauges": {n: g.value for n, g in gauges.items()},
            "histograms": {n: h.snapshot() for n, h in hists.items()},
            "components": components,
        }

    def exposition(self, namespace: str = "dl4j",
                   labels: Optional[dict] = None) -> str:
        """Prometheus-style text exposition of `snapshot()`."""
        snap = self.snapshot()
        lab = ""
        if labels:
            lab = "{" + ",".join(
                f'{_sanitize(str(k))}="{v}"'
                for k, v in sorted(labels.items())) + "}"
        lines: List[str] = []

        def emit(name, kind, value):
            # a series may embed its OWN labels in the registered name
            # (e.g. 'x{tp_rank="0"}' — per-shard gauges register one
            # series per rank); split them off before sanitizing and
            # merge with the call-level labels so the exposition stays
            # one metric name with several labelled series
            own = ""
            if "{" in name:
                name, own = name.split("{", 1)
                own = own.rstrip("}")
            full = f"{namespace}_{_sanitize(name)}"
            merged = lab
            if own:
                merged = lab[:-1] + "," + own + "}" if lab \
                    else "{" + own + "}"
            lines.append(f"# TYPE {full} {kind}")
            lines.append(f"{full}{merged} {value}")

        for name, v in sorted(snap["counters"].items()):
            emit(name, "counter", v)
        for name, v in sorted(snap["gauges"].items()):
            if v is not None:
                emit(name, "gauge", v)
        for name, h in sorted(snap["histograms"].items()):
            full = f"{namespace}_{_sanitize(name)}"
            lines.append(f"# TYPE {full} histogram")
            cum = 0
            for bound, cnt in zip(h["buckets"], h["counts"]):
                cum += cnt
                if labels:
                    le = lab[:-1] + f',le="{bound}"}}'
                else:
                    le = f'{{le="{bound}"}}'
                lines.append(f"{full}_bucket{le} {cum}")
            if labels:
                le = lab[:-1] + ',le="+Inf"}'
            else:
                le = '{le="+Inf"}'
            lines.append(f"{full}_bucket{le} {h['count']}")
            lines.append(f"{full}_sum{lab} {h['sum']}")
            lines.append(f"{full}_count{lab} {h['count']}")
        flat: List = []
        for comp, stats in sorted(snap["components"].items()):
            _flatten_numeric(_sanitize(comp), stats, flat)
        for name, v in flat:
            emit(f"stats_{name}", "gauge", v)
        return "\n".join(lines) + "\n"


# -- flight recorder -------------------------------------------------------

class FlightRecorder:
    """Bounded rings of (a) completed request timelines, (b) timelines
    that ended in a typed failure (the auto-snapshot ring: success
    traffic cannot push a postmortem out before anyone looks), and
    (c) scheduler/control-plane events. Traces are stored by reference
    and serialized at `dump()` time, so spans recorded after the
    initial `record` (e.g. a pool-level failover wrapping a replica's
    already-recorded attempt) still appear in the dump.

    Sizing: the defaults (256 requests / 64 failures / 1024 events)
    hold a few seconds of saturated decode traffic — see
    docs/observability.md for the arithmetic. All writes are O(1) deque
    appends and respect the `tracing_enabled` kill switch."""

    def __init__(self, capacity: int = 256, failure_capacity: int = 64,
                 event_capacity: int = 1024):
        self._lock = threading.Lock()
        self._requests = deque(maxlen=capacity)
        self._failures = deque(maxlen=failure_capacity)
        self._events = deque(maxlen=event_capacity)

    def record(self, trace, decision: str, kind: str = "request",
               **attrs) -> None:
        """Ring a completed request timeline. ``decision`` is the
        verdict that ended it (``served`` or a typed-error class name);
        non-served timelines are also pinned in the failures ring."""
        if not trace or not tracing_enabled():
            return
        entry = {"kind": kind, "decision": decision,
                 "wall_time": time.time(), "trace": trace}
        if attrs:
            entry["attrs"] = attrs
        with self._lock:
            self._requests.append(entry)
            if decision != "served":
                self._failures.append(entry)

    def pin(self, trace, decision: str, kind: str = "excursion",
            **attrs) -> None:
        """Pin a request timeline in the FAILURES ring without a
        request completion — the p99-excursion auto-dump: the latency
        histogram's excursion hook calls this the moment an
        observation lands past the quantile bound, so the tail
        request's full span timeline survives success traffic (the
        failures ring is the one a burst of served requests cannot
        push a postmortem out of). Also rings a matching control-plane
        event carrying the trace id."""
        if not trace or not tracing_enabled():
            return
        entry = {"kind": kind, "decision": decision,
                 "wall_time": time.time(), "trace": trace}
        if attrs:
            entry["attrs"] = attrs
        with self._lock:
            self._failures.append(entry)
        self.event(kind, decision=decision,
                   trace_id=getattr(trace, "trace_id", None), **attrs)

    def event(self, kind: str, **attrs) -> None:
        """Ring a scheduler/control-plane event (admission, retirement,
        page reclaim, probe verdict, breaker transition, chaos)."""
        if not tracing_enabled():
            return
        e = {"kind": kind, "t": time.monotonic(), "wall_time": time.time()}
        if attrs:
            e.update(attrs)
        with self._lock:
            self._events.append(e)

    @staticmethod
    def _ser(entry: dict) -> dict:
        out = {k: v for k, v in entry.items() if k != "trace"}
        tr = entry["trace"]
        out["trace"] = tr.to_dict() if hasattr(tr, "to_dict") else tr
        return out

    def dump(self) -> dict:
        with self._lock:
            requests = list(self._requests)
            failures = list(self._failures)
            events = list(self._events)
        return {
            "requests": [self._ser(e) for e in requests],
            "failures": [self._ser(e) for e in failures],
            "events": events,
            "capacity": {"requests": self._requests.maxlen,
                         "failures": self._failures.maxlen,
                         "events": self._events.maxlen},
        }


# -- stats-schema contracts ------------------------------------------------
# The single source of truth for the key sets the serving layers'
# ``stats()`` dicts promise (tests and external scrapers rely on them;
# the gateway `server_stats`/`pool_stats` RPCs return these dicts
# verbatim). Layers may ADD keys; removing or renaming one is a
# breaking change and must update these sets plus
# docs/observability.md. Pinned in one place by
# tests/test_observability.py via `MetricsRegistry.snapshot()`.

MODEL_SERVER_STATS_KEYS = frozenset({
    "served", "batches", "batch_fill_pct", "shed_overload",
    "shed_deadline", "shed_unavailable", "failures", "reloads",
    "reload_rejections", "breaker_state", "breaker_opens",
    "model_version", "queued", "in_flight", "queue_depth",
    "ewma_latency_ms",
    # quantized serving tier: weight precision actually serving (32 /
    # 16 / 8) and the drift-gate verdict counters — all numeric, so
    # `_flatten_numeric` carries them into the Prometheus exposition
    "weight_bits", "drift_gate_checks", "drift_gate_failures",
})

DECODE_ENGINE_STATS_KEYS = frozenset({
    "submitted", "served", "shed_overload", "shed_out_of_pages",
    "shed_deadline", "shed_unavailable", "failures", "prefills",
    "prefill_chunks", "decode_steps", "tokens_generated",
    "slot_occupancy_pct", "n_slots", "active_slots", "queued", "swaps",
    "max_len", "page_size", "pool_pages", "pages_in_use",
    "pages_in_use_peak", "queued_page_demand", "max_queued_pages",
    # quantized KV tier: bits per cache element actually allocated
    # (8 = int8 pools, else the compute dtype's width) and the
    # per-generated-token KV byte cost including the scale sidecar
    "kv_quant_bits", "kv_bytes_per_token",
    # tensor-parallel tier: mesh degree (1 = single-device engine, so
    # capacity dashboards never branch on key presence) and the
    # per-shard slice of kv_bytes_per_token — each device's actual
    # per-token KV residency under head sharding
    "tp_degree", "tp_kv_bytes_per_token_per_shard",
    # multi-tenant QoS tier: batch-lane preemptions, SLO-infeasible
    # sheds, quota rejections, and the per-tenant sub-dicts (keyed by
    # tenant name; each value pins TENANT_STATS_KEYS)
    "preemptions", "slo_sheds", "shed_quota", "tenants",
    # KV transfer tier (`serving.kv_transfer`): page-quota sheds, slots
    # exported/imported as leased handoffs, lease resolutions by
    # outcome, live leases, and total payload bytes shipped out
    "shed_page_quota", "migrations_out", "migrations_in",
    "handoffs_committed", "handoffs_aborted", "handoffs_expired",
    "handoff_leases", "handoffs_unfetched", "kv_transfer_bytes",
    # cluster prefix cache tier (`serving.prefix_directory`): fetches
    # landed vs degraded to cold prefill, wire bytes/latency of prefix
    # page pulls, chains exported to peers, and prompt tokens whose
    # prefill was skipped via pages fetched from ANOTHER host (the
    # cluster-level hit ratio next to the local prefix_hit_tokens_pct)
    "prefix_fetches", "prefix_fetch_fallbacks", "prefix_fetch_bytes",
    "prefix_fetch_ms", "prefix_exports", "cluster_prefix_hit_tokens",
    "cluster_prefix_hit_tokens_pct",
})

# Per-tenant counters nested under DecodeEngine ``stats()["tenants"]``
# — one dict per tenant name the engine has seen (quota'd or not).
TENANT_STATS_KEYS = frozenset({
    "submitted", "served", "shed_quota", "tokens_generated",
    "preemptions", "rate", "burst", "tokens",
    # KV page quota tier: page-ceiling rejections, the configured
    # ceiling (None = unlimited), and the tenant's live page footprint
    "shed_page_quota", "max_pages", "pages_reserved",
    # batch-lane weighted-fair queueing: this tenant's stride share
    # (1.0 default; weight 2 earns twice the admitted span of weight 1)
    "weight",
})

REPLICA_POOL_STATS_KEYS = frozenset({
    "n_replicas", "healthy_replicas", "pool_in_flight",
    "admission_budget", "served", "failovers", "hedges_fired",
    "hedge_wins", "evictions", "readmissions", "rolling_reloads",
    "rollbacks", "shed_overload", "shed_unavailable", "ewma_latency_ms",
    "replicas",
    # elasticity tier: replicas added/drained-out by the autoscaler (or
    # an operator) since construction
    "replicas_added", "replicas_removed",
    # live decode-state migration: redirects resumed on a peer vs
    # degraded to the full re-prefill fallback
    "migrations", "migration_fallbacks",
    # cluster prefix cache: dispatches steered to a chain holder within
    # the affinity margin, and the shared directory's live entry count
    # (0 when no directory is bound)
    "affinity_routes", "directory_entries",
})

# `Autoscaler.stats()` — registered under the pool's metrics registry
# as component "autoscaler", so the gateway `metrics` exposition and
# `autoscaler_stats` RPC both carry it.
AUTOSCALER_STATS_KEYS = frozenset({
    "autoscale_events", "scale_ups", "scale_downs",
    "autoscale_failures", "samples", "pressure", "pressure_ewma",
    "min_replicas", "max_replicas", "cooldown_remaining",
    "last_decision",
    # migrate-then-drain shrink: wall time of the most recent
    # scale-down — the regression alarm for "scale_down no longer
    # blocks on the longest in-flight generation"
    "last_scale_down_ms",
})

# `ExactlyOnceDoor.stats()["cache"]` (`serving.exactly_once`) — the
# gateway `exactly_once_stats` RPC returns the enclosing dict verbatim;
# the ledger counters the crash/reclaim drills and the bench assert on.
EXACTLY_ONCE_STATS_KEYS = frozenset({
    "completed", "inflight", "capacity", "ttl_s", "dedup_hits",
    "executions", "expired", "evicted", "double_executions",
    "durable_loaded",
})

POOL_REPLICA_STATS_KEYS = frozenset({
    "state", "consecutive_failures", "evictions", "stale",
}) | MODEL_SERVER_STATS_KEYS

# `StreamRegistry.stats()` (`serving.streaming`) — registered under the
# serving tier's metrics as component "streaming" by the first streamed
# request, so the gateway `metrics` exposition carries the resumable-
# streaming counters (`stream_resumes`, backpressure sheds, the cursor
# dedup totals) the chaos drills and bench assert on.
STREAMING_STATS_KEYS = frozenset({
    "streams_active", "streams_opened", "streams_finished",
    "stream_resumes", "stream_backpressure_sheds",
    "duplicate_tokens_dropped", "ring_capacity", "ttl_s",
})
