"""Serving-tier chaos injectors, completing the fault-injection family
started in `parallel/fault_tolerance.py` (worker crashes, checkpoint
save-crashes, NaN gradients). These drive the three serving ladders the
chaos suite (`tests/test_serving.py`) proves end to end:

- overload → typed shed → recovery (`SlowInferenceInjector`),
- breaker open → half-open probe → close (`BrokenModelInjector`),
- reload-of-corrupt-candidate → rejection with the previous model still
  serving (`ReloadCorruptionInjector`).

`SlowInferenceInjector` and `BrokenModelInjector` plug into
`ModelServer(infer_hooks=[...])` — called as `hook(phase, info)` at
`pre_step`/`post_step` around every device dispatch.
`ReloadCorruptionInjector` damages checkpoint artifacts on disk, the
same corruption family `tests/test_checkpoint_durability.py` uses."""
from __future__ import annotations

import threading
import time
from pathlib import Path

import numpy as np


class InjectedServingFault(RuntimeError):
    """Raised by `BrokenModelInjector` inside the device step — the
    server must translate it into a typed `InferenceFailedError` and
    count it toward the circuit breaker, exactly like a real failure."""


class SlowInferenceInjector:
    """Deterministic serving straggler: every device step sleeps `delay`
    seconds while `active`. With a delay ≫ the request arrival interval
    the bounded queue fills and admission control MUST shed — the
    overload drill. `release()` ends the slowdown (recovery phase);
    `steps` counts affected dispatches."""

    def __init__(self, delay: float = 0.2):
        self.delay = delay
        self.active = True
        self.steps = 0

    def release(self) -> None:
        self.active = False

    def __call__(self, phase: str, info: dict) -> None:
        if phase == "pre_step" and self.active:
            self.steps += 1
            time.sleep(self.delay)


class BrokenModelInjector:
    """Model breakage on demand: while `active`, every device step
    raises `InjectedServingFault` (mode='raise') or flags the step so a
    test double can poison outputs. Drives the breaker ladder: failures
    accumulate → breaker opens → `heal()` → the half-open probe succeeds
    → breaker closes. `failures` counts injected faults."""

    def __init__(self, mode: str = "raise"):
        if mode not in ("raise",):
            raise ValueError(f"unknown mode {mode!r}")
        self.mode = mode
        self.active = True
        self.failures = 0
        self._lock = threading.Lock()

    def heal(self) -> None:
        self.active = False

    def break_again(self) -> None:
        self.active = True

    def __call__(self, phase: str, info: dict) -> None:
        if phase == "pre_step" and self.active:
            with self._lock:
                self.failures += 1
            raise InjectedServingFault(
                "injected model breakage (serving chaos)")


class ReloadCorruptionInjector:
    """Damage a hot-reload candidate on disk before the server loads it.

    Three corruption families, matching how real candidates go bad:

    - `corrupt_payload(path)` — flip bytes mid-payload WITHOUT touching
      the manifest: integrity verification must catch the drift
      (`CheckpointCorruptError`) before any bytes are trusted.
    - `truncate(path)` — cut the payload short (killed copy/download);
      same typed outcome.
    - `poison_params(store, step, net)` — the insidious one: write a
      VALID, manifest-consistent checkpoint whose parameters are all
      NaN. It loads cleanly; only the server's canary validation can
      catch it (`ModelValidationError`).

    `corruptions` counts injected damages."""

    def __init__(self):
        self.corruptions = 0

    def corrupt_payload(self, path) -> Path:
        path = Path(path)
        data = bytearray(path.read_bytes())
        mid = len(data) // 2
        for i in range(mid, min(mid + 16, len(data))):
            data[i] ^= 0xFF
        path.write_bytes(bytes(data))
        self.corruptions += 1
        return path

    def truncate(self, path, keep: int = 100) -> Path:
        path = Path(path)
        path.write_bytes(path.read_bytes()[:keep])
        self.corruptions += 1
        return path

    def poison_params(self, store, step: int, net) -> Path:
        """Commit a manifest-consistent checkpoint of `net` with every
        parameter NaN into `store` at `step` — the candidate that MUST
        be stopped by canary validation, not by integrity checks."""
        from deeplearning4j_tpu.util.serialization import (
            restore_model,
            write_model,
        )

        # clone via serialize/restore so the live net is never touched
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            tmp = Path(d) / "clone.zip"
            write_model(net, tmp)
            clone = restore_model(tmp)
        clone.set_params(np.full_like(np.asarray(clone.params()), np.nan))
        path = store.save(step,
                          lambda tmp_path: write_model(clone, tmp_path,
                                                       atomic=False))
        self.corruptions += 1
        return path
