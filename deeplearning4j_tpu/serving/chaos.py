"""Serving-tier chaos injectors, completing the fault-injection family
started in `parallel/fault_tolerance.py` (worker crashes, checkpoint
save-crashes, NaN gradients). These drive the three serving ladders the
chaos suite (`tests/test_serving.py`) proves end to end:

- overload → typed shed → recovery (`SlowInferenceInjector`),
- breaker open → half-open probe → close (`BrokenModelInjector`),
- reload-of-corrupt-candidate → rejection with the previous model still
  serving (`ReloadCorruptionInjector`),

plus the REPLICA-level ladders the replicated pool
(`serving/replica_pool.py`, `tests/test_replica_pool.py`) proves:

- replica crash mid-flight → failover serves the request, probe loop
  evicts, revival re-admits (`ReplicaCrashInjector`),
- replica wedged inside a device step → watchdog eviction, hedged
  requests won by the healthy replica (`ReplicaHangInjector`),
- corrupted rolling-reload candidate → pool-wide rollback
  (`ReloadCorruptionInjector`, reused per replica).

`SlowInferenceInjector` and `BrokenModelInjector` plug into
`ModelServer(infer_hooks=[...])` — called as `hook(phase, info)` at
`pre_step`/`post_step` around every device dispatch.
`ReloadCorruptionInjector` damages checkpoint artifacts on disk, the
same corruption family `tests/test_checkpoint_durability.py` uses."""
from __future__ import annotations

import contextlib
import logging
import socket
import struct
import threading
import time
from pathlib import Path

import numpy as np

logger = logging.getLogger("deeplearning4j_tpu")


class InjectedServingFault(RuntimeError):
    """Raised by `BrokenModelInjector` inside the device step — the
    server must translate it into a typed `InferenceFailedError` and
    count it toward the circuit breaker, exactly like a real failure."""


class SlowInferenceInjector:
    """Deterministic serving straggler: every device step sleeps `delay`
    seconds while `active`. With a delay ≫ the request arrival interval
    the bounded queue fills and admission control MUST shed — the
    overload drill. `release()` ends the slowdown (recovery phase);
    `steps` counts affected dispatches."""

    def __init__(self, delay: float = 0.2):
        self.delay = delay
        self.active = True
        self.steps = 0

    def release(self) -> None:
        self.active = False

    def __call__(self, phase: str, info: dict) -> None:
        if phase == "pre_step" and self.active:
            self.steps += 1
            time.sleep(self.delay)


class BrokenModelInjector:
    """Model breakage on demand: while `active`, every device step
    raises `InjectedServingFault` (mode='raise') or flags the step so a
    test double can poison outputs. Drives the breaker ladder: failures
    accumulate → breaker opens → `heal()` → the half-open probe succeeds
    → breaker closes. `failures` counts injected faults."""

    def __init__(self, mode: str = "raise"):
        if mode not in ("raise",):
            raise ValueError(f"unknown mode {mode!r}")
        self.mode = mode
        self.active = True
        self.failures = 0
        self._lock = threading.Lock()

    def heal(self) -> None:
        self.active = False

    def break_again(self) -> None:
        self.active = True

    def __call__(self, phase: str, info: dict) -> None:
        if phase == "pre_step" and self.active:
            with self._lock:
                self.failures += 1
            raise InjectedServingFault(
                "injected model breakage (serving chaos)")


class ReplicaCrashInjector:
    """Simulated replica process death. Plug into ONE replica's
    `infer_hooks`; after `crash()` every device step on that replica
    raises `InjectedServingFault` — the shape of a replica whose
    process died with requests in flight (in-flight work errors, the
    pool fails the request over, the probe loop evicts). `revive()`
    brings the 'process' back so re-admission can be drilled.
    `steps_killed` counts dispatches the crash ate."""

    def __init__(self, crashed: bool = False):
        self.crashed = crashed
        self.steps_killed = 0
        self._lock = threading.Lock()

    def crash(self) -> None:
        self.crashed = True

    def revive(self) -> None:
        self.crashed = False

    def __call__(self, phase: str, info: dict) -> None:
        if phase == "pre_step" and self.crashed:
            with self._lock:
                self.steps_killed += 1
            raise InjectedServingFault(
                "injected replica crash (replica-pool chaos)")


class ReplicaHangInjector:
    """Wedged replica: while `active`, every device step on the wired
    replica BLOCKS (no error, no progress — the failure deadlines
    cannot reach, because the hang is inside the accelerator dispatch).
    Drives the pool's watchdog-eviction and hedging ladders: the probe
    loop's watchdog reads the silence as a hang, and a hedged request
    is won by the healthy replica while this one sits. `release()`
    unblocks every waiter (test teardown MUST call it, or the replica's
    executor thread sleeps forever); `hangs` counts trapped steps."""

    def __init__(self):
        self.active = True
        self.hangs = 0
        self._lock = threading.Lock()
        self._released = threading.Event()

    def release(self) -> None:
        self.active = False
        self._released.set()

    def __call__(self, phase: str, info: dict) -> None:
        if phase == "pre_step" and self.active:
            with self._lock:
                self.hangs += 1
            self._released.wait()


class ReloadCorruptionInjector:
    """Damage a hot-reload candidate on disk before the server loads it.

    Three corruption families, matching how real candidates go bad:

    - `corrupt_payload(path)` — flip bytes mid-payload WITHOUT touching
      the manifest: integrity verification must catch the drift
      (`CheckpointCorruptError`) before any bytes are trusted.
    - `truncate(path)` — cut the payload short (killed copy/download);
      same typed outcome.
    - `poison_params(store, step, net)` — the insidious one: write a
      VALID, manifest-consistent checkpoint whose parameters are all
      NaN. It loads cleanly; only the server's canary validation can
      catch it (`ModelValidationError`).

    `corruptions` counts injected damages."""

    def __init__(self):
        self.corruptions = 0

    def corrupt_payload(self, path) -> Path:
        path = Path(path)
        data = bytearray(path.read_bytes())
        mid = len(data) // 2
        for i in range(mid, min(mid + 16, len(data))):
            data[i] ^= 0xFF
        path.write_bytes(bytes(data))
        self.corruptions += 1
        return path

    def truncate(self, path, keep: int = 100) -> Path:
        path = Path(path)
        path.write_bytes(path.read_bytes()[:keep])
        self.corruptions += 1
        return path

    def poison_params(self, store, step: int, net) -> Path:
        """Commit a manifest-consistent checkpoint of `net` with every
        parameter NaN into `store` at `step` — the candidate that MUST
        be stopped by canary validation, not by integrity checks."""
        from deeplearning4j_tpu.util.serialization import (
            restore_model,
            write_model,
        )

        # clone via serialize/restore so the live net is never touched
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            tmp = Path(d) / "clone.zip"
            write_model(net, tmp)
            clone = restore_model(tmp)
        clone.set_params(np.full_like(np.asarray(clone.params()), np.nan))
        path = store.save(step,
                          lambda tmp_path: write_model(clone, tmp_path,
                                                       atomic=False))
        self.corruptions += 1
        return path


class JournalCorruptionInjector:
    """Damage an exactly-once request journal on disk
    (`serving/exactly_once.RequestJournal`) between gateway
    incarnations — the disk hazards replay must survive typed, never
    as a double execution or a crash at load:

    - `torn_tail(journal_dir)` — truncate the NEWEST segment mid-way
      through its last record: the shape of `kill -9` landing between
      `write()` and a completed line. Replay must count it
      `torn_skipped` and carry on; the half-written admit is a request
      the client never got an ack for, so dropping it is correct.
    - `corrupt_record(journal_dir, index)` — flip bytes inside a
      COMMITTED record of the OLDEST segment (bit-rot, a bad sector):
      the CRC must refuse it (`corrupt_skipped`), and every other
      record in the segment must still replay.

    `corruptions` counts injected damages."""

    def __init__(self):
        self.corruptions = 0

    @staticmethod
    def _segments(journal_dir) -> list:
        segs = sorted(Path(journal_dir).glob("journal-*.wal"))
        if not segs:
            raise FileNotFoundError(
                f"no journal segments under {journal_dir}")
        return segs

    def torn_tail(self, journal_dir) -> Path:
        """Cut the newest segment's last record in half — a torn write."""
        path = self._segments(journal_dir)[-1]
        data = path.read_bytes()
        lines = data.splitlines(keepends=True)
        if not lines:
            raise ValueError(f"segment {path} is empty — nothing to tear")
        last = lines[-1]
        path.write_bytes(b"".join(lines[:-1]) + last[: max(1, len(last) // 2)])
        self.corruptions += 1
        return path

    def corrupt_record(self, journal_dir, index: int = 0) -> Path:
        """Flip bytes inside committed record `index` of the oldest
        segment WITHOUT touching its length — the CRC, not the line
        framing, must catch this one."""
        path = self._segments(journal_dir)[0]
        lines = path.read_bytes().splitlines(keepends=True)
        if not 0 <= index < len(lines):
            raise IndexError(f"record {index} not in {path} "
                             f"({len(lines)} records)")
        rec = bytearray(lines[index])
        # flip payload bytes mid-line; keep the trailing newline intact
        mid = len(rec) // 2
        for i in range(mid, min(mid + 8, len(rec) - 1)):
            rec[i] ^= 0x5A
        lines[index] = bytes(rec)
        path.write_bytes(b"".join(lines))
        self.corruptions += 1
        return path


class KVTransferCorruptionInjector:
    """Damage a KV handoff payload between `fetch_handoff` and
    `resume_generate` — the wire hazards a migrated slot must survive
    typed (`KVTransferError` → re-prefill fallback), never as wrong
    tokens.

    Three corruption families, matching how real transfers go bad:

    - `flip_page(payload)` — XOR bytes inside one shipped KV page
      (bit-rot / a bad NIC): the per-page checksum must refuse it.
    - `truncate(payload)` — drop the tail pages of every block (a
      transfer killed mid-flight): the span/shape validation must
      refuse it.
    - `expire_lease(server, handoff_id)` — resolve the lease out from
      under the receiver (the TTL sweep racing a slow resume): the
      NEXT fetch must answer the typed unknown-lease error.

    Every method works on a COPY of the payload dicts it mutates, so
    the sender's leased original stays intact — exactly like a wire
    that corrupts in transit without touching the source buffers.
    `corruptions` counts injected damages."""

    def __init__(self):
        self.corruptions = 0

    @staticmethod
    def _copy(payload: dict) -> dict:
        out = dict(payload)
        out["blocks"] = [dict(b) for b in payload.get("blocks", [])]
        return out

    def flip_page(self, payload: dict, block: int = 0,
                  tensor: str = "k", page: int = 0) -> dict:
        """One shipped page's bytes flipped; checksums untouched."""
        out = self._copy(payload)
        arr = np.array(out["blocks"][block][tensor])  # private copy
        flat = arr.view(np.uint8).reshape(-1)
        flat[: min(16, flat.size)] ^= 0xFF
        out["blocks"][block][tensor] = arr
        self.corruptions += 1
        return out

    def truncate(self, payload: dict, keep: int = 0) -> dict:
        """Every block's page arrays cut to `keep` pages — but
        `pages_shipped` still claims the original count, like a frame
        that stopped arriving mid-transfer."""
        out = self._copy(payload)
        for blk in out["blocks"]:
            for name, arr in blk.items():
                blk[name] = np.array(arr[:keep])
        self.corruptions += 1
        return out

    def expire_lease(self, server, handoff_id: str) -> None:
        """Kill the lease mid-flight (the receiver already fetched; the
        sender reclaims as if the TTL swept it)."""
        server.abort_handoff(handoff_id)
        self.corruptions += 1


class PrefixFetchSaboteur:
    """Wire hazards on the cluster-prefix fetch path: wraps a holder
    server (the `peers` resolver hands the fetching engine THIS object
    instead) and damages the framed transfer in one of three ways a
    real deployment produces. The contract under every mode is the
    same — the fetching engine degrades to cold prefill with ZERO
    failed requests, counting `prefix_fetch_fallbacks`, never binding
    damaged pages.

    - ``mode="corrupt-frame"`` — one frame's page bytes flipped in
      transit: the reassembled payload's per-page checksum refuses it.
    - ``mode="die-after-header"`` — the holder vanishes between the
      header and the first frame (kill -9 mid-fetch): the fetcher sees
      a raw `ConnectionError`.
    - ``mode="stale-version"`` — the header claims a `weight_version`
      the holder no longer serves (a rolling reload landed between
      directory lookup and fetch): `verify_payload` refuses the skew.

    `sabotages` counts injected damages."""

    def __init__(self, holder, mode: str = "corrupt-frame"):
        if mode not in ("corrupt-frame", "die-after-header",
                        "stale-version"):
            raise ValueError(f"unknown sabotage mode {mode!r}")
        self._holder = holder
        self.mode = mode
        self.sabotages = 0

    def __getattr__(self, name):
        return getattr(self._holder, name)

    def export_prefix(self, *a, **kw) -> dict:
        header = self._holder.export_prefix(*a, **kw)
        if self.mode == "stale-version":
            header = dict(header)
            header["weight_version"] = "stale-" * 2 + "deadbeef"
            self.sabotages += 1
        return header

    def fetch_handoff_frame(self, handoff_id: str, frame: int,
                            **kw) -> dict:
        if self.mode == "die-after-header":
            self.sabotages += 1
            raise ConnectionResetError(
                "injected: holder died between header and frame 0")
        out = self._holder.fetch_handoff_frame(handoff_id, frame, **kw)
        if self.mode == "corrupt-frame" and frame == 0:
            out = dict(out)
            out["blocks"] = [dict(b) for b in out["blocks"]]
            blk = out["blocks"][0]
            name = next(iter(blk))
            arr = np.array(blk[name])
            flat = arr.view(np.uint8).reshape(-1)
            flat[: min(16, flat.size)] ^= 0xFF
            blk[name] = arr
            self.sabotages += 1
        return out


# -- network chaos (cross-process replica pool) ---------------------------

class ChaosProxy:
    """Network-fault man-in-the-middle for ONE gateway endpoint: point
    a `RemoteReplica` at `proxy.port` instead of the replica's real
    port and every wire hazard becomes injectable without touching the
    replica process. Modes (exactly one active; `heal()` returns to
    clean forwarding):

    - ``forward``   — transparent TCP relay (the healthy baseline)
    - ``partition`` — existing connections are RESET (SO_LINGER 0) and
      new ones reset right after accept: the filtered-network shape a
      pool must answer with eviction, then re-admission after `heal()`
    - ``latency``   — each response chunk is delayed `delay` seconds
      before forwarding (slow network, alive replica)
    - ``slowloris`` — responses dribble one byte per `interval`: the
      connection is alive but the response never completes inside any
      reasonable deadline
    - ``garbage``   — responses are replaced with bytes that do not
      parse as a gateway response line (protocol corruption)
    - ``reset``     — the connection is RESET the moment a response
      chunk arrives: death mid-response, the ambiguous failure retries
      must respect

    The proxy accepts on an ephemeral port (`.port`) at construction;
    `close()` tears everything down. Thread-safe."""

    _MODES = frozenset({"forward", "partition", "latency", "slowloris",
                        "garbage", "reset"})
    GARBAGE_LINE = b"!!chaos-garbage-not-a-gateway-response!!\n"

    def __init__(self, upstream_host: str, upstream_port: int,
                 listen_host: str = "127.0.0.1"):
        self._upstream = (upstream_host, upstream_port)
        self._mode = "forward"  # guarded by: _lock
        self._delay = 0.0
        self._interval = 0.05
        self._lock = threading.Lock()
        self._conns: list = []  # guarded by: _lock
        self._closed = False  # guarded by: _lock
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((listen_host, 0))
        self._listener.listen(64)
        self.host = listen_host
        self.port = self._listener.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"chaos-proxy-{self.port}").start()

    # -- mode control ------------------------------------------------------
    def _set_mode(self, mode: str) -> None:
        if mode not in self._MODES:
            raise ValueError(f"unknown chaos mode {mode!r}")
        with self._lock:
            self._mode = mode

    def heal(self) -> None:
        self._set_mode("forward")

    def partition(self) -> None:
        """Cut the replica off: reset every live connection and every
        future one until `heal()`."""
        self._set_mode("partition")
        with self._lock:
            conns, self._conns = self._conns, []
        for s in conns:
            self._reset_close(s)

    def inject_latency(self, delay: float) -> None:
        self._delay = float(delay)
        self._set_mode("latency")

    def inject_slowloris(self, interval: float = 0.05) -> None:
        self._interval = float(interval)
        self._set_mode("slowloris")

    def inject_garbage(self) -> None:
        self._set_mode("garbage")

    def inject_reset(self) -> None:
        self._set_mode("reset")

    # -- plumbing ----------------------------------------------------------
    @staticmethod
    def _reset_close(sock) -> None:
        """Close with SO_LINGER 0 — the peer sees RST, not FIN: real
        partition/crash behavior, not a polite shutdown."""
        with contextlib.suppress(OSError):
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                            struct.pack("ii", 1, 0))
        with contextlib.suppress(OSError):
            sock.close()

    def _accept_loop(self) -> None:
        # the suppress ends this loop when close() shuts the listener
        with contextlib.suppress(OSError):
            while True:
                client, _ = self._listener.accept()
                threading.Thread(target=self._open_link, args=(client,),
                                 daemon=True).start()

    def _open_link(self, client) -> None:
        with self._lock:
            refuse = self._closed or self._mode == "partition"
        if refuse:
            self._reset_close(client)
            return
        try:
            upstream = socket.create_connection(self._upstream,
                                                timeout=10.0)
        except OSError as e:
            logger.info("chaos proxy: upstream %s:%d unreachable (%s)",
                        self._upstream[0], self._upstream[1],
                        type(e).__name__)
            self._reset_close(client)
            return
        with self._lock:
            if self._closed:
                self._reset_close(client)
                self._reset_close(upstream)
                return
            self._conns += [client, upstream]
        threading.Thread(target=self._pump, args=(client, upstream, False),
                         daemon=True).start()
        threading.Thread(target=self._pump, args=(upstream, client, True),
                         daemon=True).start()

    # pump recv poll tick: a linger-0 close from the sibling pump (or
    # partition()/close()) cannot tear the kernel socket down — and so
    # cannot emit its RST — while this thread is parked inside recv()
    # on the same fd; the syscall holds the last reference.  Bounded
    # recv waits mean a closed socket is noticed within one tick, the
    # reference drops, and the deferred RST actually reaches the peer.
    _PUMP_POLL = 0.25

    def _pump(self, src, dst, response_path: bool) -> None:
        # OSErrors end the link (either side vanishing is normal here)
        with contextlib.suppress(OSError):
            src.settimeout(self._PUMP_POLL)
            while True:
                try:
                    data = src.recv(65536)
                # graftlint: disable=typed-error  idle poll tick, not a failure: re-enter recv so a concurrently closed socket raises and ends the link
                except TimeoutError:
                    continue
                if not data:
                    break
                mode = self._mode
                if mode == "partition":
                    break
                if response_path and mode == "latency":
                    time.sleep(self._delay)
                elif response_path and mode == "slowloris":
                    for i in range(len(data)):
                        if self._mode != "slowloris":
                            dst.sendall(data[i:])
                            break
                        time.sleep(self._interval)
                        dst.sendall(data[i:i + 1])
                    continue
                elif response_path and mode == "garbage":
                    dst.sendall(self.GARBAGE_LINE)
                    continue
                elif response_path and mode == "reset":
                    self._reset_close(dst)
                    self._reset_close(src)
                    break
                dst.sendall(data)
        self._reset_close(src)
        self._reset_close(dst)
        with self._lock:
            for s in (src, dst):
                if s in self._conns:
                    self._conns.remove(s)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            conns, self._conns = self._conns, []
        with contextlib.suppress(OSError):
            self._listener.close()
        for s in conns:
            self._reset_close(s)


class PartitionInjector:
    """Network partition of one replica: every connection through the
    proxy is reset until `heal()` — the pool must evict on failed
    probes and re-admit after `readmit_successes` passes post-heal.
    `partitions` counts injections."""

    def __init__(self, proxy: ChaosProxy):
        self.proxy = proxy
        self.partitions = 0

    def partition(self) -> None:
        self.partitions += 1
        self.proxy.partition()

    def heal(self) -> None:
        self.proxy.heal()


class NetworkLatencyInjector:
    """Slow network to one replica: responses arrive `delay` seconds
    late. Below the caller deadline this is a tail-latency drill
    (hedging); above it, a deadline drill. `release()` restores clean
    forwarding."""

    def __init__(self, proxy: ChaosProxy, delay: float = 0.2):
        self.proxy = proxy
        self.delay = delay

    def inject(self) -> None:
        self.proxy.inject_latency(self.delay)

    def release(self) -> None:
        self.proxy.heal()


class SlowLorisInjector:
    """Byte-at-a-time responses: the connection looks alive while the
    response never completes inside any reasonable deadline — the
    drill proving read deadlines (not liveness checks) bound a call."""

    def __init__(self, proxy: ChaosProxy, interval: float = 0.05):
        self.proxy = proxy
        self.interval = interval

    def inject(self) -> None:
        self.proxy.inject_slowloris(self.interval)

    def release(self) -> None:
        self.proxy.heal()


class GarbageResponseInjector:
    """Protocol corruption: responses are replaced with bytes that do
    not parse as a gateway response line. The client must answer with
    the typed protocol error (mapped to retryable sickness), never a
    hang or an unhandled decode crash."""

    def __init__(self, proxy: ChaosProxy):
        self.proxy = proxy

    def inject(self) -> None:
        self.proxy.inject_garbage()

    def release(self) -> None:
        self.proxy.heal()


class ConnectionResetInjector:
    """Death mid-response: the connection is RESET the moment response
    bytes arrive — the ambiguous failure (did the side effect land?)
    that only idempotent calls may retry."""

    def __init__(self, proxy: ChaosProxy):
        self.proxy = proxy

    def inject(self) -> None:
        self.proxy.inject_reset()

    def release(self) -> None:
        self.proxy.heal()


class SlowConsumerInjector:
    """A streaming consumer that reads `read_frames` frames and then
    stops draining — the slow-consumer drill for `generate_stream`.

    The contract under drill: the decode slot keeps emitting at full
    speed (a stalled socket must never block the scheduler loop or
    other requests), the gateway pump sheds THIS consumer once a frame
    write stalls past `stream_send_timeout`, and the consumer's
    recovery ladder is typed the whole way down — ring replay on
    reconnect while the cursor is retained, `StreamBackpressureError`
    + parked-outcome `claim` once it fell out. `run()` executes one
    stalled consumption end to end and returns the outcome record;
    counters aggregate across runs. `client` is a `GatewayClient`
    against a server with streaming enabled."""

    def __init__(self, client, name: str, prompt=None,
                 n_tokens: int = 8, read_frames: int = 1,
                 stall: float = 1.0, **gen_kw):
        self.client = client
        self.name = name
        self.prompt = (np.arange(8, dtype=np.int32)
                       if prompt is None else np.asarray(prompt, np.int32))
        self.n_tokens = int(n_tokens)
        self.read_frames = int(read_frames)
        self.stall = float(stall)
        self.gen_kw = gen_kw
        self.runs = 0             # guarded by: _lock
        self.stalls = 0           # guarded by: _lock
        self.completions = 0      # guarded by: _lock
        self.backpressure_errors = 0  # guarded by: _lock
        self.other_errors = 0     # guarded by: _lock
        self._lock = threading.Lock()

    def run(self) -> dict:
        from deeplearning4j_tpu.gateway import GatewayError

        with self._lock:
            self.runs += 1
        stream = self.client.generate_stream(
            self.name, self.prompt, self.n_tokens, **self.gen_kw)
        frames = 0
        outcome = {"error_type": None}
        try:
            for _ in stream:
                frames += 1
                if frames == self.read_frames and self.stall > 0:
                    with self._lock:
                        self.stalls += 1
                    # the stall: the socket stays open but nothing
                    # drains — the server-side pump, not the decode
                    # slot, must absorb this
                    time.sleep(self.stall)
            with self._lock:
                self.completions += 1
        except GatewayError as err:
            outcome["error_type"] = err.error_type
            with self._lock:
                if err.error_type == "StreamBackpressureError":
                    self.backpressure_errors += 1
                else:
                    self.other_errors += 1
        finally:
            stream.close()
        outcome.update(frames=frames, resumes=stream.resumes,
                       tokens=list(stream.tokens),
                       request_id=stream.request_id)
        return outcome

    def counters(self) -> dict:
        with self._lock:
            return {"runs": self.runs, "stalls": self.stalls,
                    "completions": self.completions,
                    "backpressure_errors": self.backpressure_errors,
                    "other_errors": self.other_errors}


class TenantFloodInjector:
    """One tenant floods the serving tier with batch-priority generate
    traffic — the multi-tenant isolation drill. `concurrency` threads
    hammer `target.generate(...)` under the flooding tenant's identity
    until `release()`; per-outcome counters record what the flooder got
    back. The QoS contract under drill: the flooder's rejections are its
    OWN `TenantQuotaExceededError` (with retry_after), never anyone
    else's `ServerOverloadedError`, and other tenants' interactive p99
    stays within 2x unloaded. `target` is anything with the generate
    signature (DecodeEngine, ModelServer, ReplicaPool, RemoteReplica)."""

    def __init__(self, target, tenant: str = "flooder",
                 prompt=None, n_tokens: int = 8,
                 concurrency: int = 4, timeout: float = 5.0):
        self.target = target
        self.tenant = tenant
        self.prompt = (np.arange(8, dtype=np.int32)
                       if prompt is None else np.asarray(prompt, np.int32))
        self.n_tokens = int(n_tokens)
        self.concurrency = int(concurrency)
        self.timeout = float(timeout)
        self.active = True
        self.served = 0           # guarded by: _lock
        self.quota_rejections = 0  # guarded by: _lock
        self.sheds = 0            # guarded by: _lock
        self.other_errors = 0     # guarded by: _lock
        self._lock = threading.Lock()
        self._threads: list = []

    def start(self) -> "TenantFloodInjector":
        for i in range(self.concurrency):
            t = threading.Thread(target=self._flood,
                                 name=f"tenant-flood-{self.tenant}-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def _flood(self) -> None:
        from .model_server import ServingError, TenantQuotaExceededError

        while self.active:
            try:
                self.target.generate(self.prompt, self.n_tokens,
                                     timeout=self.timeout,
                                     tenant=self.tenant, priority="batch")
                with self._lock:
                    self.served += 1
            except TenantQuotaExceededError as err:
                with self._lock:
                    self.quota_rejections += 1
                # back off as told — a well-behaved flooder; the drill
                # for a non-compliant one just shrinks this sleep
                time.sleep(min(getattr(err, "retry_after", 0.01) or 0.01,
                               0.05))
            except ServingError:
                with self._lock:
                    self.sheds += 1
            # graftlint: disable=typed-error  deliberate: a chaos driver
            # counts whatever the target throws; killing the flood
            # thread on a surprise would end the drill early
            except Exception:
                with self._lock:
                    self.other_errors += 1

    def release(self) -> None:
        self.active = False
        for t in self._threads:
            t.join(timeout=self.timeout + 5.0)
        self._threads = []

    def counters(self) -> dict:
        with self._lock:
            return {"served": self.served,
                    "quota_rejections": self.quota_rejections,
                    "sheds": self.sheds,
                    "other_errors": self.other_errors}


class LoadSpikeInjector:
    """A sudden sustained jump in interactive arrivals — the autoscale
    drill's stimulus. `concurrency` closed-loop threads submit
    interactive generate traffic under distinct tenants until
    `release()`, recording each request's latency so the drill can
    check p99 against the unloaded baseline while the autoscaler reacts
    (scale-up on pressure, scale-down after the spike, zero failed
    requests through both transitions)."""

    def __init__(self, target, prompt=None, n_tokens: int = 8,
                 concurrency: int = 8, tenant: str = "spike",
                 timeout: float = 10.0):
        self.target = target
        self.prompt = (np.arange(8, dtype=np.int32)
                       if prompt is None else np.asarray(prompt, np.int32))
        self.n_tokens = int(n_tokens)
        self.concurrency = int(concurrency)
        self.tenant = tenant
        self.timeout = float(timeout)
        self.active = True
        self.served = 0       # guarded by: _lock
        self.failures = 0     # guarded by: _lock
        self.sheds = 0        # guarded by: _lock
        self.latencies: list = []  # guarded by: _lock
        self._lock = threading.Lock()
        self._threads: list = []

    def start(self) -> "LoadSpikeInjector":
        for i in range(self.concurrency):
            t = threading.Thread(target=self._drive, args=(i,),
                                 name=f"load-spike-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def _drive(self, i: int) -> None:
        from .model_server import ServerOverloadedError, ServingError

        while self.active:
            t0 = time.monotonic()
            try:
                self.target.generate(self.prompt, self.n_tokens,
                                     timeout=self.timeout,
                                     tenant=f"{self.tenant}-{i}",
                                     priority="interactive")
                with self._lock:
                    self.served += 1
                    self.latencies.append(time.monotonic() - t0)
            except ServerOverloadedError as err:
                with self._lock:
                    self.sheds += 1
                time.sleep(min(getattr(err, "retry_after", 0.01) or 0.01,
                               0.05))
            except ServingError:
                with self._lock:
                    self.failures += 1
            # graftlint: disable=typed-error  deliberate: the spike must
            # keep driving through any surprise — an uncounted crash of
            # a driver thread would silently thin the load
            except Exception:
                with self._lock:
                    self.failures += 1

    def release(self) -> None:
        self.active = False
        for t in self._threads:
            t.join(timeout=self.timeout + 5.0)
        self._threads = []

    def p99(self) -> float:
        with self._lock:
            lats = sorted(self.latencies)
        if not lats:
            return 0.0
        return lats[min(len(lats) - 1, int(0.99 * len(lats)))]

    def counters(self) -> dict:
        with self._lock:
            return {"served": self.served, "failures": self.failures,
                    "sheds": self.sheds, "n_latencies": len(self.latencies)}
