"""Serving-tier chaos injectors, completing the fault-injection family
started in `parallel/fault_tolerance.py` (worker crashes, checkpoint
save-crashes, NaN gradients). These drive the three serving ladders the
chaos suite (`tests/test_serving.py`) proves end to end:

- overload → typed shed → recovery (`SlowInferenceInjector`),
- breaker open → half-open probe → close (`BrokenModelInjector`),
- reload-of-corrupt-candidate → rejection with the previous model still
  serving (`ReloadCorruptionInjector`),

plus the REPLICA-level ladders the replicated pool
(`serving/replica_pool.py`, `tests/test_replica_pool.py`) proves:

- replica crash mid-flight → failover serves the request, probe loop
  evicts, revival re-admits (`ReplicaCrashInjector`),
- replica wedged inside a device step → watchdog eviction, hedged
  requests won by the healthy replica (`ReplicaHangInjector`),
- corrupted rolling-reload candidate → pool-wide rollback
  (`ReloadCorruptionInjector`, reused per replica).

`SlowInferenceInjector` and `BrokenModelInjector` plug into
`ModelServer(infer_hooks=[...])` — called as `hook(phase, info)` at
`pre_step`/`post_step` around every device dispatch.
`ReloadCorruptionInjector` damages checkpoint artifacts on disk, the
same corruption family `tests/test_checkpoint_durability.py` uses."""
from __future__ import annotations

import threading
import time
from pathlib import Path

import numpy as np


class InjectedServingFault(RuntimeError):
    """Raised by `BrokenModelInjector` inside the device step — the
    server must translate it into a typed `InferenceFailedError` and
    count it toward the circuit breaker, exactly like a real failure."""


class SlowInferenceInjector:
    """Deterministic serving straggler: every device step sleeps `delay`
    seconds while `active`. With a delay ≫ the request arrival interval
    the bounded queue fills and admission control MUST shed — the
    overload drill. `release()` ends the slowdown (recovery phase);
    `steps` counts affected dispatches."""

    def __init__(self, delay: float = 0.2):
        self.delay = delay
        self.active = True
        self.steps = 0

    def release(self) -> None:
        self.active = False

    def __call__(self, phase: str, info: dict) -> None:
        if phase == "pre_step" and self.active:
            self.steps += 1
            time.sleep(self.delay)


class BrokenModelInjector:
    """Model breakage on demand: while `active`, every device step
    raises `InjectedServingFault` (mode='raise') or flags the step so a
    test double can poison outputs. Drives the breaker ladder: failures
    accumulate → breaker opens → `heal()` → the half-open probe succeeds
    → breaker closes. `failures` counts injected faults."""

    def __init__(self, mode: str = "raise"):
        if mode not in ("raise",):
            raise ValueError(f"unknown mode {mode!r}")
        self.mode = mode
        self.active = True
        self.failures = 0
        self._lock = threading.Lock()

    def heal(self) -> None:
        self.active = False

    def break_again(self) -> None:
        self.active = True

    def __call__(self, phase: str, info: dict) -> None:
        if phase == "pre_step" and self.active:
            with self._lock:
                self.failures += 1
            raise InjectedServingFault(
                "injected model breakage (serving chaos)")


class ReplicaCrashInjector:
    """Simulated replica process death. Plug into ONE replica's
    `infer_hooks`; after `crash()` every device step on that replica
    raises `InjectedServingFault` — the shape of a replica whose
    process died with requests in flight (in-flight work errors, the
    pool fails the request over, the probe loop evicts). `revive()`
    brings the 'process' back so re-admission can be drilled.
    `steps_killed` counts dispatches the crash ate."""

    def __init__(self, crashed: bool = False):
        self.crashed = crashed
        self.steps_killed = 0
        self._lock = threading.Lock()

    def crash(self) -> None:
        self.crashed = True

    def revive(self) -> None:
        self.crashed = False

    def __call__(self, phase: str, info: dict) -> None:
        if phase == "pre_step" and self.crashed:
            with self._lock:
                self.steps_killed += 1
            raise InjectedServingFault(
                "injected replica crash (replica-pool chaos)")


class ReplicaHangInjector:
    """Wedged replica: while `active`, every device step on the wired
    replica BLOCKS (no error, no progress — the failure deadlines
    cannot reach, because the hang is inside the accelerator dispatch).
    Drives the pool's watchdog-eviction and hedging ladders: the probe
    loop's watchdog reads the silence as a hang, and a hedged request
    is won by the healthy replica while this one sits. `release()`
    unblocks every waiter (test teardown MUST call it, or the replica's
    executor thread sleeps forever); `hangs` counts trapped steps."""

    def __init__(self):
        self.active = True
        self.hangs = 0
        self._lock = threading.Lock()
        self._released = threading.Event()

    def release(self) -> None:
        self.active = False
        self._released.set()

    def __call__(self, phase: str, info: dict) -> None:
        if phase == "pre_step" and self.active:
            with self._lock:
                self.hangs += 1
            self._released.wait()


class ReloadCorruptionInjector:
    """Damage a hot-reload candidate on disk before the server loads it.

    Three corruption families, matching how real candidates go bad:

    - `corrupt_payload(path)` — flip bytes mid-payload WITHOUT touching
      the manifest: integrity verification must catch the drift
      (`CheckpointCorruptError`) before any bytes are trusted.
    - `truncate(path)` — cut the payload short (killed copy/download);
      same typed outcome.
    - `poison_params(store, step, net)` — the insidious one: write a
      VALID, manifest-consistent checkpoint whose parameters are all
      NaN. It loads cleanly; only the server's canary validation can
      catch it (`ModelValidationError`).

    `corruptions` counts injected damages."""

    def __init__(self):
        self.corruptions = 0

    def corrupt_payload(self, path) -> Path:
        path = Path(path)
        data = bytearray(path.read_bytes())
        mid = len(data) // 2
        for i in range(mid, min(mid + 16, len(data))):
            data[i] ^= 0xFF
        path.write_bytes(bytes(data))
        self.corruptions += 1
        return path

    def truncate(self, path, keep: int = 100) -> Path:
        path = Path(path)
        path.write_bytes(path.read_bytes()[:keep])
        self.corruptions += 1
        return path

    def poison_params(self, store, step: int, net) -> Path:
        """Commit a manifest-consistent checkpoint of `net` with every
        parameter NaN into `store` at `step` — the candidate that MUST
        be stopped by canary validation, not by integrity checks."""
        from deeplearning4j_tpu.util.serialization import (
            restore_model,
            write_model,
        )

        # clone via serialize/restore so the live net is never touched
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            tmp = Path(d) / "clone.zip"
            write_model(net, tmp)
            clone = restore_model(tmp)
        clone.set_params(np.full_like(np.asarray(clone.params()), np.nan))
        path = store.save(step,
                          lambda tmp_path: write_model(clone, tmp_path,
                                                       atomic=False))
        self.corruptions += 1
        return path
