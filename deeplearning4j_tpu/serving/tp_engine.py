"""Tensor-parallel serving plan: shard ONE decode engine over a `tp` mesh.

ROADMAP item 3(a): training composes dp×tp×pp in one mesh, but the
serving tier was single-device — a model whose weights or KV pool
exceed one chip's HBM simply could not serve. This module is the
serving-side tensor parallelism: a `TPPlan` shards a `GPTPlan` net
Megatron-style (Shoeybi et al., 2019) over a named `tp` mesh axis and
wraps the engine's jitted step closures in `shard_map`, so the whole
existing serving stack — chunked prefill, prefix-cache sharing,
speculative verify, int8 KV pools, the Pallas paged-attention kernel —
rides the sharded engine untouched.

**Sharding layout** (per transformer block, degree N):

| tensor | layout | shard |
|---|---|---|
| `Wqkv` | (d, d + 2·Hkv·hd), columns permuted to [Q_t ‖ K_t ‖ V_t] | columns over `tp` |
| `bqkv` | same permutation | over `tp` |
| `Wo`   | (d, d), rows ordered by query head | rows over `tp` |
| `bo`   | replicated, added AFTER the all-reduce | — |
| `W1`/`W3`/`b1` | column-parallel FFN in | columns over `tp` |
| `W2`   | row-parallel FFN out | rows over `tp` |
| `b2`   | replicated, added AFTER the all-reduce | — |
| embeddings / LNs / logits head | replicated | — |
| K/V page pools (+ int8 scale sidecars) | `(P+1, Hkv, …)` | head axis over `tp` |

Exactly TWO all-reduces per block per token (after out-proj, after
FFN-out — `models.transformer._psum_partial`), the Megatron minimum.
Each device owns `Hkv/N` heads of EVERY page, so the page table,
free list, refcounts, prefix-cache promotions and trash-page masking
stay host-global and byte-identical to the single-device engine: page
management is head-agnostic. Attention itself is embarrassingly
parallel over heads — the per-device body is the EXISTING kernel (or
gather fallback) at `Hkv/N`, and GQA grouping is preserved because
`(H/N)/(Hkv/N) == H/Hkv`.

**Why column permutation.** `Wqkv` packs [Q | K | V] along its output
axis; a plain column split would hand device t an arbitrary mix of Q
and K columns. Permuting columns so device t's contiguous block is
[Q_t | K_t | V_t] keeps the per-device projection a single matmul whose
output slices exactly like the global one (`_block_heads(shard=N)`),
at zero runtime cost — the permutation happens once at `shard_params`
time on host.

**Parity.** The sharded computation is the same math with one changed
reduction: row-parallel contractions accumulate d/N-length partials
then sum across devices. f32 argmax-exact parity with the single-device
engine is pinned in `tests/test_tp_engine.py` across chunked prefill ×
prefix hits × speculative × GQA × int8 KV on a forced-host-device mesh
(`XLA_FLAGS=--xla_force_host_platform_device_count=8`).
"""
from __future__ import annotations

import numpy as np

TP_AXIS = "tp"

# keys sharded along their OUTPUT axis (column-parallel)
_COL_KEYS = ("Wqkv", "W1", "W3")
_COL_BIAS_KEYS = ("bqkv", "b1", "b3")
# keys sharded along their INPUT axis (row-parallel; bias replicated
# and added after the psum — see models.transformer)
_ROW_KEYS = ("Wo", "W2")

# one Mesh per degree per process: the conftest session fixture warms
# this once so every tier-1 TP test shares a mesh instead of re-paying
# mesh construction (and XLA device queries) per engine build
_MESH_CACHE: dict = {}


def _shard_map(fn, *, mesh, in_specs, out_specs):
    """Version-portable `shard_map`: the top-level `jax.shard_map`
    spelling with `check_vma` (the repo's training-side idiom —
    parallel/sequence.py) where available, else the older
    `jax.experimental.shard_map` with `check_rep`. Replication checking
    is off either way: every non-pool output is produced by identical
    deterministic per-device math after each psum."""
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_vma=False)


def tp_mesh(degree: int):
    """The serving `tp` mesh over the first `degree` local devices,
    cached per process. Raises ValueError (typed, at construction —
    never a trace error) when the platform doesn't expose enough
    devices; on CPU hosts the fix is
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N``."""
    mesh = _MESH_CACHE.get(degree)
    if mesh is not None:
        return mesh
    import jax

    from deeplearning4j_tpu.parallel.mesh import make_mesh

    devs = jax.devices()
    if len(devs) < degree:
        raise ValueError(
            f"parallel={{'tp': {degree}}} needs {degree} devices but the "
            f"platform exposes {len(devs)} — on a CPU host set "
            "XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{degree} (the tier-1 conftest does)")
    mesh = make_mesh({TP_AXIS: degree}, devices=devs[:degree])
    _MESH_CACHE[degree] = mesh
    return mesh


class TPPlan:
    """Sharding plan for one `GPTPlan` net at tensor-parallel degree N:
    validates the geometry at CONSTRUCTION (typed ValueErrors, never
    trace errors), owns the mesh and per-argument PartitionSpec trees,
    permutes+places params, and wraps step closures in
    `jit(shard_map(...))` with the engine's donation discipline."""

    def __init__(self, net, plan, degree: int):
        from jax.sharding import PartitionSpec as P

        if not isinstance(degree, int) or degree < 2:
            raise ValueError(
                f"tensor-parallel degree must be an int >= 2, got "
                f"{degree!r} (tp=1 is the single-device engine — omit "
                "parallel= instead)")
        self.degree = degree
        self.axis = TP_AXIS
        self.mesh = tp_mesh(degree)
        self.plan = plan
        params = net._params
        # per-layer-index spec: dict-of-specs for transformer blocks,
        # replicated prefix for everything else (embedding, LNs, head)
        specs: list = [P()] * len(params)
        self._perms: dict = {}
        for i in plan.block_is:
            layer = plan.layers[i]
            if getattr(layer, "moe_experts", 0) > 0:
                raise ValueError(
                    "parallel={'tp': N} does not compose with MoE blocks "
                    "(expert parallelism is its own axis) — serve the "
                    "dense net or drop parallel=")
            H, Hkv = layer.n_heads, layer._kv_heads
            if H % degree or Hkv % degree:
                raise ValueError(
                    f"tp={degree} must divide the head counts of every "
                    f"block: block {i} has n_heads={H}, kv_heads={Hkv}")
            p = params[i]
            f = int(p["W1"].shape[1]) if "W1" in p else 0
            if f % degree:
                raise ValueError(
                    f"tp={degree} must divide the FFN width of every "
                    f"block: block {i} has ffn={f}")
            d = int(layer.n_out)
            hd = d // H
            self._perms[i] = self._qkv_perm(d, H, Hkv, hd, degree)
            specs[i] = {
                k: (P(None, TP_AXIS) if k in _COL_KEYS
                    else P(TP_AXIS) if k in _COL_BIAS_KEYS
                    else P(TP_AXIS, None) if k in _ROW_KEYS
                    else P())
                for k in p}
        self.param_specs = specs

    @staticmethod
    def _qkv_perm(d, H, Hkv, hd, n):
        """Column permutation of the packed [Q | K | V] output axis so
        device t's contiguous axis-1 block is [Q_t | K_t | V_t]."""
        Hl, Hkvl = H // n, Hkv // n
        k0, v0 = d, d + Hkv * hd
        idx = []
        for t in range(n):
            idx.extend(range(t * Hl * hd, (t + 1) * Hl * hd))
            idx.extend(range(k0 + t * Hkvl * hd, k0 + (t + 1) * Hkvl * hd))
            idx.extend(range(v0 + t * Hkvl * hd, v0 + (t + 1) * Hkvl * hd))
        return np.asarray(idx, np.int64)

    # -- placement ---------------------------------------------------------
    def shard_params(self, params):
        """Permute + place the net's params once per (re)build. Returns
        a NEW list — `net._params` stays the untouched host-layout copy
        (weight swaps, checkpoints, and the parity oracle all read it),
        so a reload reshards from clean state."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(self.mesh, P())
        out = []
        for i, p in enumerate(params):
            spec = self.param_specs[i]
            if isinstance(spec, dict):
                perm = self._perms[i]
                q = {}
                for k, v in p.items():
                    if k == "Wqkv":
                        v = v[:, perm]
                    elif k == "bqkv":
                        v = v[perm]
                    q[k] = jax.device_put(
                        v, NamedSharding(self.mesh, spec[k]))
                out.append(q)
            else:
                out.append(jax.tree_util.tree_map(
                    lambda v: jax.device_put(v, repl), p))
        return out

    def shard_pool(self, x):
        """Place one page-pool (or scale-sidecar) array with its head
        axis (axis 1 in every pool layout) split over `tp`."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P(*([None, TP_AXIS] + [None] * (x.ndim - 2)))
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    # -- shard_map wrapping ------------------------------------------------
    def in_specs(self, n: int, params_at: int = 0, caches_at: int = 1):
        """Per-argument spec tuple: the params tree-of-specs, the pools
        as a `P(None, 'tp')` pytree prefix (head axis is axis 1 of every
        pool leaf, trailing dims unsharded), everything else — page
        table, slot state, scalars — replicated."""
        from jax.sharding import PartitionSpec as P

        specs = [P()] * n
        specs[params_at] = self.param_specs
        specs[caches_at] = P(None, TP_AXIS)
        return tuple(specs)

    def out_specs(self, n: int, caches_at: int = 0):
        from jax.sharding import PartitionSpec as P

        specs = [P(None, TP_AXIS) if j == caches_at else P()
                 for j in range(n)]
        return specs[0] if n == 1 else tuple(specs)

    def shard(self, fn, *, n_in: int, n_out: int,
              params_at: int = 0, caches_at: int = 1,
              caches_out_at: int = 0):
        """`shard_map` a step closure over the tp mesh. Callers jit the
        result with their own donation discipline — the literal
        ``x = jax.jit(tp.shard(f, ...), donate_argnums=...)`` assign is
        exactly the form graftlint's donation rule tracks, so the
        donated-sharded-pool hazard stays linted. Non-pool outputs are
        declared replicated: every device runs the identical
        deterministic math on replicated inputs after each psum, so
        replication checking off (the repo's established shard_map
        idiom — parallel/sequence.py) is sound here."""
        return _shard_map(
            fn, mesh=self.mesh,
            in_specs=self.in_specs(n_in, params_at, caches_at),
            out_specs=self.out_specs(n_out, caches_out_at))

    # -- byte accounting ---------------------------------------------------
    def weight_bytes_per_chip(self, params) -> int:
        """Per-chip weight residency: sharded matmul slices divide by
        the degree, replicated tensors don't — the bench's
        `tp_max_model_bytes_per_chip` numerator."""
        import jax
        from jax.sharding import PartitionSpec as P

        total = 0
        for i, p in enumerate(params):
            spec = self.param_specs[i]
            if isinstance(spec, dict):
                for k, v in p.items():
                    total += v.nbytes // (self.degree
                                          if spec[k] != P() else 1)
            else:
                total += sum(x.nbytes
                             for x in jax.tree_util.tree_leaves(p))
        return total
