"""Robust model serving: admission control, deadlines, circuit breaking,
safe hot reload.

The reference stack ships a serve-from-streams path
(`DL4jServeRouteBuilder.java`, SURVEY §dl4j-streaming) with none of the
protections a "heavy traffic from millions of users" tier needs: a slow
model backs requests up without bound, a broken model serves garbage
forever, and swapping a model under live traffic means a window of broken
predictions. `ModelServer` wraps a fitted `MultiLayerNetwork` /
`ComputationGraph` behind four defenses, mirroring what PRs 1–3 did for
training:

- **admission control** — a bounded request queue plus a concurrency
  limiter sized to device capacity (`max_concurrent` executor threads,
  each dispatching one device step at a time). A full queue raises the
  typed `ServerOverloadedError` carrying a `retry_after` hint (EWMA step
  latency × backlog) instead of queueing unboundedly — load is shed at
  the door, never absorbed until the process OOMs.
- **per-request deadlines** — `predict(x, timeout=...)` stamps a
  monotonic deadline. Expired requests are shed (typed
  `DeadlineExceededError`) BEFORE touching the accelerator — at pop time
  and again at batch-assembly time — and batch assembly never waits past
  the earliest deadline in the forming batch.
- **adaptive micro-batching** — concurrent predict calls with compatible
  shapes coalesce into one device step (rows padded up to the next
  power-of-two bucket ≤ `max_batch_size`, so the jitted forward compiles
  O(log max_batch) shapes, not one per arrival pattern). Assembly waits
  at most `batch_window` seconds for stragglers, bounded by the earliest
  deadline.
- **circuit breaking** — `breaker_threshold` CONSECUTIVE inference
  failures (device-step exceptions or non-finite outputs, screened via
  the PR-3 `optimize.health.non_finite_array_reason` helper) open the
  breaker: requests fail fast with the typed `ServiceUnavailableError`
  (`retry_after` = time until half-open) without touching the device.
  After `breaker_reset_timeout` the breaker half-opens and admits ONE
  probe batch; a healthy probe closes it, a failed probe re-opens it.
- **safe hot reload** — `reload(source)` loads a candidate from a path or
  a PR-2 `CheckpointStore` (integrity manifest verified before any bytes
  are trusted), validates it on a canary batch (finite outputs, input
  accepted, output width matching the live model), then swaps under a
  read-write lock: in-flight requests finish on the old model, the first
  request after the swap sees the new one, and a failed candidate is
  rejected with a typed `ModelValidationError` /
  `CheckpointCorruptError` while the old model keeps serving — no
  request ever observes the bad model.

`shutdown(drain_timeout)` stops admission (typed `ServerClosedError`),
drains queued + in-flight requests for up to `drain_timeout` seconds,
then fails whatever remains — a shutdown is a bounded event, not a hang.

**Generation serving** — construct with `generation={...}`
(`serving.decode_engine.DecodeEngine` kwargs, or `True` for defaults)
and `generate(prompt_ids, n_tokens, ...)` serves autoregressive
generation through the continuous-batching decode engine (paged KV
cache + chunked prefill): requests ride the same
admission-control/deadline/breaker discipline as `predict` (typed
`ServerOverloadedError` + `retry_after` on overload, typed
`OutOfPagesError` when the KV page pool's wait room is full; a
deadline expiring in the queue sheds before prefill; one expiring in
flight frees its decode slot AND its pages), and `reload()` drains the
engine's slots so in-flight generations finish on the old weights
before the swap. `stats()` surfaces `pages_in_use`,
`page_fragmentation_pct`, and `prefill_chunks` top-level.

Chaos seam: `infer_hooks=[hook]` fires `hook(phase, info)` at
`pre_step` / `post_step` around every device dispatch —
`serving.chaos.SlowInferenceInjector` and `BrokenModelInjector` use it to
drive the overload and breaker ladders end to end
(`tests/test_serving.py`).

Observability (`serving/observability.py`): every request joins (or
mints) a `Trace` — queue-wait and device-step spans recorded by the
executor, the end decision (``served`` / typed-error class name)
stamped at the `predict` exit and attached to the raised
`ServingError` (`attach_trace`) so gateway error payloads carry the
timeline. The server owns a `MetricsRegistry` (predict-latency
histogram, queue-depth/in-flight gauges, its own ``stats()`` adopted
as a component snapshot) and a `FlightRecorder` ring (completed
timelines, breaker transitions, reload/rollback events), both shared
with the lazily-built decode engine and exposed via
`metrics_text()`/`flight_record()` → the gateway ``metrics`` /
``flight_record`` RPCs. See docs/observability.md.
"""
from __future__ import annotations

import collections
import contextlib
import logging
import threading
import time
from pathlib import Path
from typing import Callable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.serving import observability
from deeplearning4j_tpu.util.concurrency import assert_owned

logger = logging.getLogger("deeplearning4j_tpu")


# ---------------------------------------------------------------------------
# typed give-up errors


class ServingError(RuntimeError):
    """Base class for every typed serving-tier give-up."""


class ServerOverloadedError(ServingError):
    """Admission control shed this request: the bounded queue is full.
    `retry_after` (seconds) estimates when capacity frees up."""

    def __init__(self, msg: str, retry_after: float = 0.1):
        super().__init__(msg)
        self.retry_after = retry_after


class OutOfPagesError(ServerOverloadedError):
    """The decode engine's paged KV pool cannot reserve enough pages
    for this request right now: memory-side admission control shed it
    at the door. Subclasses `ServerOverloadedError` so every existing
    overload handler (gateway retry_after payloads, serve-route shed
    counting) composes unchanged; `retry_after` estimates when enough
    pages free up."""


class DeadlineExceededError(ServingError):
    """The request's deadline expired before (or while) it could be
    served; it was shed without touching the accelerator."""


class ServiceUnavailableError(ServingError):
    """The circuit breaker is open (or the probe slot is taken while
    half-open): recent inference failed repeatedly, so requests fail
    fast instead of queueing behind a broken model. `retry_after`
    (seconds) is the time until the next half-open probe window."""

    def __init__(self, msg: str, retry_after: float = 0.1):
        super().__init__(msg)
        self.retry_after = retry_after


class InferenceFailedError(ServingError):
    """The device step for this request's batch raised, or produced
    non-finite outputs. Counted by the circuit breaker."""


class ModelValidationError(ServingError):
    """A hot-reload candidate failed canary validation (raised on the
    canary batch, produced non-finite outputs, or changed the output
    width). The previous model is still serving."""


class ServerClosedError(ServingError):
    """The server is shut (or shutting) down; no new requests are
    admitted and unfinished queued requests fail with this."""


class TenantQuotaExceededError(ServingError):
    """This tenant's own token-rate quota is exhausted — deliberately
    NOT a `ServerOverloadedError` subclass: a flooding tenant must hear
    about ITS budget, and well-behaved co-tenants must never see this
    error for someone else's flood. `retry_after` (seconds) is when the
    tenant's token bucket refills enough to admit this request."""

    def __init__(self, msg: str, retry_after: float = 0.1):
        super().__init__(msg)
        self.retry_after = retry_after


class AutoscaleError(ServingError):
    """The autoscaler could not complete a scale action: the supervisor
    exhausted its spawn budget, the pool refused the mutation, or the
    new replica never passed the probe ladder. The pool keeps serving
    at its previous size."""


# ---------------------------------------------------------------------------
# read-write lock (hot reload swaps under the write side; every device
# step holds the read side, so in-flight requests finish on the old model)


class _RWLock:
    """Writer-preferring reader-writer lock: once a writer is waiting,
    new readers queue behind it, so a reload cannot be starved by a
    steady request stream."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    @contextlib.contextmanager
    def read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1
        try:
            yield
        finally:
            with self._cond:
                self._readers -= 1
                if not self._readers:
                    self._cond.notify_all()

    @contextlib.contextmanager
    def write(self):
        with self._cond:
            self._writers_waiting += 1
            while self._writer or self._readers:
                self._cond.wait()
            self._writers_waiting -= 1
            self._writer = True
        try:
            yield
        finally:
            with self._cond:
                self._writer = False
                self._cond.notify_all()


# ---------------------------------------------------------------------------
# circuit breaker


class CircuitBreaker:
    """Classic three-state breaker over consecutive failures.

    closed --(threshold consecutive failures)--> open
    open --(reset_timeout elapsed)--> half_open (one probe admitted)
    half_open --(probe ok)--> closed; --(probe fails)--> open

    Thread-safe; all transitions are logged. Successes anywhere reset
    the consecutive-failure count."""

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout: float = 5.0,
                 on_event: Optional[Callable[[str], None]] = None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if reset_timeout <= 0:
            raise ValueError("reset_timeout must be > 0")
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self.on_event = on_event
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        self._pending_events: List[str] = []
        self.opens = 0  # telemetry: how many times the breaker tripped

    def _transition(self, state: str) -> None:
        if state != self._state:
            logger.warning("circuit breaker: %s -> %s", self._state, state)
            self._state = state
            if state == "open":
                self.opens += 1
                self._opened_at = time.monotonic()
            if self.on_event is not None:
                self._pending_events.append(state)

    def _take_events(self) -> List[str]:
        events, self._pending_events = self._pending_events, []
        return events

    def _fire(self, events: List[str]) -> None:
        # OUTSIDE the lock: a callback that reads .state / calls reset()
        # must not deadlock against the transition that fired it
        for state in events:
            self.on_event(state)

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            out, events = self._state, self._take_events()
        self._fire(events)
        return out

    def _maybe_half_open(self) -> None:
        if self._state == "open" and \
                time.monotonic() - self._opened_at >= self.reset_timeout:
            self._transition("half_open")
            self._probe_in_flight = False

    def _reject_open_locked(self) -> None:
        assert_owned(self._lock, "CircuitBreaker._reject_open_locked")
        if self._state == "open":
            remaining = max(
                0.0, self.reset_timeout
                - (time.monotonic() - self._opened_at))
            raise ServiceUnavailableError(
                f"circuit breaker open after "
                f"{self._consecutive_failures} consecutive inference "
                f"failures; retry in {remaining:.3f}s",
                retry_after=remaining)

    def reject_if_open(self) -> None:
        """Fail-fast door check: raises `ServiceUnavailableError` while
        open, NEVER consumes the half-open probe slot (only `acquire`,
        whose caller always reports success/failure, may take it — a
        door check that took the slot could never give it back)."""
        with self._lock:
            self._maybe_half_open()
            try:
                self._reject_open_locked()
            finally:
                events = self._take_events()
        self._fire(events)

    def acquire(self) -> bool:
        """Gate one unit of work. Raises `ServiceUnavailableError` when
        open (retry_after = time to half-open) or when half-open with
        the probe slot already taken. Returns True when the caller IS
        the half-open probe — it MUST pass that token back to
        `record_success`/`record_failure` (both release the slot; only
        the probe's outcome drives half-open transitions, so a stale
        pre-open step finishing late cannot corrupt the probe state)."""
        with self._lock:
            self._maybe_half_open()
            try:
                self._reject_open_locked()
                probe = False
                if self._state == "half_open":
                    if self._probe_in_flight:
                        raise ServiceUnavailableError(
                            "circuit breaker half-open: probe in flight",
                            retry_after=self.reset_timeout / 4)
                    self._probe_in_flight = True
                    probe = True
            finally:
                events = self._take_events()
        self._fire(events)
        return probe

    def record_success(self, probe: bool = False) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if probe:
                self._probe_in_flight = False
                self._transition("closed")
            # a stale (non-probe) success during open/half_open only
            # resets the failure streak — the probe decides the state
            events = self._take_events()
        self._fire(events)

    def record_failure(self, probe: bool = False) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if probe:
                self._probe_in_flight = False
                self._transition("open")  # failed probe: re-open
            elif self._state == "closed" and \
                    self._consecutive_failures >= self.failure_threshold:
                self._transition("open")
            events = self._take_events()
        self._fire(events)

    def reset(self) -> None:
        """Force-close (used after a successful hot reload: the new
        model's health is proven by the canary, not inherited)."""
        with self._lock:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            self._transition("closed")
            events = self._take_events()
        self._fire(events)


# ---------------------------------------------------------------------------
# requests


class _Request:
    __slots__ = ("features", "deadline", "event", "result", "error",
                 "enqueued_at", "trace")

    def __init__(self, features, deadline: Optional[float]):
        self.features = features
        self.deadline = deadline
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.enqueued_at = time.monotonic()
        # the request's timeline, carried across the caller-thread →
        # executor-thread hop (thread-locals don't cross it)
        self.trace = observability.NULL_TRACE

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None and \
            (now if now is not None else time.monotonic()) >= self.deadline

    def finish(self, result=None, error: Optional[BaseException] = None):
        self.result = result
        self.error = error
        self.event.set()


def _bucket(n: int, max_batch: int) -> int:
    """Next power-of-two ≥ n, capped at max_batch — bounds the number of
    distinct shapes the jitted forward ever compiles."""
    b = 1
    while b < n:
        b <<= 1
    return min(b, max_batch)


# ---------------------------------------------------------------------------
# the server


class ModelServer:
    """Admission-controlled, deadline-aware, breaker-protected serving
    wrapper around a fitted network (see module docstring).

    `predict(x)` is thread-safe and blocking: any number of caller
    threads (gateway handlers, serve routes) may call it concurrently;
    compatible concurrent calls coalesce into one device step.
    """

    def __init__(self, net, *, max_queue: int = 64, max_concurrent: int = 1,
                 max_batch_size: int = 64, batch_window: float = 0.002,
                 default_timeout: Optional[float] = None,
                 breaker_threshold: int = 5,
                 breaker_reset_timeout: float = 5.0,
                 canary: Optional[np.ndarray] = None,
                 auto_canary: bool = True,
                 infer_hooks: Sequence[Callable] = (),
                 pad_batches: bool = True,
                 generation: Optional[dict] = None,
                 quantize: Optional[dict] = None,
                 drift_gate: Optional[dict] = None,
                 parallel: Optional[dict] = None):
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if max_concurrent < 1:
            raise ValueError("max_concurrent must be >= 1")
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        # quantized serving tier (serving/quantize.py): weights are
        # quantized HERE — at construction and again on every reload
        # candidate, BEFORE canary/drift validation, so the exact
        # numerics that will serve are the numerics that get gated
        if quantize is not None:
            unknown = set(quantize) - {"weights", "kv"}
            if unknown:
                raise ValueError(f"unknown quantize keys: {sorted(unknown)}")
            if quantize.get("weights") not in (None, "int8", "bf16"):
                raise ValueError(
                    "quantize['weights'] must be 'int8' or 'bf16', got "
                    f"{quantize.get('weights')!r}")
            if quantize.get("kv") not in (None, "int8"):
                raise ValueError("quantize['kv'] must be 'int8', got "
                                 f"{quantize.get('kv')!r}")
        self._quantize_cfg = dict(quantize) if quantize else None
        # tensor-parallel serving (serving/tp_engine.py): validated and
        # applied by the DecodeEngine at construction; the server only
        # routes the config, so the batch-predict path stays
        # single-device (generation is where HBM capacity binds)
        if parallel is not None and not isinstance(parallel, dict):
            raise ValueError('parallel must be a dict like {"tp": N}')
        self._parallel_cfg = dict(parallel) if parallel else None
        if drift_gate is not None:
            unknown = set(drift_gate) - {"eval_set", "max_argmax_drift",
                                         "max_ppl_delta"}
            if unknown:
                raise ValueError(
                    f"unknown drift_gate keys: {sorted(unknown)}")
            if drift_gate.get("eval_set") is None:
                raise ValueError(
                    "drift_gate needs an 'eval_set' (pinned (B, T) token "
                    "ids the argmax-drift / perplexity gates score)")
        self._drift_gate = dict(drift_gate) if drift_gate else None
        self.drift_gate_checks = 0  # guarded by: _cond
        self.drift_gate_failures = 0  # guarded by: _cond
        self._last_drift: Optional[dict] = None  # guarded by: _cond
        wq = self._quantize_cfg.get("weights") if self._quantize_cfg \
            else None
        self._weight_bits = {"int8": 8, "bf16": 16}.get(wq, 32)
        if wq is not None:
            from deeplearning4j_tpu.serving.quantize import (
                quantize_net_weights,
            )

            raw = net
            net = quantize_net_weights(net, wq)
            # the raw full-precision net IS the drift reference (and the
            # only honest one: the quantized clone can't re-derive it)
            self._raw_net = raw
        else:
            self._raw_net = net
        self._net = net  # guarded by: _rwlock.write()
        self.max_queue = max_queue
        self.max_batch_size = max_batch_size
        self.batch_window = batch_window
        self.default_timeout = default_timeout
        self.pad_batches = pad_batches
        self.infer_hooks: List[Callable] = list(infer_hooks)
        self.breaker = CircuitBreaker(failure_threshold=breaker_threshold,
                                      reset_timeout=breaker_reset_timeout)
        # observability: registry + flight recorder, shared with the
        # decode engine (built lazily below) so one snapshot / one dump
        # covers both serving paths. Breaker transitions ring as events.
        self.metrics = observability.MetricsRegistry()
        self.recorder = observability.FlightRecorder()
        self.metrics.register_stats("model_server", self.stats)
        self._latency_hist = self.metrics.histogram(
            "model_server_predict_latency_ms")
        self._step_hist = self.metrics.histogram("model_server_step_ms")
        self.metrics.gauge("model_server_queue_depth",
                           lambda: len(self._queue))
        self.metrics.gauge("model_server_in_flight",
                           lambda: self._in_flight)
        self.breaker.on_event = self._breaker_event
        self._canary = None if canary is None else np.asarray(canary)  # guarded by: _cond
        # with auto_canary, the first successfully-served request donates
        # its leading row as the reload-validation batch — a server that
        # has taken traffic can always validate a candidate
        self.auto_canary = auto_canary
        self._rwlock = _RWLock()
        self._reload_lock = threading.Lock()
        self.model_version = 0  # guarded by: _rwlock.write()
        # queue machinery: a deque under one condition (executors need to
        # peek deadlines and pop several compatible requests per batch,
        # which queue.Queue cannot express)
        self._cond = threading.Condition()
        self._queue: collections.deque = collections.deque()  # guarded by: _cond
        self._in_flight = 0  # guarded by: _cond
        self._closed = False  # guarded by: _cond
        self._step_latency_ewma = 0.01  # guarded by: _cond (retry_after hint seed)
        # generation tier: DecodeEngine kwargs (or {} for defaults);
        # the engine itself is built lazily on the first generate() so a
        # predict-only server never pays for it
        self._generation_cfg = {} if generation is True else generation
        self._engine = None  # guarded by: _engine_lock
        self._engine_lock = threading.Lock()
        # cluster prefix directory binding, stored until the lazy engine
        # exists (a predict-only server never builds one just to bind)
        self._prefix_bind = None  # guarded by: _engine_lock
        # counters (observable state for tests/telemetry)
        self.served = 0          # guarded by: _cond — requests completed
        self.batches = 0         # guarded by: _cond — device steps dispatched
        self.rows_dispatched = 0  # guarded by: _cond — rows across micro-batches
        self.shed_overload = 0   # guarded by: _cond — rejected at admission
        self.shed_deadline = 0   # guarded by: _cond — expired pre device step
        self.shed_unavailable = 0  # guarded by: _cond — open-breaker rejects
        self.failures = 0        # guarded by: _cond — bad device steps
        self.reloads = 0  # guarded by: _reload_lock
        self.reload_rejections = 0  # guarded by: _cond
        if wq is not None and self._drift_gate is not None:
            # gate the construction-time quantization too: a server must
            # not START serving numerics it would refuse to reload into
            self._check_drift_gate(self._raw_net, self._net)
        self._threads = [
            threading.Thread(target=self._serve_loop, daemon=True,
                             name=f"model-server-exec-{i}")
            for i in range(max_concurrent)]
        for t in self._threads:
            t.start()

    # -- public surface ----------------------------------------------------
    @property
    def net(self):
        """The live model (read-only peek; swapped by `reload`)."""
        return self._net

    def _breaker_event(self, state: str) -> None:
        # fired by CircuitBreaker OUTSIDE its lock (see _fire)
        self.recorder.event("breaker", state=state)
        self.metrics.counter("model_server_breaker_transitions").inc()

    def _shed_obs(self, trace, err: BaseException, kind: str = "predict"):
        """Stamp a typed give-up onto the request's timeline, attach the
        timeline to the error (so it rides the wire), and pin it in the
        flight recorder's failure ring."""
        decision = type(err).__name__
        trace.finish(decision)
        observability.attach_trace(err, trace)
        self.recorder.record(trace, decision, kind=kind)

    def flight_record(self) -> dict:
        """Serialized flight-recorder dump (completed request timelines,
        pinned failures, breaker/reload scheduler events) — the payload
        of the gateway ``flight_record`` RPC."""
        return self.recorder.dump()

    def metrics_text(self, labels=None) -> str:
        """Prometheus-style text exposition of the metrics registry —
        the payload of the gateway ``metrics`` RPC. `labels` (e.g.
        ``{"model": name}``) keep multi-model expositions collision-
        free on one scrape page."""
        return self.metrics.exposition(labels=labels)

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    def stats(self) -> dict:
        with self._cond:
            queued = len(self._queue)
            in_flight = self._in_flight
            ewma_ms = 1e3 * self._step_latency_ewma
            # batch starvation observability: how full are dispatched
            # micro-batches relative to device capacity (max_batch_size)?
            # Low batch_fill_pct = the chip runs under-occupied steps —
            # raise batch_window / offered concurrency, not kernel work
            fill = (100.0 * self.rows_dispatched
                    / (self.batches * self.max_batch_size)
                    if self.batches else 0.0)
        out = {"served": self.served, "batches": self.batches,
               "batch_fill_pct": round(fill, 1),
               "shed_overload": self.shed_overload,
               "shed_deadline": self.shed_deadline,
               "shed_unavailable": self.shed_unavailable,
               "failures": self.failures, "reloads": self.reloads,
               "reload_rejections": self.reload_rejections,
               "breaker_state": self.breaker.state,
               "breaker_opens": self.breaker.opens,
               "model_version": self.model_version, "queued": queued,
               # the routing contract (serving/replica_pool.py leans on
               # these top-level): how loaded is this replica right now,
               # and how long does one device step take it.
               # "queue_depth" deliberately aliases the pre-existing
               # "queued" — the routing contract name vs the historical
               # one; both are pinned by tests
               "in_flight": in_flight, "queue_depth": queued,
               "ewma_latency_ms": round(ewma_ms, 3),
               # quantized-serving tier: numeric, unconditional (the
               # stats-schema contract + Prometheus exposition carry
               # them for every config, quantized or not)
               "weight_bits": self._weight_bits,
               "drift_gate_checks": self.drift_gate_checks,
               "drift_gate_failures": self.drift_gate_failures}
        with self._cond:
            last_drift = self._last_drift
        if last_drift is not None:
            out["drift"] = dict(last_drift)
        engine = self._engine
        if engine is not None:
            gen = engine.stats()
            # the decode-side starvation number, surfaced at top level
            # next to batch_fill_pct: the two tell an operator whether
            # they are batch-starved on predict and/or generation
            out["slot_occupancy_pct"] = gen["slot_occupancy_pct"]
            # paged-KV health, also top-level: pages_in_use vs the pool
            # is the memory-side occupancy, page_fragmentation_pct the
            # allocated-but-unused tail, prefill_chunks how much prompt
            # work is riding the interleaved chunked path
            out["pages_in_use"] = gen["pages_in_use"]
            out["page_fragmentation_pct"] = gen["page_fragmentation_pct"]
            out["prefill_chunks"] = gen["prefill_chunks"]
            # latency tier (prefix cache / speculative decoding), when
            # enabled: the two headline ratios an operator tunes by
            for key in ("prefix_hit_tokens_pct", "spec_accept_rate",
                        "spec_tokens_per_step"):
                if key in gen:
                    out[key] = gen[key]
            # QoS control-plane counters, top-level next to the shed
            # family: how often the batch lane yielded to interactive
            # pressure, and how many requests the SLO estimator turned
            # away before prefill
            out["preemptions"] = gen["preemptions"]
            out["slo_sheds"] = gen["slo_sheds"]
            out["shed_quota"] = gen["shed_quota"]
            out["generation"] = gen
        return out

    def predict(self, x, timeout: Optional[float] = None) -> np.ndarray:
        """Serve one request: features `x` of shape (B, ...). Blocks
        until the result is ready or a typed give-up fires
        (`ServerOverloadedError`, `DeadlineExceededError`,
        `ServiceUnavailableError`, `InferenceFailedError`,
        `ServerClosedError`). `timeout` (seconds; `default_timeout` when
        None) stamps the request's deadline."""
        x = np.asarray(x)
        if x.ndim < 2:
            raise ValueError(
                f"predict expects a batched (B, ...) array, got shape "
                f"{x.shape} — wrap a single example as x[None]")
        timeout = self.default_timeout if timeout is None else timeout
        deadline = None if timeout is None else time.monotonic() + timeout
        # join the upstream trace (gateway/pool, via thread-local) or
        # mint one at this in-process entry point
        trace = observability.maybe_trace()
        # fail fast at the door while the breaker is open: these requests
        # must not consume queue capacity that recovered traffic needs
        # (reject_if_open never takes the half-open probe slot — only the
        # executor's acquire/record pair may)
        try:
            self.breaker.reject_if_open()
        except ServiceUnavailableError as e:
            with self._cond:
                self.shed_unavailable += 1
            self._shed_obs(trace, e)
            raise
        req = _Request(x, deadline)
        req.trace = trace
        err: Optional[ServingError] = None
        with self._cond:
            # a FULL queue must be swept of already-dead entries BEFORE
            # the queue-full verdict: expired requests padding the
            # queue are not real backpressure, and each swept entry
            # fails with ITS truth (DeadlineExceededError) instead of
            # being the reason a live request hears
            # ServerOverloadedError
            now = time.monotonic()
            if len(self._queue) >= self.max_queue:
                live = [r for r in self._queue
                        if not self._pop_expired(r, now)]
                if len(live) != len(self._queue):
                    self._queue.clear()
                    self._queue.extend(live)
            if self._closed:
                err = ServerClosedError("model server is shut down")
            elif deadline is not None and deadline <= now:
                self.shed_deadline += 1
                err = DeadlineExceededError(
                    "deadline expired before admission; request shed at "
                    "the door")
            elif len(self._queue) >= self.max_queue:
                self.shed_overload += 1
                # backlog ÷ capacity × EWMA step latency: how long until
                # the queue has likely drained enough to admit us
                retry = max(0.001, self._step_latency_ewma
                            * (len(self._queue) / max(1, len(self._threads))
                               / max(1, self.max_batch_size) + 1))
                err = ServerOverloadedError(
                    f"request queue full ({self.max_queue} pending); "
                    f"retry in {retry:.3f}s", retry_after=retry)
            else:
                trace.event("admission", queue_depth=len(self._queue))
                self._queue.append(req)
                self._cond.notify()
        if err is not None:
            self._shed_obs(trace, err)
            raise err
        wait = None if deadline is None \
            else max(0.0, deadline - time.monotonic()) + 30.0
        if not req.event.wait(wait):  # executor always finishes requests;
            err = InferenceFailedError(  # this is a belt-and-braces bound
                "request was never completed (executor stalled)")
            self._shed_obs(trace, err)
            raise err
        if req.error is not None:
            self._shed_obs(trace, req.error)
            raise req.error
        with self._cond:
            self.served += 1
        trace.finish("served")
        self._latency_hist.observe(
            1e3 * (time.monotonic() - req.enqueued_at))
        self.recorder.record(trace, "served", kind="predict")
        return req.result

    def __call__(self, x, timeout: Optional[float] = None) -> np.ndarray:
        return self.predict(x, timeout=timeout)

    def pending(self) -> int:
        """Queued + in-flight request count, across BOTH serving paths
        (predict queue AND the decode engine's queued/in-slot
        generations) — the load number a least-loaded router compares,
        and the drain condition a replica-at-a-time rolling reload
        waits on. A replica saturated with multi-second generates must
        not read as idle to the router."""
        with self._cond:
            n = len(self._queue) + self._in_flight
        engine = self._engine
        if engine is not None:
            n += engine.pending()
        return n

    def probe(self, x=None,
              timeout: Optional[float] = None) -> Optional[bool]:
        """Active health probe: serve one canary-sized batch through the
        FULL predict path (admission, batching, breaker, non-finite
        screen). Three-valued so a router can tell sickness from load:

        - **True** — the canary was served end to end.
        - **False** — sickness: the step failed, outputs were
          non-finite, or the breaker is open. (A probe arriving while
          the breaker is half-open IS the half-open probe, so repeated
          probing drives a broken-then-healed replica back to closed.)
        - **None** — inconclusive: the probe was shed on LOAD
          (queue-full `ServerOverloadedError`) or TIME
          (`DeadlineExceededError` while queued behind real traffic).
          A busy replica proves nothing either way — treating this as
          failure would let a saturating burst evict healthy replicas
          and cascade a pool into degraded mode.

        With no batch available (none passed, no canary armed yet) the
        probe degrades to a breaker-state check — `None` unless the
        breaker is open (it cannot prove health, only flag known
        sickness)."""
        batch = x if x is not None else self._canary
        if batch is None:
            return False if self.breaker.state == "open" else None
        try:
            out = self.predict(np.asarray(batch), timeout=timeout)
        except (ServerOverloadedError, DeadlineExceededError):
            return None  # load/time shed: not evidence of sickness
        except ServingError:
            return False
        assert out is not None
        return True

    def restore_model(self, net) -> int:
        """Swap `net` in WITHOUT canary validation — the rollback seam a
        replica pool uses to put known-good old weights back after a
        failed rolling reload (their health was proven by having
        served). Same swap discipline as `reload`: write lock (in-flight
        finishes on the outgoing model), engine drain, breaker reset,
        monotonic version bump. Returns the new model_version."""
        with self._reload_lock:
            with self._rwlock.write():
                self._net = net
                self._raw_net = net  # restored weights are their own
                self.model_version += 1  # drift reference
                version = self.model_version
            with self._engine_lock:
                engine = self._engine
            if engine is not None:
                engine.drain_and_swap(net)
            self.breaker.reset()
            self.recorder.event("reload", decision="rolled-back",
                                model_version=version)
            logger.warning("model server: restored previous model "
                           "(model_version=%d)", version)
            return version

    # -- generation (continuous batching) ----------------------------------
    def _ensure_engine(self):
        if self._generation_cfg is None:
            raise ServingError(
                "generation serving is not enabled — construct the server "
                "with generation={...} (DecodeEngine kwargs) or "
                "generation=True")
        # closed-check and lazy construction share the engine lock, and
        # shutdown() snapshots the engine under the same lock — a
        # generate() racing shutdown() either sees _closed here or
        # finishes building an engine shutdown() will then drain
        with self._engine_lock:
            with self._cond:
                if self._closed:
                    raise ServerClosedError("model server is shut down")
            if self._engine is None:
                from deeplearning4j_tpu.serving.decode_engine import (
                    DecodeEngine,
                )

                cfg = dict(self._generation_cfg)
                cfg.setdefault("max_queue", self.max_queue)
                cfg.setdefault("breaker", self.breaker)
                # one recorder/registry across both serving paths: the
                # engine's scheduler events and generate timelines land
                # in the same dump as predicts and breaker transitions
                cfg.setdefault("recorder", self.recorder)
                cfg.setdefault("metrics", self.metrics)
                # the server's KV quantization flows to the engine
                # unless the generation cfg overrides it explicitly
                if self._quantize_cfg and self._quantize_cfg.get("kv"):
                    cfg.setdefault(
                        "quantize", {"kv": self._quantize_cfg["kv"]})
                if self._parallel_cfg:
                    cfg.setdefault("parallel", self._parallel_cfg)
                self._engine = DecodeEngine(self._net, **cfg)
                if self._prefix_bind is not None:
                    a, kw = self._prefix_bind
                    self._engine.bind_prefix_directory(*a, **kw)
            return self._engine

    # streaming sinks (`on_token=`) reach the engine in-process here;
    # remote adapters that cannot ship a callable override this False
    supports_stream_sink = True

    def generate(self, prompt_ids, n_tokens: int, *,
                 temperature: float = 0.0, seed: int = 0,
                 timeout: Optional[float] = None,
                 tenant: Optional[str] = None,
                 priority: str = "interactive",
                 logprobs: int = 0,
                 on_token: Optional[Callable] = None):
        """Serve one generation request through the continuous-batching
        decode engine (`serving.decode_engine.DecodeEngine`): admitted
        into a decode slot as soon as one frees, decoded alongside every
        other in-flight request, returned the moment ITS tokens are done
        — never waiting on another request's tail. Shares the server's
        circuit breaker and admission discipline; typed give-ups match
        `predict`'s. `tenant`/`priority` feed the engine's QoS admission
        path (per-tenant token-rate quotas; `"interactive"` preempts
        the `"batch"` lane under pressure). Returns the generated token
        ids (1-D int32) — or, with `logprobs=K > 0`, a dict
        `{"tokens", "logprobs"}` carrying per-step top-K entries.
        `on_token(cursor, token, logprob_entry)` streams each emitted
        token into a `serving.streaming.TokenStream` ring."""
        engine = self._ensure_engine()
        timeout = self.default_timeout if timeout is None else timeout
        return engine.generate(prompt_ids, n_tokens,
                               temperature=temperature, seed=seed,
                               timeout=timeout, tenant=tenant,
                               priority=priority, logprobs=logprobs,
                               on_token=on_token)

    def set_tenant_quota(self, tenant: str, rate: Optional[float] = None,
                         burst: Optional[float] = None,
                         max_pages: Optional[int] = None,
                         weight: Optional[float] = None) -> None:
        """Set (or clear, with `rate=None` / `max_pages=None`) tenant
        `tenant`'s token-rate quota, KV page ceiling, and batch-lane
        fair-queueing `weight` on the decode engine — the admin seam
        the gateway's quota RPC lands on. Requires generation
        serving."""
        self._ensure_engine().set_tenant_quota(tenant, rate=rate,
                                               burst=burst,
                                               max_pages=max_pages,
                                               weight=weight)

    # -- KV handoff / live migration (kv_transfer) -------------------------
    def migrate_slots(self, wait: Optional[float] = 5.0) -> int:
        """Export every in-flight generation as a leased KV handoff
        (waiters raise the `SlotMigratedError` redirect; the pool
        resumes them on peers). 0 when generation was never exercised —
        an idle engine is not built just to migrate nothing."""
        with self._engine_lock:
            if self._engine is None:
                return 0
        return self._ensure_engine().migrate_slots(wait=wait)

    def resume_generate(self, payload: dict,
                        timeout: Optional[float] = None, *,
                        on_token: Optional[Callable] = None):
        """Admit a fetched KV handoff payload and return the TAIL
        tokens this server generates (typed `KVTransferError` when the
        payload fails validation against this server's weights or
        geometry). `on_token` re-attaches a stream sink so a mid-stream
        migration keeps publishing under the sender's cursor."""
        timeout = self.default_timeout if timeout is None else timeout
        return self._ensure_engine().resume_generate(payload,
                                                     timeout=timeout,
                                                     on_token=on_token)

    def fetch_handoff(self, handoff_id: str) -> dict:
        return self._ensure_engine().fetch_handoff(handoff_id)

    def commit_handoff(self, handoff_id: str) -> bool:
        return self._ensure_engine().commit_handoff(handoff_id)

    def abort_handoff(self, handoff_id: str) -> bool:
        return self._ensure_engine().abort_handoff(handoff_id)

    # -- cluster prefix cache (prefix_directory) ---------------------------
    def bind_prefix_directory(self, directory, holder_id: str,
                              peers=None, **kw) -> "ModelServer":
        """Join a cluster-global prefix directory (chainable). Applied
        to the decode engine immediately if it exists, else stored and
        applied when the lazy engine is first built — binding must not
        force an engine into a server that may never generate."""
        with self._engine_lock:
            self._prefix_bind = ((directory, holder_id, peers), kw)
            if self._engine is not None:
                self._engine.bind_prefix_directory(directory, holder_id,
                                                   peers, **kw)
        return self

    def prefix_depth(self, prompt_ids, tenant=None) -> int:
        with self._engine_lock:
            if self._engine is None:
                return 0
        return self._ensure_engine().prefix_depth(prompt_ids,
                                                  tenant=tenant)

    def prefix_chains(self) -> dict:
        with self._engine_lock:
            if self._engine is None:
                return {}  # never-generated: nothing resident to publish
        return self._ensure_engine().prefix_chains()

    def export_prefix(self, prompt_ids, have_pages: int = 0,
                      tenant=None, frame_pages=None,
                      timeout=None) -> dict:
        return self._ensure_engine().export_prefix(
            prompt_ids, have_pages=have_pages, tenant=tenant,
            frame_pages=frame_pages, timeout=timeout)

    def fetch_handoff_header(self, handoff_id: str, skip_pages: int = 0,
                             frame_pages=None) -> dict:
        return self._ensure_engine().fetch_handoff_header(
            handoff_id, skip_pages=skip_pages, frame_pages=frame_pages)

    def fetch_handoff_frame(self, handoff_id: str, frame: int,
                            skip_pages: int = 0,
                            frame_pages=None) -> dict:
        return self._ensure_engine().fetch_handoff_frame(
            handoff_id, frame, skip_pages=skip_pages,
            frame_pages=frame_pages)

    # -- batch assembly ----------------------------------------------------
    def _pop_expired(self, req: _Request, now: float) -> bool:  # graftlint: holds _cond
        if req.expired(now):
            self.shed_deadline += 1
            req.finish(error=DeadlineExceededError(
                f"deadline expired {now - req.deadline:.3f}s ago while "
                "queued; request shed before the device step"))
            return True
        return False

    def _assemble(self) -> Optional[List[_Request]]:
        """Pop one deadline-respecting micro-batch (None = shut down and
        queue drained). Waits up to `batch_window` after the first
        request for compatible stragglers, but never past the earliest
        deadline in the forming batch."""
        with self._cond:
            while True:
                now = time.monotonic()
                while self._queue and self._pop_expired(self._queue[0], now):
                    self._queue.popleft()
                if self._queue:
                    break
                if self._closed:
                    return None
                self._cond.wait(0.05)
            first = self._queue.popleft()
            batch = [first]
            rows = first.features.shape[0]
            shape, dtype = first.features.shape[1:], first.features.dtype
            # the straggler window closes EARLY enough that the batch can
            # still make its tightest deadline: deadline minus the EWMA
            # step latency, never merely the deadline itself
            margin = self._step_latency_ewma

            def _bound(end, deadline):
                return end if deadline is None \
                    else min(end, deadline - margin)

            window_end = _bound(time.monotonic() + self.batch_window,
                                first.deadline)
            while rows < self.max_batch_size:
                now = time.monotonic()
                if self._queue:
                    nxt = self._queue[0]
                    if self._pop_expired(nxt, now):
                        self._queue.popleft()
                        continue
                    if nxt.features.shape[1:] != shape \
                            or nxt.features.dtype != dtype \
                            or rows + nxt.features.shape[0] \
                            > self.max_batch_size:
                        break  # incompatible/overflow: next batch's problem
                    self._queue.popleft()
                    batch.append(nxt)
                    rows += nxt.features.shape[0]
                    window_end = _bound(window_end, nxt.deadline)
                    continue
                if now >= window_end or self._closed:
                    break
                self._cond.wait(window_end - now)
            self._in_flight += len(batch)
            return batch

    def _finish(self, batch: List[_Request], *, results=None, error=None):
        for i, req in enumerate(batch):
            req.finish(result=None if results is None else results[i],
                       error=error)
        with self._cond:
            self._in_flight -= len(batch)
            self._cond.notify_all()

    # -- the device step ---------------------------------------------------
    def _hook(self, phase: str, info: dict) -> None:
        for hook in self.infer_hooks:
            hook(phase, info)

    def _serve_loop(self) -> None:
        while True:
            batch = self._assemble()
            if batch is None:
                return
            # final pre-accelerator deadline screen: assembly may have
            # waited on a window; expired members are shed, not computed
            now = time.monotonic()
            live = []
            with self._cond:
                for req in batch:
                    if req.expired(now):
                        self.shed_deadline += 1
                        self._in_flight -= 1
                        req.finish(error=DeadlineExceededError(
                            "deadline expired during batch assembly; "
                            "request shed before the device step"))
                    else:
                        live.append(req)
                if not live:
                    self._cond.notify_all()
            if not live:
                continue
            for req in live:  # host-side bookkeeping only
                req.trace.add_timed("queue-wait", req.enqueued_at, now,
                                    batch=len(live))
            try:
                probe = self.breaker.acquire()
            except ServiceUnavailableError as e:
                with self._cond:
                    self.shed_unavailable += len(live)
                self._finish(live, error=e)
                continue
            try:
                results = self._execute(live)
            # graftlint: disable=typed-error  serve-loop firewall: the
            # failure is converted to InferenceFailedError and delivered to
            # every waiter below — re-raising would kill the serving thread
            except BaseException as e:
                self.breaker.record_failure(probe)
                with self._cond:
                    self.failures += len(live)
                err = e if isinstance(e, ServingError) else \
                    InferenceFailedError(
                        f"device step failed: {type(e).__name__}: {e}")
                logger.warning("model server: inference failure (%s)", err)
                self._finish(live, error=err)
                continue
            self.breaker.record_success(probe)
            self._finish(live, results=results)

    # graftlint: hot-loop
    def _execute(self, batch: List[_Request]) -> List[np.ndarray]:
        from deeplearning4j_tpu.optimize.health import non_finite_array_reason

        feats = np.concatenate([r.features for r in batch], axis=0) \
            if len(batch) > 1 else batch[0].features
        rows = feats.shape[0]
        padded = rows
        if self.pad_batches:
            padded = _bucket(rows, self.max_batch_size)
            if padded > rows:
                pad = np.zeros((padded - rows,) + feats.shape[1:],
                               feats.dtype)
                feats = np.concatenate([feats, pad], axis=0)
        info = {"batch_size": rows, "padded_size": padded,
                "requests": len(batch), "model_version": self.model_version}
        t0 = time.monotonic()
        with self._rwlock.read():
            self._hook("pre_step", info)
            out = np.asarray(self._net.output(feats))
            self._hook("post_step", info)
        t1 = time.monotonic()
        # one device step serves the whole micro-batch: the same span
        # lands on every member's timeline (host floats only — never
        # device values, per the host-sync recorder discipline)
        for req in batch:
            req.trace.add_timed("device-step", t0, t1, rows=rows,
                                padded=padded, requests=len(batch),
                                model_version=info["model_version"])
        self._step_hist.observe(1e3 * (t1 - t0))
        with self._cond:  # concurrent executors must not lose updates
            self._step_latency_ewma = (0.8 * self._step_latency_ewma
                                       + 0.2 * (t1 - t0))
            self.batches += 1
            self.rows_dispatched += rows
        out = out[:rows]
        reason = non_finite_array_reason(out, "outputs")
        if reason is not None:
            raise InferenceFailedError(
                f"model produced poisoned predictions: {reason}")
        if self._canary is None and self.auto_canary:
            # a concurrent executor may be donating its own row; the
            # first publication under the lock wins
            with self._cond:
                if self._canary is None:
                    self._canary = np.array(batch[0].features[:1])
        results, lo = [], 0
        for req in batch:
            hi = lo + req.features.shape[0]
            results.append(out[lo:hi])
            lo = hi
        return results

    # -- hot reload --------------------------------------------------------
    def reload(self, source, step: Optional[int] = None,
               canary: Optional[np.ndarray] = None) -> int:
        """Safely swap in a new model under live traffic.

        `source` is a checkpoint path or a `util.checkpoint_store
        .CheckpointStore` (newest verified step when `step` is None).
        The candidate's integrity manifest is verified before any bytes
        are trusted, then the candidate must pass canary validation
        (accept the canary batch, produce finite outputs of the live
        model's output width) BEFORE the swap: a failed candidate raises
        `CheckpointCorruptError` / `ModelValidationError` with the old
        model still serving. The swap itself happens under the write
        lock — in-flight requests finish on the old model — and resets
        the circuit breaker. Returns the new `model_version`."""
        with self._reload_lock:
            try:
                candidate = self._load_candidate(source, step)
                raw_candidate = candidate
                wq = self._quantize_cfg.get("weights") \
                    if self._quantize_cfg else None
                if wq is not None:
                    from deeplearning4j_tpu.serving.quantize import (
                        quantize_net_weights,
                    )

                    # quantize BEFORE validation: the canary + drift
                    # gates must score the numerics that will serve
                    candidate = quantize_net_weights(raw_candidate, wq)
                self._validate_candidate(candidate, canary)
                if wq is not None and self._drift_gate is not None:
                    self._check_drift_gate(raw_candidate, candidate)
            except Exception as e:
                # every pre-swap failure is a rejected deploy: integrity
                # (CheckpointCorruptError) and canary rejections alike
                # must show in the telemetry counter
                with self._cond:
                    self.reload_rejections += 1
                self.recorder.event("reload", decision="rejected",
                                    error=type(e).__name__)
                raise
            with self._rwlock.write():
                old_net = self._net
                old_raw = self._raw_net
                self._net = candidate
                self._raw_net = raw_candidate
                self.model_version += 1
                version = self.model_version
            # generation tier: the decode engine drains its slots (every
            # in-flight generation FINISHES on the old weights — its KV
            # cache was computed with them), swaps, and resumes serving
            # queued + new requests on the candidate. Runs after the
            # predict-path swap, outside the rwlock: generation steps
            # must keep dispatching while the engine drains. Snapshot
            # under _engine_lock so a concurrent FIRST generate() that is
            # mid-build cannot install an old-weights engine this reload
            # never sees (the lock blocks until the build lands)
            with self._engine_lock:
                engine = self._engine
            if engine is not None:
                try:
                    engine.drain_and_swap(candidate)
                except BaseException:
                    # the engine rejected/aborted the swap and still
                    # serves the old weights — roll the predict path
                    # back too, or the server would be split-brained
                    # (predict on v2, generate on v1). The version stays
                    # MONOTONIC: the rollback is its own version bump,
                    # so telemetry tagged with the candidate's version
                    # never aliases a later successful reload
                    with self._rwlock.write():
                        self._net = old_net
                        self._raw_net = old_raw
                        self.model_version += 1
                    with self._cond:
                        self.reload_rejections += 1
                    self.recorder.event("reload", decision="rolled-back",
                                        model_version=self.model_version)
                    raise
            self.breaker.reset()
            self.reloads += 1
            self.recorder.event("reload", decision="complete",
                                model_version=version)
            logger.warning("model server: hot reload complete "
                           "(model_version=%d)", version)
            return version

    def _load_candidate(self, source, step: Optional[int]):
        from deeplearning4j_tpu.util.checkpoint_store import (
            CheckpointStore,
            manifest_path_for,
            verify_manifest,
        )
        from deeplearning4j_tpu.util.serialization import restore_model

        if isinstance(source, CheckpointStore):
            if step is None:
                candidate, got = source.load_latest_verified(restore_model)
                logger.info("reload candidate: checkpoint step %d", got)
                return candidate
            source.verify(step)
            return restore_model(source.path_for(step))
        path = Path(source)
        if manifest_path_for(path).exists():
            verify_manifest(path)  # raises CheckpointCorruptError on drift
        else:
            logger.warning("reload candidate %s has no integrity manifest; "
                           "loading unverified", path)
        return restore_model(path)

    def _validate_candidate(self, candidate,
                            canary: Optional[np.ndarray]) -> None:
        from deeplearning4j_tpu.optimize.health import non_finite_array_reason

        canary = canary if canary is not None else self._canary
        if canary is None:
            logger.warning("model server: no canary batch configured — "
                           "hot-reload candidate swaps in UNVALIDATED "
                           "(pass canary= to the server or to reload())")
            return
        canary = np.asarray(canary)
        try:
            out = np.asarray(candidate.output(canary))
        except Exception as e:
            raise ModelValidationError(
                f"reload candidate rejected: canary batch of shape "
                f"{canary.shape} raised {type(e).__name__}: {e}") from e
        reason = non_finite_array_reason(out, "canary outputs")
        if reason is not None:
            raise ModelValidationError(
                f"reload candidate rejected: {reason} on the canary batch "
                "(non-finite parameters or a numerically broken graph)")
        try:
            live_out = np.asarray(self._net.output(canary))
        # graftlint: disable=typed-error  deliberate absorb: the LIVE
        # model failing the canary must not block reloading a good
        # candidate — the width contract check is simply skipped
        except Exception:
            live_out = None  # live model can't serve the canary; skip the
        if live_out is not None \
                and live_out.shape[1:] != out.shape[1:]:  # width contract
            raise ModelValidationError(
                f"reload candidate rejected: output shape {out.shape[1:]} "
                f"!= live model's {live_out.shape[1:]} — clients would "
                "observe a silent contract break")

    def _check_drift_gate(self, reference, candidate) -> None:
        """Quantization drift gates (serving/quantize.py): score the
        QUANTIZED candidate against its own full-precision reference on
        the pinned eval set — argmax token-disagreement rate (the
        number greedy serving actually exposes) and perplexity delta.
        A breach raises `ModelValidationError` BEFORE any swap, so the
        old weights keep serving and the reload machinery rolls back
        free. The reference is the raw candidate, never the live net:
        new weights legitimately differ from old ones — the gate
        polices what quantization changed, nothing else."""
        from deeplearning4j_tpu.serving.quantize import drift_report

        gate = self._drift_gate
        ids = np.asarray(gate["eval_set"])
        try:
            ref_out = np.asarray(reference.output(ids))
            cand_out = np.asarray(candidate.output(ids))
        except Exception as e:
            raise ModelValidationError(
                f"drift gate could not score the eval set "
                f"{ids.shape}: {type(e).__name__}: {e}") from e
        report = drift_report(ref_out, cand_out, ids)
        max_drift = gate.get("max_argmax_drift")
        max_ppl = gate.get("max_ppl_delta")
        breaches = []
        if max_drift is not None and report["argmax_drift"] > max_drift:
            breaches.append(
                f"argmax drift {report['argmax_drift']:.4f} > "
                f"{max_drift}")
        if max_ppl is not None and report["ppl_delta"] > max_ppl:
            breaches.append(
                f"perplexity delta {report['ppl_delta']:.4f} > {max_ppl}")
        with self._cond:
            self.drift_gate_checks += 1
            if breaches:
                self.drift_gate_failures += 1
            self._last_drift = report
        if breaches:
            self.recorder.event("drift-gate", decision="rejected",
                                **report)
            raise ModelValidationError(
                "quantized candidate rejected by drift gate: "
                + "; ".join(breaches))
        self.recorder.event("drift-gate", decision="accepted", **report)

    # -- shutdown ----------------------------------------------------------
    def shutdown(self, drain_timeout: float = 10.0) -> bool:
        """Stop admission, drain queued + in-flight requests for up to
        `drain_timeout` seconds, fail the rest with `ServerClosedError`,
        and join the executor threads. Returns True when every admitted
        request finished (clean drain), False when stragglers were
        failed at the timeout. Idempotent."""
        deadline = time.monotonic() + drain_timeout
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        with self._engine_lock:  # see _ensure_engine: closes the race
            engine = self._engine  # with a concurrent lazy construction
        drained = True
        engine_result: dict = {}
        engine_thread = None
        if engine is not None:
            # drain the decode engine CONCURRENTLY with the predict
            # queue: both run against the same drain_timeout budget, so
            # a long in-flight generation cannot starve queued predicts
            # of their drain window (nor stretch shutdown to 2x budget)
            engine_thread = threading.Thread(
                target=lambda: engine_result.update(
                    ok=engine.shutdown(drain_timeout=drain_timeout)),
                daemon=True)
            engine_thread.start()
        with self._cond:
            while self._queue or self._in_flight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    drained = False
                    while self._queue:
                        self._queue.popleft().finish(
                            error=ServerClosedError(
                                "server shut down before this request "
                                "could be served"))
                    break
                self._cond.wait(min(remaining, 0.05))
        for t in self._threads:
            t.join(max(0.0, deadline - time.monotonic()) + 1.0)
        if engine_thread is not None:
            engine_thread.join(max(0.0, deadline - time.monotonic()) + 5.0)
            drained = drained and engine_result.get("ok", False)
        if not drained:
            logger.warning("model server: shutdown drain timed out with "
                           "requests still pending")
        return drained
