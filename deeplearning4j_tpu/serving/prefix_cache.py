"""Refcounted shared-prefix KV cache over the decode engine's paged pool.

Production chat traffic is dominated by requests sharing a long system
prompt, yet the paged decode engine (PR 6) re-prefills that prefix per
request — the largest remaining lever on the generate path. The page
tables make sharing a refcount away: resident KV pages are already
position-indexed and write-masked, so two slots whose prompts share a
page-aligned prefix can point their page-table rows at the SAME pool
pages. This module is the bookkeeping for that sharing (PagedAttention-
style prefix caching, Kwon et al., SOSP '23):

- **keying**: a prompt is cut into page-size token chunks and hashed as
  a ROLLING CHAIN — each node's key is (parent node, chunk digest), so
  a chunk's identity includes everything before it and two prompts
  share a node only when their entire prefix up to that page matches.
  Digests are collision-guarded by an exact token comparison on lookup.
- **refcounting**: a node's page is held by `requests` (slots currently
  bound to it) plus the cache itself while the node is resident. The
  engine frees a page ONLY when it is neither bound nor cached —
  retiring a request whose prefix another slot still shares can never
  free the shared pages.
- **read-only sharing / copy-on-write at page granularity**: only pages
  FULLY covered by the prompt are ever cached, and a binding request
  recomputes its prompt from the first uncached page boundary into
  freshly allocated pages — shared pages are never written (decode
  writes land at positions >= t0, past every cached page), so the
  "copy" of copy-on-write is free: divergence starts in a new page.
- **LRU eviction under pressure**: when the engine's free list cannot
  cover an admission, `reclaim` releases unreferenced cached pages
  leaf-first in LRU order — caching borrows idle pages, it never
  reduces the pool's effective capacity (`OutOfPagesError` semantics
  are unchanged).
- **invalidation**: `clear()` drops every node; the engine calls it
  whenever the paged pools rebuild (weight swap via `drain_and_swap`,
  post-failure recovery) so stale pages can never serve new weights.
  A swap back to the SAME net object the pools were built under — the
  canary ladder's rollback (`ModelServer.restore_model` hands back the
  exact old net) — skips the rebuild entirely and PRESERVES the cache:
  the pages were computed under precisely those weights, so a failed
  deploy no longer pays a cold prefix cache on top of the rollback.
- **quantized pools**: with the engine's int8 KV tier
  (`quantize={"kv": "int8"}`, serving/quantize.py) cached pages hold
  int8 payloads plus their f32 scale-pool rows. Sharing is unchanged —
  the scale pages ride the same page table and refcounts — and a
  prefix hit re-serves pages exactly as quantized by the request that
  wrote them, so hit and miss paths decode identical values.

Thread-safety: externally synchronized — every method is called by the
`DecodeEngine` under its scheduler condition lock.
"""
from __future__ import annotations

import hashlib
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.util.concurrency import assert_owned


class _PrefixNode:
    """One cached page of prompt KV: `page_id` in the engine's pool,
    `tokens` the page's exact token content (collision guard),
    `requests` the number of slots currently bound to it, `children`
    how many cached nodes extend this chain (a node with children can
    not be evicted — its descendants would become unreachable pages).
    `chain` is the node's CLUSTER identity — the cumulative content
    digest of everything up to and including this page (see
    `chain_keys`) — and `version` the weight digest its KV was computed
    under."""

    __slots__ = ("seq", "parent", "page_id", "tokens", "requests",
                 "children", "last_used", "key", "chain", "version")

    def __init__(self, seq: int, parent: Optional["_PrefixNode"],
                 page_id: int, tokens: np.ndarray, key,
                 chain: str = "", version: Optional[str] = None):
        self.seq = seq
        self.parent = parent
        self.page_id = page_id
        self.tokens = tokens
        self.requests = 0
        self.children = 0
        self.last_used = 0
        self.key = key
        self.chain = chain
        self.version = version


def _digest(tokens: np.ndarray) -> bytes:
    return hashlib.blake2b(np.ascontiguousarray(tokens, np.int32).tobytes(),
                           digest_size=16).digest()


def _chain_root(tenant: Optional[str]) -> bytes:
    """Seed of the cumulative chain digest. The tenant is folded in
    HERE, at the root, so every downstream chain key — and therefore
    every directory entry — is tenant-scoped: one tenant's published
    prefixes are simply unreachable from another tenant's lookups."""
    h = hashlib.blake2b(b"dl4j-prefix-chain-v1", digest_size=16)
    if tenant is not None:
        h.update(b"\x00tenant\x00" + str(tenant).encode())
    return h.digest()


def chain_keys(prompt: np.ndarray, page_size: int,
               tenant: Optional[str] = None,
               digest_cache: Optional[list] = None) -> List[str]:
    """Instance-independent cumulative content keys, one per FULL page
    of `prompt`: ``key[i] = H(key[i-1] || digest(chunk_i))`` rooted at
    the tenant-scoped seed. Two hosts compute identical keys for
    identical (tenant, token-prefix) pairs — the directory's address
    space. `digest_cache` memoizes per-chunk digests exactly like
    `PrefixCache.lookup`'s."""
    prompt = np.asarray(prompt)
    page = int(page_size)
    run = _chain_root(tenant)
    out: List[str] = []
    for i in range(int(prompt.shape[0]) // page):
        if digest_cache is not None and i < len(digest_cache):
            dig = digest_cache[i]
        else:
            dig = _digest(np.ascontiguousarray(
                prompt[i * page:(i + 1) * page], np.int32))
            if digest_cache is not None:
                digest_cache.append(dig)
        run = hashlib.blake2b(run + dig, digest_size=16).digest()
        out.append(run.hex())
    return out


class PrefixCache:
    """Refcounted chain cache mapping page-aligned prompt prefixes to
    resident pool pages (see module docstring).

    Parameters
    ----------
    page_size : the engine's KV page length (positions per page) — the
        sharing granularity.
    max_pages : optional cap on resident cached pages. On insert past
        the cap the LRU unpinned tail is evicted first; if everything
        is pinned the new chunk is simply not cached. None = bounded
        only by pool pressure (`reclaim`).
    """

    def __init__(self, page_size: int, max_pages: Optional[int] = None):
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        if max_pages is not None and max_pages < 0:
            raise ValueError("max_pages must be >= 0 (or None)")
        self.page_size = page_size
        self.max_pages = max_pages
        # the owner's lock (`bind_guard`); None until bound. Mutating
        # methods assert the calling thread holds it (under tests)
        self._guard = None
        self._nodes: dict = {}   # guarded by: _guard [external] — (parent_seq, digest) -> _PrefixNode
        self._seq = 0  # guarded by: _guard [external]
        self._clock = 0  # guarded by: _guard [external]
        # tenant-scoped chain roots: tenant -> synthetic root seq (None
        # tenant keeps the historic root 0; others draw from the same
        # counter as nodes, so roots and nodes can never collide). A
        # request can only ever walk chains grown from ITS tenant's
        # root — cross-tenant page binding is structurally impossible
        self._roots: dict = {}  # guarded by: _guard [external]
        # structural counters (hit/miss/token accounting lives on the
        # engine, which counts once per BINDING — a page-blocked queue
        # head re-runs lookup every scheduler iteration)
        self.insertions = 0  # guarded by: _guard [external]
        self.evictions = 0  # guarded by: _guard [external]
        self._recorder = None  # optional FlightRecorder (engine's)
        # weight-version tag (`bind_version`): every cached page holds
        # KV computed under exactly these weights. The engine re-binds
        # the tag on every (re)build — shipped pages from a KV handoff
        # may only promote here after the transfer layer proved the
        # sender's version equal (kv_transfer.verify_payload)
        self.weight_version: Optional[str] = None
        # optional cluster directory (`bind_directory`): promotions
        # publish their chain keys, evictions retract, clear drops the
        # holder wholesale — the in-process push half of the protocol
        self._directory = None
        self._holder: Optional[str] = None

    def bind_guard(self, lock) -> "PrefixCache":
        """Register the owner's lock. Every mutating method then runs
        `assert_owned` against it under tests, turning a silently-racy
        unlocked call into a hard failure."""
        self._guard = lock
        return self

    def bind_recorder(self, recorder) -> "PrefixCache":
        """Register the owner's flight recorder: cache invalidations and
        cap-driven eviction bursts land in the scheduler-event ring (its
        lock is a leaf, so emitting under the engine's condition lock is
        deadlock-free)."""
        self._recorder = recorder
        return self

    def bind_version(self, version: Optional[str]) -> "PrefixCache":
        """Tag the cache with the serving weights' content digest (the
        key under which cached KV is valid). Nodes are STAMPED with the
        version live at their insert and `lookup` only walks nodes
        matching the CURRENT tag — so re-binding to a different version
        invalidates every older entry without dropping it, and binding
        BACK to the original version (a rollback to the same weights)
        makes those entries hittable again: the pages were computed
        under exactly those weights."""
        self.weight_version = version
        return self

    def bind_directory(self, directory, holder: str) -> "PrefixCache":
        """Register the cluster prefix directory and this cache's
        holder id: every promotion publishes its chain keys, every
        eviction retracts, `clear()` drops the holder wholesale. The
        directory's lock is a leaf — publishing under the engine's
        condition lock is deadlock-free."""
        self._directory = directory
        self._holder = holder
        return self

    # -- introspection -----------------------------------------------------
    @property
    def cached_pages(self) -> int:
        return len(self._nodes)

    def stats(self) -> dict:
        return {"cached_pages": len(self._nodes),
                "pinned_pages": sum(1 for n in self._nodes.values()
                                    if n.requests or n.children),
                "insertions": self.insertions,
                "evictions": self.evictions,
                "page_size": self.page_size,
                "max_pages": self.max_pages,
                "weight_version": self.weight_version}

    # -- lookup / binding --------------------------------------------------
    def _max_hit_pages(self, t0: int) -> int:
        """A hit never covers the whole prompt: position t0-1 must be
        recomputed so the first-token logits (and the decode state they
        seed) come from a live prefill — cap the match at the last page
        boundary strictly before t0-1's page end."""
        return max(0, (t0 - 1) // self.page_size)

    def _root_seq(self, tenant: Optional[str]) -> int:
        """Synthetic root seq for a tenant's chain space (None keeps
        the historic root 0). Allocated from the node counter, so a
        tenant root can never alias a node seq."""
        if tenant is None:
            return 0
        root = self._roots.get(tenant)
        if root is None:
            self._seq += 1
            root = self._roots[tenant] = self._seq
        return root

    def _walk(self, prompt: np.ndarray, cap: int,
              digest_cache: Optional[list],
              tenant: Optional[str]) -> List[_PrefixNode]:
        """Shared chain walk for `lookup`/`match`: longest cached chain
        matching `prompt`'s first `cap` pages under `tenant`'s root,
        current weight version only. Touches matched LRU clocks."""
        page = self.page_size
        out: List[_PrefixNode] = []
        parent_seq = self._root_seq(tenant)
        for i in range(cap):
            chunk = np.ascontiguousarray(prompt[i * page:(i + 1) * page],
                                         np.int32)
            if digest_cache is not None and i < len(digest_cache):
                dig = digest_cache[i]
            else:
                dig = _digest(chunk)
                if digest_cache is not None:
                    digest_cache.append(dig)
            node = self._nodes.get((parent_seq, dig))
            if node is None or node.version != self.weight_version \
                    or not np.array_equal(node.tokens, chunk):
                break
            out.append(node)
            parent_seq = node.seq
        self._clock += 1
        for node in out:
            node.last_used = self._clock
        return out

    def lookup(self, prompt: np.ndarray,
               digest_cache: Optional[list] = None,
               tenant: Optional[str] = None) -> List[_PrefixNode]:
        """Longest cached chain matching `prompt`'s page-aligned prefix
        (possibly empty), capped at `_max_hit_pages`. Touches the
        matched nodes' LRU clocks; does NOT take references — pair with
        `acquire` under the same lock before any other cache call can
        run. `digest_cache`: a caller-owned list memoizing the prompt's
        per-chunk digests — a page-blocked queue head re-runs lookup
        every scheduler iteration, and the prompt is immutable, so
        hashing it once is enough."""
        assert_owned(self._guard, "PrefixCache.lookup")
        t0 = int(prompt.shape[0])
        return self._walk(prompt, self._max_hit_pages(t0), digest_cache,
                          tenant)

    def match(self, prompt: np.ndarray,
              tenant: Optional[str] = None) -> List[_PrefixNode]:
        """Longest cached chain over EVERY full page of `prompt` — no
        `_max_hit_pages` cap, because the caller is not binding a slot:
        used by the cluster export path (a peer asking for exactly the
        pages it saw in the directory) and by delta-transfer depth
        queries."""
        assert_owned(self._guard, "PrefixCache.match")
        t0 = int(prompt.shape[0])
        return self._walk(prompt, t0 // self.page_size, None, tenant)

    def chains(self) -> List[str]:
        """Chain keys of every resident node at the CURRENT weight
        version — the pull-mode directory refresh payload
        (`prefix_chains` RPC)."""
        assert_owned(self._guard, "PrefixCache.chains")
        return [n.chain for n in self._nodes.values()
                if n.version == self.weight_version and n.chain]

    def acquire(self, nodes: List[_PrefixNode]) -> None:
        assert_owned(self._guard, "PrefixCache.acquire")
        for node in nodes:
            node.requests += 1

    def release(self, nodes: List[_PrefixNode]) -> None:
        assert_owned(self._guard, "PrefixCache.release")
        for node in nodes:
            node.requests -= 1
            assert node.requests >= 0, "prefix-cache refcount underflow"

    # -- insertion ---------------------------------------------------------
    def insert(self, prompt: np.ndarray, pages: List[int],
               held: List[_PrefixNode], tenant: Optional[str] = None):
        """Promote the prompt's fully-covered pages into the cache after
        a successful prefill. `pages` is the request's LOGICAL page list
        (shared prefix pages first, then owned pages); `held` the nodes
        the request already references (its admission-time hit). New
        nodes are created only ON TOP of the held chain and only from
        the request's OWN pages: if another request already cached a
        deeper chunk with a different page, promotion stops there — a
        chain's pages always share one numeric lineage, never a mix of
        two requests' prefills. Returns `(nodes, freed)`: the full node
        list the request now holds one reference on (callers replace
        their held list with it; ownership of the promoted pages
        transfers to the cache), and the page ids of any nodes evicted
        to respect `max_pages` — the CALLER must return those to its
        free list, or each cap-driven eviction would leak a pool page."""
        assert_owned(self._guard, "PrefixCache.insert")
        page = self.page_size
        t0 = int(prompt.shape[0])
        cacheable = t0 // page  # pages fully covered by the prompt
        nodes = list(held)
        freed: List[int] = []
        parent = held[-1] if held else None
        chain = (bytes.fromhex(parent.chain) if parent is not None
                 and parent.chain else _chain_root(tenant))
        self._clock += 1
        published: List[str] = []
        for i in range(len(held), cacheable):
            parent_seq = (parent.seq if parent is not None
                          else self._root_seq(tenant))
            chunk = np.ascontiguousarray(prompt[i * page:(i + 1) * page],
                                         np.int32)
            dig = _digest(chunk)
            key = (parent_seq, dig)
            if key in self._nodes:
                # raced by another request's promotion of the same
                # prefix: its page is canonical for future lookups, ours
                # stays privately owned — do not extend past it with a
                # mixed-lineage chain
                break
            if self.max_pages is not None \
                    and len(self._nodes) >= self.max_pages:
                evicted = self._evict_one(protect=nodes)
                if evicted is None:
                    break  # cap reached, everything pinned: skip caching
                freed.append(evicted)
            chain = hashlib.blake2b(chain + dig, digest_size=16).digest()
            self._seq += 1
            node = _PrefixNode(self._seq, parent, int(pages[i]), chunk,
                               key, chain=chain.hex(),
                               version=self.weight_version)
            node.requests = 1  # the promoting request's reference
            node.last_used = self._clock
            if parent is not None:
                parent.children += 1
            self._nodes[key] = node
            nodes.append(node)
            parent = node
            self.insertions += 1
            published.append(node.chain)
        if freed and self._recorder is not None:
            # cap pressure displaced resident prefixes — one aggregated
            # event per promotion, not one per page
            self._recorder.event("prefix-cache", decision="cap-evict",
                                 pages=len(freed),
                                 cached_pages=len(self._nodes))
        if published and self._directory is not None:
            # publish-on-promotion: the cluster learns this holder has
            # the chain the moment it becomes shareable locally
            self._directory.publish(self.weight_version, page,
                                    published, self._holder)
            if self._recorder is not None:
                self._recorder.event("prefix-publish",
                                     pages=len(published),
                                     holder=self._holder)
        return nodes, freed

    # -- eviction ----------------------------------------------------------
    def _evict_one(self, protect: List[_PrefixNode] = ()) -> Optional[int]:
        """Evict the least-recently-used unpinned LEAF node (no bound
        requests, no cached children, not in `protect`); returns its
        page id or None when nothing is evictable. Leaf-first keeps
        every resident chain reachable from its root."""
        best = None
        protected = {id(n) for n in protect}
        for node in self._nodes.values():
            if node.requests or node.children or id(node) in protected:
                continue
            if best is None or node.last_used < best.last_used:
                best = node
        if best is None:
            return None
        del self._nodes[best.key]
        if best.parent is not None:
            best.parent.children -= 1
        self.evictions += 1
        if self._directory is not None and best.chain:
            # retract-on-evict: the directory must never advertise a
            # chain whose pages are back on the free list
            self._directory.retract(best.version, (best.chain,),
                                    self._holder)
        return best.page_id

    def reclaim(self, n_pages: int) -> List[int]:
        """Release up to `n_pages` cached pages (LRU leaf-first) back to
        the caller's free list — the admission-pressure valve that keeps
        caching from ever shrinking effective pool capacity. Pinned
        pages (bound requests or interior chain nodes) are never
        touched."""
        assert_owned(self._guard, "PrefixCache.reclaim")
        freed: List[int] = []
        while len(freed) < n_pages:
            pid = self._evict_one()
            if pid is None:
                break
            freed.append(pid)
        return freed

    def clear(self) -> None:
        """Drop every node WITHOUT returning pages (the engine rebuilds
        its free list wholesale after a pool rebuild — weight swap or
        post-failure recovery — which is the only time this runs). A
        stale page can never serve new weights."""
        assert_owned(self._guard, "PrefixCache.clear")
        dropped = len(self._nodes)
        self._nodes.clear()
        self._roots.clear()
        if self._directory is not None:
            self._directory.drop_holder(self._holder)
        if dropped and self._recorder is not None:
            self._recorder.event("prefix-cache", decision="invalidate",
                                 dropped=dropped)
