"""Cluster-wide prefix directory: who holds which prompt-prefix chain.

PR 8's prefix cache made a shared system prompt free *within* one
engine; this directory makes it free *across* engines. Every promoted
prefix chain is published here as a content-addressed key — the
cumulative rolling chain digest from `prefix_cache.chain_keys`, which
is identical on every host for the same (tenant, token) prefix — and a
router or an admitting engine can ask "who already holds the KV for
this prompt's longest page-aligned prefix?".

Keying discipline:

- **weight_version first.** Entries live under the publisher's weight
  digest, so a rolling reload atomically strands the old version's
  entries instead of `clear()`-ing the world: lookups from engines on
  the new weights simply never see them, and the stale generation ages
  out (TTL) or is dropped when the publisher's cache clears
  (`drop_holder`). A fetched page can therefore never bind under the
  wrong weights even before the transfer layer re-verifies.
- **tenant inside the key.** `chain_keys` folds the tenant into the
  chain root, so one tenant's published prefixes are unreachable from
  another tenant's lookups — isolation holds at the directory, not
  just at the fetch.
- **TTL per (key, holder).** A dead host stops refreshing; its entries
  expire lazily on lookup and eagerly on `sweep`. In-process pools
  (threads, not hosts) may pass ``ttl=None`` — their publishers retract
  synchronously on evict/clear, so aging is redundant.

Thread-safety: self-locking on a private leaf lock. Publishers call
under their engine's scheduler lock (engine lock -> directory lock is
the only ordering; the directory never calls back out), routers call
from arbitrary threads.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional

import numpy as np


class PrefixDirectory:
    """Maps (weight_version, chain key) -> the set of holders with that
    prefix chain resident, TTL'd per holder.

    Parameters
    ----------
    ttl : seconds a published entry stays live without a refresh;
        ``None`` disables aging (in-process pools whose publishers
        retract synchronously).
    """

    def __init__(self, ttl: Optional[float] = None):
        if ttl is not None and ttl <= 0:
            raise ValueError(f"directory ttl must be > 0 or None, got {ttl}")
        self.ttl = None if ttl is None else float(ttl)
        self._lock = threading.Lock()
        # weight_version -> {"page_size": int,
        #                    "keys": {chain_key: {holder: expires_at|None}}}
        self._versions: Dict[str, dict] = {}  # guarded by: _lock
        self.publishes = 0    # guarded by: _lock
        self.retracts = 0     # guarded by: _lock
        self.expirations = 0  # guarded by: _lock

    # -- publication -------------------------------------------------------
    def publish(self, weight_version: str, page_size: int,
                keys: Iterable[str], holder: str,
                now: Optional[float] = None) -> None:
        """Register `holder` as having each chain key resident under
        `weight_version`. Refreshes the TTL of already-published keys."""
        now = time.monotonic() if now is None else now
        expires = None if self.ttl is None else now + self.ttl
        with self._lock:
            ver = self._versions.setdefault(
                weight_version, {"page_size": int(page_size), "keys": {}})
            if ver["page_size"] != int(page_size):
                raise ValueError(
                    f"prefix directory: weight version {weight_version} "
                    f"already published with page_size {ver['page_size']}, "
                    f"got {page_size}")
            for key in keys:
                ver["keys"].setdefault(key, {})[holder] = expires
                self.publishes += 1

    def retract(self, weight_version: str, keys: Iterable[str],
                holder: str) -> None:
        """Remove `holder` from each chain key (evict-side hook)."""
        with self._lock:
            ver = self._versions.get(weight_version)
            if ver is None:
                return
            for key in keys:
                holders = ver["keys"].get(key)
                if holders is None or holder not in holders:
                    continue
                del holders[holder]
                self.retracts += 1
                if not holders:
                    del ver["keys"][key]
            if not ver["keys"]:
                del self._versions[weight_version]

    def drop_holder(self, holder: str) -> int:
        """Remove every entry naming `holder` — a cleared cache, an
        evicted replica, or a rebuilt engine retracts wholesale.
        Returns the number of entries dropped."""
        dropped = 0
        with self._lock:
            for wv in list(self._versions):
                keys = self._versions[wv]["keys"]
                for key in list(keys):
                    if holder in keys[key]:
                        del keys[key][holder]
                        dropped += 1
                        if not keys[key]:
                            del keys[key]
                if not keys:
                    del self._versions[wv]
            self.retracts += dropped
        return dropped

    # -- lookup ------------------------------------------------------------
    def _live_holders_locked(self, ver: dict, key: str,
                             now: float) -> List[str]:
        holders = ver["keys"].get(key)
        if not holders:
            return []
        out = []
        for holder, expires in list(holders.items()):
            if expires is not None and expires <= now:
                del holders[holder]
                self.expirations += 1
                continue
            out.append(holder)
        if not holders:
            del ver["keys"][key]
        return out

    def holders(self, weight_version: str, key: str,
                now: Optional[float] = None) -> List[str]:
        """Live holders of one chain key (expired entries pruned)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            ver = self._versions.get(weight_version)
            if ver is None:
                return []
            return self._live_holders_locked(ver, key, now)

    def deepest(self, weight_version: str, keys: List[str],
                exclude: Iterable[str] = (),
                now: Optional[float] = None):
        """Walk `keys` (shallow -> deep chain order) and return
        ``(depth_pages, holders)`` for the DEEPEST key with a live
        holder not in `exclude`, or ``(0, [])``."""
        now = time.monotonic() if now is None else now
        excluded = set(exclude)
        with self._lock:
            ver = self._versions.get(weight_version)
            if ver is None:
                return 0, []
            for i in range(len(keys) - 1, -1, -1):
                live = [h for h in
                        self._live_holders_locked(ver, keys[i], now)
                        if h not in excluded]
                if live:
                    return i + 1, live
        return 0, []

    def best_holder(self, prompt: np.ndarray, tenant: Optional[str] = None,
                    *, exclude: Iterable[str] = (),
                    now: Optional[float] = None) -> Optional[dict]:
        """Router-side lookup: compute the prompt's chain keys for every
        published (weight_version, page_size) generation and return the
        deepest live match as ``{"weight_version", "page_size", "depth",
        "holders"}``, or None. Depth is capped one page short of the
        prompt end (`_max_hit_pages` semantics: the final position is
        always recomputed live)."""
        from deeplearning4j_tpu.serving.prefix_cache import chain_keys

        prompt = np.asarray(prompt)
        t0 = int(prompt.shape[0])
        with self._lock:
            groups = [(wv, ver["page_size"])
                      for wv, ver in self._versions.items()]
        best = None
        for wv, page in groups:
            cap = max(0, (t0 - 1) // page)
            if cap == 0:
                continue
            keys = chain_keys(prompt, page, tenant=tenant)[:cap]
            depth, live = self.deepest(wv, keys, exclude=exclude, now=now)
            if depth and (best is None or depth > best["depth"]):
                best = {"weight_version": wv, "page_size": page,
                        "depth": depth, "holders": live}
        return best

    # -- maintenance -------------------------------------------------------
    def sweep(self, now: Optional[float] = None) -> int:
        """Eagerly prune every expired entry; returns the count."""
        if self.ttl is None:
            return 0
        now = time.monotonic() if now is None else now
        pruned = 0
        with self._lock:
            for wv in list(self._versions):
                keys = self._versions[wv]["keys"]
                for key in list(keys):
                    holders = keys[key]
                    for holder, expires in list(holders.items()):
                        if expires is not None and expires <= now:
                            del holders[holder]
                            pruned += 1
                    if not holders:
                        del keys[key]
                if not keys:
                    del self._versions[wv]
            self.expirations += pruned
        return pruned

    def stats(self) -> dict:
        with self._lock:
            entries = sum(len(ver["keys"])
                          for ver in self._versions.values())
            return {"directory_entries": entries,
                    "directory_versions": len(self._versions),
                    "publishes": self.publishes,
                    "retracts": self.retracts,
                    "expirations": self.expirations}
