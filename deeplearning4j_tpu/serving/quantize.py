"""Quantized inference tier: int8 paged KV cache + int8/bf16 weight
serving (ROADMAP item 2 / round-4 ask #4).

The serving decode path is bandwidth-bound on KV bytes — the r4 decode
profile and PR 9's paged-attention kernel both priced the cache stream
as the dominant cost. This module halves it again: K/V are quantized to
**symmetric per-head int8 at cache-write time** and dequantized at the
read site — inside the Pallas page loop on TPU (int8 pages DMA'd,
scales prefetched, dequant-in-VMEM before the matmul,
`ops/pallas_paged_attention.py`) and in the `paged_gather`-path int8
reference on CPU (`ops.attention.paged_gather_quant`), which is the
tier-1 / kill-switch numerics oracle.

**Scale layout.** Pools stay in the r4 decode layouts with int8
elements — K `(P+1, Hkv, hd, page)`, V `(P+1, Hkv, page, hd)` — plus
two small f32 scale pools `(P+1, Hkv, page)`: one scale per
(page, head, position). Per-position granularity (not per-page) is what
makes the page pools soundly *appendable*: the decode step writes one
position into a page that already holds earlier positions, and a
coarser per-page scale could only absorb the new abs-max by re-scaling
(rewriting) the old int8 entries or clipping against a stale bound.
One f32 scalar per (head, position) costs ``4/hd`` of the int8 payload
(~3% at hd=128) and rides the SAME page table / free list / refcounts
as the payload pools — PrefixCache sharing, speculative draft pools,
and trash-page masking (int8 zeros dequantize to exact 0.0) all work
unchanged.

**Weight quantization** (`quantize_net_weights`) follows the LLM.int8
per-output-channel recipe (Dettmers et al., 2022): symmetric int8 over
the contraction axis, stored dequantized-to-bf16 so every compiled
serving path (predict, prefill, decode) runs unmodified; ``"bf16"`` is
the plain cast. Embeddings, positional tables, biases and LayerNorm
parameters keep full precision — they are neither bandwidth-bound nor
outlier-tolerant.

**Drift gates** (`drift_report`): quantization is a *numerics change*,
so it ships through the canary ladder like any other candidate — an
argmax-drift gate (token-disagreement rate vs the f32 rollout on a
pinned eval set) and a perplexity-delta gate, enforced by
`ModelServer._validate_candidate` before a quantized candidate swaps
in, and rolled back for free by the PR-4/PR-7 reload machinery when
breached.

Kill switch: ``DL4J_TPU_NO_INT8_KV=1`` (checked by the engine at build
time AND by the kernel dispatch) forces full-precision pools — the
bench's ``int8_kv_vs_bf16_device_ms_per_token`` A/B lever.
"""
from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

KV_KILL_ENV = "DL4J_TPU_NO_INT8_KV"

#: block-parameter matmul weights eligible for weight quantization
#: (attention projections + FFN/SwiGLU); everything else — embedding,
#: positional table, biases, LayerNorm gains — keeps full precision
BLOCK_MATMUL_KEYS = ("Wqkv", "Wo", "W1", "W2", "W3")


def int8_kv_enabled() -> bool:
    """The int8-KV kill switch: ``DL4J_TPU_NO_INT8_KV=1`` makes the
    engine allocate full-precision pools (and the int8 kernel decline
    dispatch) — the A/B lever `bench.py serve_generate` flips to price
    ``int8_kv_vs_bf16_device_ms_per_token`` on identical traffic."""
    return os.environ.get(KV_KILL_ENV, "") not in ("1", "true", "yes")


# -- int8 KV quantization (traced inside the engine's step closures) -------

def quantize_heads(x, axis: int = -1):
    """Symmetric per-head int8 quantization of one KV write span.

    Reduces abs-max over `axis` (the head_dim axis of the span — the
    last axis for the decode step's (S, Hkv, hd) single-position write,
    axis 2 / 3 for the prefill span's lane-last (1, Hkv, hd, W) /
    (1, Hkv, W, hd) layouts), yielding one f32 scale per (head,
    position). Returns ``(q, scale)`` with ``q`` int8 in [-127, 127]
    and ``scale = abs_max / 127`` (1.0 for all-zero spans, so dequant
    is exact 0.0 — the trash-page convention). Round-trip error is
    bounded by scale/2 per element (ULP-bound pinned in
    tests/test_quantize.py)."""
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axis)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.round(xf / jnp.expand_dims(scale, axis))
    q = jnp.clip(q, -127.0, 127.0).astype(jnp.int8)
    return q, scale


def dequantize_heads(q, scale, axis: int = -1, dtype=None):
    """Inverse of `quantize_heads`: broadcast the per-(head, position)
    scale back over `axis`. The reference read path
    (`ops.attention.paged_gather_quant`) inlines exactly this."""
    import jax.numpy as jnp

    out = q.astype(jnp.float32) * jnp.expand_dims(scale, axis)
    return out if dtype is None else out.astype(dtype)


def _write_scale_pages(sp, scol, wpids, woff, page):
    """Scatter one prefill span's per-position scales (1, Hkv, W) into
    the f32 scale pool (P+1, Hkv, page) — the exact write discipline of
    `decode_engine._write_pages` with the lane (position) axis last:
    floor(W/page) aligned full-page writes, then a partial tail at
    in-page offset `woff`. Module level so the speculative draft's
    prefill mirrors the same writes into its own scale pools."""
    import jax
    import jax.numpy as jnp

    W = scol.shape[2]
    z = jnp.zeros((), jnp.int32)
    nfull = W // page
    for j in range(nfull):
        sp = jax.lax.dynamic_update_slice(
            sp, scol[..., j * page:(j + 1) * page], (wpids[j], z, z))
    if W % page:
        sp = jax.lax.dynamic_update_slice(
            sp, scol[..., nfull * page:], (wpids[nfull], z, woff))
    return sp


def kv_bytes_per_token(kv_geometry: Sequence[Tuple[int, int]],
                       kv_quant: Optional[str],
                       cache_itemsize: int) -> int:
    """Resident KV bytes one generated token adds across all blocks —
    the number `stats()["kv_bytes_per_token"]` and the bench satellite
    report. int8 pools pay 1 byte/element plus the f32 scale sidecar
    (2 heads-worth of 4-byte scalars per position — ``8·Hkv`` vs the
    payload's ``2·Hkv·hd``, i.e. a 4/hd overhead); full-precision pools
    pay ``cache_itemsize`` per element. `kv_geometry` is
    `GPTPlan.kv_geometry()`: per-block (Hkv, hd) pairs."""
    total = 0
    for Hkv, hd in kv_geometry:
        if kv_quant == "int8":
            total += 2 * Hkv * hd + 2 * Hkv * 4
        else:
            total += 2 * Hkv * hd * cache_itemsize
    return total


# -- weight quantization ---------------------------------------------------

def quantize_weight_int8(w):
    """Per-output-channel symmetric int8 fake-quantization of one
    matmul weight, stored dequantized-to-bf16. The scale reduces over
    axis -2 — the contraction (input) dimension — so each output
    channel keeps its own dynamic range (the LLM.int8 layout; a single
    tensor-wide scale lets one outlier channel crush the rest). Works
    for 2-D (d_in, d_out) and any leading-batched layout."""
    import jax.numpy as jnp

    wf = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -127.0, 127.0)
    return (q * scale).astype(jnp.bfloat16)


def quantize_net_weights(net, mode: str):
    """Clone `net` with its transformer matmul weights quantized.

    ``mode="int8"``: per-output-channel symmetric int8
    (`quantize_weight_int8`), stored dequantized-to-bf16 — every
    compiled serving path runs unmodified on the quantized clone.
    ``mode="bf16"``: plain bf16 cast of the same weight set. Both
    quantize the block projections (`BLOCK_MATMUL_KEYS`) and the output
    head's ``W``; embeddings, positional tables, biases and LayerNorm
    parameters keep full precision. The original `net` is untouched —
    `ModelServer` keeps it (or the raw reload candidate) as the
    drift-gate reference and the rollback target."""
    if mode not in ("int8", "bf16"):
        raise ValueError(
            f'unknown weight quantization mode {mode!r} — expected '
            '"int8" or "bf16"')
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.transformer import GPTPlan
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    plan = GPTPlan(net)
    cast = quantize_weight_int8 if mode == "int8" \
        else (lambda w: jnp.asarray(w, jnp.bfloat16))
    params = [dict(p) for p in net._params]
    for i in plan.block_is:
        for key in BLOCK_MATMUL_KEYS:
            w = params[i].get(key)
            if w is not None and getattr(w, "ndim", 0) >= 2:
                params[i][key] = cast(w)
    out_w = params[plan.out_i].get("W")
    if out_w is not None and getattr(out_w, "ndim", 0) >= 2:
        params[plan.out_i]["W"] = cast(out_w)
    clone = MultiLayerNetwork(net.conf, dtype=net.dtype,
                              compute_dtype=net.compute_dtype)
    clone.init()  # allocates layer state; params replaced wholesale
    clone._params = params
    clone._layer_state = net._layer_state
    if net.get_normalizer() is not None:
        clone.set_normalizer(net.get_normalizer())
    return clone


# -- drift gates -----------------------------------------------------------

def _log_softmax(out: np.ndarray) -> np.ndarray:
    m = out.max(axis=-1, keepdims=True)
    lse = m + np.log(np.exp(out - m).sum(axis=-1, keepdims=True))
    return out - lse


def argmax_drift_rate(ref_out: np.ndarray, cand_out: np.ndarray) -> float:
    """Token-disagreement rate between two models' outputs (B, T, V)
    over a pinned eval set: the fraction of positions whose greedy
    (argmax) token differs. THE serving-facing drift number — greedy
    decode emits exactly these argmaxes, so a 0.0 rate means the
    quantized model serves identical greedy tokens on the eval set."""
    ref = np.argmax(np.asarray(ref_out), axis=-1)
    cand = np.argmax(np.asarray(cand_out), axis=-1)
    return float(np.mean(ref != cand))


def perplexity(out: np.ndarray, ids: np.ndarray) -> float:
    """Next-token perplexity of `ids` (B, T) under model outputs `out`
    (B, T, V): position t's output scores token t+1. `out` is treated
    as unnormalized logits (log-softmax applied here); already-
    normalized log-probs pass through unchanged, so the DELTA between
    two models is well-defined either way."""
    out = np.asarray(out, np.float64)
    ids = np.asarray(ids)
    logp = _log_softmax(out[:, :-1, :])
    B, Tm1 = ids.shape[0], ids.shape[1] - 1
    nll = -logp[np.arange(B)[:, None], np.arange(Tm1)[None, :],
                ids[:, 1:]]
    return float(np.exp(nll.mean()))


def drift_report(ref_out: np.ndarray, cand_out: np.ndarray,
                 ids: np.ndarray) -> dict:
    """The drift-gate verdict numerics for one (reference, candidate)
    pair on the pinned eval set: argmax disagreement rate plus the
    perplexity delta (candidate - reference; positive = worse). These
    are the numbers `ModelServer._validate_candidate` compares against
    `drift_gate={"max_argmax_drift": ..., "max_ppl_delta": ...}` and
    surfaces through ``stats()["drift"]`` / the flight recorder."""
    rate = argmax_drift_rate(ref_out, cand_out)
    ppl_ref = perplexity(ref_out, ids)
    ppl_cand = perplexity(cand_out, ids)
    return {"argmax_drift": round(rate, 6),
            "ppl_ref": round(ppl_ref, 6),
            "ppl_cand": round(ppl_cand, 6),
            "ppl_delta": round(ppl_cand - ppl_ref, 6)}
