"""Exactly-once serving: idempotency keys, a durable request journal,
and detach/reclaim across gateway crashes.

Three legs share one `request_id` spine (client-minted, stamped on every
gateway call):

- **Idempotency-keyed dedup** (`DedupCache`) — a bounded, TTL'd
  completed-result ring plus an in-flight registry. ANY wire-level retry
  of a stamped request — including the historically non-retryable
  `fit`/`reload_model`/`resume_generate` — returns the ORIGINAL outcome
  instead of re-executing, so the client-side `_IDEMPOTENT` whitelist
  collapses into one dedup door and a seeded `generate` retry stops
  recomputing the whole rollout.
- **Detach/reclaim** — a connection lost mid-`generate` no longer wastes
  the decode: the handler keeps executing, the outcome parks in the
  cache (completion happens BEFORE the reply is written), and the
  reconnecting client `claim(request_id)`s it. Typed
  `ResultPendingError` (+ retry_after) while still executing, typed
  `UnknownRequestError` once the outcome ages past the TTL.
- **Durable intake journal** (`RequestJournal`) — accepted
  generate/predict/fit requests append to a CRC'd, fsync'd WAL built on
  `util.checkpoint_store`'s atomic-commit/checksum machinery
  (journal-at-admission, mark-complete on reply, segment rotation + GC).
  On gateway restart, unfinished journaled requests replay through fresh
  prefill — same seed, argmax-identical — so a kill -9 of the gateway
  under live traffic completes every accepted request exactly once.

The promise is *exactly-once observable behavior*: at-least-once
delivery (journal replay + client retries) with at-most-once side
effects (the dedup door), bounded by the TTL — a client must reclaim a
detached outcome within `ttl` seconds.
"""
from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.serving.model_server import ServingError
from deeplearning4j_tpu.util.checkpoint_store import crc32_hex, fsync_dir

logger = logging.getLogger("deeplearning4j_tpu")


# ---------------------------------------------------------------------------
# RPC-contract classification (pinned by tests/test_gateway_robustness):
# every public gateway entry-point method must appear in EXACTLY one set.
# A new RPC that is in neither fails the contract test — nobody ships an
# endpoint without deciding its retry story.

# Side-effectful (or install-like) methods whose retry-safety comes FROM
# the dedup door: a stamped retry returns the parked outcome, never
# re-executes.
DEDUPED_RPCS = frozenset({
    "fit", "create_model", "load_model", "reload_model", "rolling_reload",
    "resume_generate",
    # streaming: a reconnect must re-attach to the live ring (or claim
    # the parked outcome), never start a second decode of the sequence
    "generate_stream",
    # remote-replica entry-point extras (install-like)
    "serve_net", "restore_snapshot",
})

# Documented side-effect-free: safe to blindly re-execute even WITHOUT
# the door (read-only, resolve-by-id, or seeded-deterministic). The door
# still dedups them when stamped — a generate retry returns the parked
# rollout instead of recomputing it — but correctness never depends on it.
SIDE_EFFECT_FREE_RPCS = frozenset({
    "predict", "evaluate", "score", "generate", "save_model",
    "server_stats", "pool_stats", "autoscaler_stats", "metrics",
    "flight_record", "set_tenant_quota", "migrate_slots",
    "fetch_handoff", "commit_handoff", "abort_handoff",
    # cluster prefix cache: reads (header/frame/depth/chains) plus
    # export_prefix, whose re-execution grants a fresh lease the
    # orphaned original's TTL sweep unpins
    "fetch_handoff_header", "fetch_handoff_frame", "prefix_depth",
    "prefix_chains", "export_prefix",
    # streaming: re-attach-by-id + cursor dedup in the ring — a replayed
    # resume can only re-deliver frames the client already trimmed
    "resume_stream",
    # remote-replica entry-point extras (reads)
    "health", "snapshot_model", "replica_metrics",
})

# The subset of deduped traffic that also journals at admission: the
# data-path requests a gateway crash must not lose, plus fit (whose
# durable complete record is what makes a post-restart retry return the
# original outcome instead of training twice).
JOURNALED_RPCS = frozenset({"generate", "generate_stream", "predict",
                            "fit"})


# ---------------------------------------------------------------------------
# typed errors (join the serving error taxonomy)


class ResultPendingError(ServingError):
    """The request is still executing (the original submission, or a
    crash-recovery replay, holds the in-flight slot): come back with
    `claim(request_id)` after `retry_after` seconds."""

    def __init__(self, msg: str, retry_after: float = 0.05):
        super().__init__(msg)
        self.retry_after = float(retry_after)


class UnknownRequestError(ServingError):
    """No record of this request_id: never admitted here, or its
    completed outcome aged out of the dedup ring (TTL / capacity). The
    at-most-once promise is TTL-bounded — reclaim within the window."""


# ---------------------------------------------------------------------------
# leg 1: the dedup door's completed-result ring + in-flight registry


class _Entry:
    __slots__ = ("outcome", "expires_at", "durable")

    def __init__(self, outcome: dict, expires_at: float, durable: bool):
        self.outcome = outcome
        self.expires_at = expires_at
        self.durable = durable


class DedupCache:
    """Bounded TTL'd completed-result ring + in-flight registry.

    Thread-safe. `begin(request_id)` verdicts:

    - ``("cached", outcome)`` — finished already; return the parked
      outcome verbatim.
    - ``("pending", retry_after)`` — some handler (or the replay loop)
      owns the execution right now.
    - ``("execute", None)`` — the caller now OWNS the execution and must
      call `complete` (park the outcome) or `abandon` (a shed the client
      should genuinely re-attempt) exactly once.

    Entries expire `ttl` seconds after completion; the ring is bounded
    at `capacity` (oldest completion evicted first)."""

    def __init__(self, capacity: int = 1024, ttl: float = 300.0,
                 pending_retry_after: float = 0.05):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if ttl <= 0:
            raise ValueError("ttl must be > 0")
        self.capacity = int(capacity)
        self.ttl = float(ttl)
        self.pending_retry_after = float(pending_retry_after)
        self._lock = threading.Lock()
        # completion-ordered ring of finished outcomes — guarded by: _lock
        self._done: "collections.OrderedDict[str, _Entry]" = \
            collections.OrderedDict()
        # request_id -> monotonic start time — guarded by: _lock
        self._inflight: Dict[str, float] = {}
        # counters — guarded by: _lock
        self._hits = 0
        self._executions = 0
        self._expired = 0
        self._evicted = 0
        self._double_executions = 0
        self._loaded = 0

    def _sweep_locked(self, now: float) -> None:
        # completion order == expiry order (uniform ttl), so expired
        # entries cluster at the front of the ring
        while self._done:
            rid, ent = next(iter(self._done.items()))
            if ent.expires_at > now:
                break
            del self._done[rid]
            self._expired += 1

    def begin(self, request_id: str) -> Tuple[str, Any]:
        now = time.monotonic()
        with self._lock:
            self._sweep_locked(now)
            ent = self._done.get(request_id)
            if ent is not None:
                self._hits += 1
                return "cached", ent.outcome
            if request_id in self._inflight:
                return "pending", self.pending_retry_after
            self._inflight[request_id] = now
            self._executions += 1
            return "execute", None

    def complete(self, request_id: str, outcome: dict,
                 durable: bool = False) -> None:
        """Park `outcome` (a wire response body, no "id") and release
        the in-flight slot."""
        now = time.monotonic()
        with self._lock:
            self._inflight.pop(request_id, None)
            if request_id in self._done:
                # two executors raced past begin() — impossible through
                # one door, so count it loudly rather than hide it
                self._double_executions += 1
                del self._done[request_id]
            self._done[request_id] = _Entry(outcome, now + self.ttl, durable)
            while len(self._done) > self.capacity:
                self._done.popitem(last=False)
                self._evicted += 1

    def abandon(self, request_id: str) -> None:
        """Release the in-flight slot WITHOUT caching: the outcome was a
        shed (carries retry_after) and the client's retry is a genuine
        new attempt, not a duplicate."""
        with self._lock:
            self._inflight.pop(request_id, None)

    def load(self, request_id: str, outcome: dict) -> None:
        """Preload a durable completed outcome at startup (journal
        replay of the at-most-once promise across a crash): counted as
        neither a hit nor an execution."""
        now = time.monotonic()
        with self._lock:
            if request_id in self._done:
                return
            self._done[request_id] = _Entry(outcome, now + self.ttl, True)
            self._loaded += 1
            while len(self._done) > self.capacity:
                self._done.popitem(last=False)
                self._evicted += 1

    def claim(self, request_id: str) -> dict:
        """The detach/reclaim edge: the parked outcome of a request
        whose client disconnected mid-reply. Typed `ResultPendingError`
        while it is still executing, typed `UnknownRequestError` when
        there is no record (never admitted, or aged past the TTL)."""
        now = time.monotonic()
        with self._lock:
            self._sweep_locked(now)
            ent = self._done.get(request_id)
            if ent is not None:
                self._hits += 1
                return ent.outcome
            inflight = request_id in self._inflight
        if inflight:
            raise ResultPendingError(
                f"request {request_id!r} is still executing; claim it "
                f"again in {self.pending_retry_after:.3g}s",
                retry_after=self.pending_retry_after)
        raise UnknownRequestError(
            f"no record of request {request_id!r}: never admitted here, "
            f"or its outcome aged past the {self.ttl:.3g}s TTL")

    def stats(self) -> dict:
        with self._lock:
            return {
                "completed": len(self._done),
                "inflight": len(self._inflight),
                "capacity": self.capacity,
                "ttl_s": self.ttl,
                "dedup_hits": self._hits,
                "executions": self._executions,
                "expired": self._expired,
                "evicted": self._evicted,
                "double_executions": self._double_executions,
                "durable_loaded": self._loaded,
            }


# ---------------------------------------------------------------------------
# leg 3: the durable intake journal (WAL on checkpoint_store discipline)


class _Segment:
    __slots__ = ("path", "open_ids", "n_records", "last_write")

    def __init__(self, path: Path):
        self.path = path
        self.open_ids: set = set()  # admits not yet completed
        self.n_records = 0
        self.last_write = time.monotonic()


class RequestJournal:
    """Append-only WAL of accepted journaled requests.

    Record format: one JSON object per line,
    ``{"crc": <crc32_hex of the canonical "rec" JSON>, "rec": {...}}``
    — the same checksum primitive checkpoint manifests use, so a torn
    tail (the kill -9 signature) or a flipped byte is refused by the
    CRC, skipped, and counted rather than replayed as garbage. ``rec``
    carries ``kind`` ("admit" | "complete"), ``seq``, ``request_id``,
    and for admits the method + wire-encoded params; completes carry
    the wire outcome body (or ``"void": true`` for shed outcomes a
    retry should genuinely re-attempt).

    Durability: every append flushes + fsyncs before returning, and a
    freshly created segment fsyncs its directory (the
    `util.checkpoint_store` atomic-commit discipline). Segments rotate
    at `segment_max_records`; a segment is GC'd once every admit in it
    has completed AND its newest record is older than `gc_ttl` — the
    durable dedup outcomes must outlive the in-memory ring's TTL
    promise, not vanish the moment the ledger balances."""

    _SEG_FMT = "journal-{:08d}.wal"

    def __init__(self, root, *, segment_max_records: int = 512,
                 gc_ttl: float = 300.0, fsync: bool = True):
        if segment_max_records < 1:
            raise ValueError("segment_max_records must be >= 1")
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.segment_max_records = int(segment_max_records)
        self.gc_ttl = float(gc_ttl)
        self.fsync = bool(fsync)
        self._lock = threading.Lock()
        # everything below guarded by: _lock
        self._segments: List[_Segment] = []
        self._fh = None  # open append handle of the current segment
        self._seq = 0
        self._pending: Dict[str, dict] = {}  # admits without a complete
        self._admit_seg: Dict[str, _Segment] = {}
        # request_id -> (wall completion time, outcome | None for void)
        self._completed: Dict[str, Tuple[float, Optional[dict]]] = {}
        self._completed_methods: Dict[str, str] = {}
        self.appends = 0
        self.completes = 0
        self.torn_skipped = 0
        self.corrupt_skipped = 0
        self.gc_segments = 0
        self.loaded_pending = 0
        self.loaded_completed = 0
        self._load_locked()

    # -- record codec -------------------------------------------------------
    @staticmethod
    def _encode(rec: dict) -> bytes:
        body = json.dumps(rec, sort_keys=True, separators=(",", ":"))
        line = json.dumps({"crc": crc32_hex(body.encode("utf-8")),
                           "rec": rec},
                          sort_keys=True, separators=(",", ":"))
        return line.encode("utf-8") + b"\n"

    @staticmethod
    def _decode(line: bytes) -> dict:
        obj = json.loads(line)
        rec = obj["rec"]
        body = json.dumps(rec, sort_keys=True, separators=(",", ":"))
        if crc32_hex(body.encode("utf-8")) != obj["crc"]:
            raise ValueError("record CRC mismatch")
        if rec.get("kind") not in ("admit", "complete") \
                or "request_id" not in rec:
            raise ValueError("malformed journal record")
        return rec

    # -- load / replay scan ---------------------------------------------
    def _segment_paths(self) -> List[Path]:
        return sorted(p for p in self.root.iterdir()
                      if p.name.startswith("journal-")
                      and p.name.endswith(".wal"))

    def _load_locked(self) -> None:
        paths = self._segment_paths()
        for pi, path in enumerate(paths):
            seg = _Segment(path)
            try:
                # segment names embed the seq at open time; folding them
                # into the counter keeps fresh segment names from ever
                # colliding with an old (possibly empty) file
                self._seq = max(self._seq,
                                int(path.name[len("journal-"):-len(".wal")]))
            except ValueError:
                pass
            try:
                raw = path.read_bytes()
            except OSError as e:
                logger.warning("journal: unreadable segment %s skipped: %s",
                               path.name, e)
                self.corrupt_skipped += 1
                continue
            lines = [ln for ln in raw.split(b"\n") if ln.strip()]
            for li, line in enumerate(lines):
                try:
                    rec = self._decode(line)
                except (ValueError, KeyError, TypeError) as e:
                    # the very last line of the very last segment is the
                    # kill -9 torn-write signature; anything else is
                    # damage (counted separately, chaos-drilled)
                    if pi == len(paths) - 1 and li == len(lines) - 1:
                        self.torn_skipped += 1
                        logger.warning(
                            "journal: torn tail record in %s skipped "
                            "(%s) — the request was never durably "
                            "admitted", path.name, e)
                    else:
                        self.corrupt_skipped += 1
                        logger.warning(
                            "journal: corrupt record %d in %s skipped "
                            "(%s)", li, path.name, e)
                    continue
                seg.n_records += 1
                self._seq = max(self._seq, int(rec.get("seq", 0)))
                self._apply_locked(rec, seg)
            # loaded segments age from NOW (monotonic, like appends): a
            # wall-clock mtime cannot be compared against monotonic time
            seg.last_write = time.monotonic()
            self._segments.append(seg)
        self.loaded_pending = len(self._pending)
        self.loaded_completed = len(self._completed)

    def _apply_locked(self, rec: dict, seg: _Segment) -> None:
        rid = str(rec["request_id"])
        if rec["kind"] == "admit":
            if rid in self._pending or rid in self._completed:
                return  # duplicate admit: idempotent
            self._pending[rid] = rec
            self._admit_seg[rid] = seg
            seg.open_ids.add(rid)
        else:  # complete
            admit_seg = self._admit_seg.pop(rid, None)
            if admit_seg is not None:
                admit_seg.open_ids.discard(rid)
            admit = self._pending.pop(rid, None)
            if admit is not None:
                self._completed_methods[rid] = str(admit.get("method", ""))
            outcome = None if rec.get("void") else rec.get("outcome")
            self._completed[rid] = (float(rec.get("t", time.time())),
                                    outcome)

    # -- append path ------------------------------------------------------
    def _open_segment_locked(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError as e:
                logger.warning("journal: segment close failed: %s", e)
        path = self.root / self._SEG_FMT.format(self._seq + 1)
        self._fh = open(path, "ab")
        # a new WAL segment must itself survive power loss before the
        # records inside it can claim to
        fsync_dir(self.root)
        self._segments.append(_Segment(path))

    def _append_locked(self, rec: dict) -> None:
        # _fh is not None implies _segments[-1] is the live segment
        if self._fh is None \
                or self._segments[-1].n_records >= self.segment_max_records:
            self._open_segment_locked()
        seg = self._segments[-1]
        self._fh.write(self._encode(rec))
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())
        seg.n_records += 1
        seg.last_write = time.monotonic()
        return None

    def admit(self, request_id: str, method: str, params: dict) -> bool:
        """Journal an accepted request BEFORE it executes. Idempotent:
        a retry or replay of an already-journaled id appends nothing."""
        request_id = str(request_id)
        with self._lock:
            if request_id in self._pending or request_id in self._completed:
                return False
            self._seq += 1
            rec = {"kind": "admit", "seq": self._seq,
                   "request_id": request_id, "method": str(method),
                   "params": params, "t": time.time()}
            self._append_locked(rec)
            seg = self._segments[-1]
            self._pending[request_id] = rec
            self._admit_seg[request_id] = seg
            seg.open_ids.add(request_id)
            self.appends += 1
            return True

    def complete(self, request_id: str, outcome: Optional[dict],
                 void: bool = False) -> bool:
        """Mark a journaled request done (outcome = the wire response
        body), or resolve it VOID (a shed the client should genuinely
        retry — no durable dedup entry). No-op for ids this journal
        never admitted (non-journaled methods ride the in-memory ring
        only)."""
        request_id = str(request_id)
        with self._lock:
            if request_id not in self._pending:
                return False
            self._seq += 1
            rec = {"kind": "complete", "seq": self._seq,
                   "request_id": request_id, "t": time.time()}
            if void:
                rec["void"] = True
            else:
                rec["outcome"] = outcome
            self._append_locked(rec)
            admit = self._pending.pop(request_id)
            self._completed_methods[request_id] = \
                str(admit.get("method", ""))
            seg = self._admit_seg.pop(request_id, None)
            if seg is not None:
                seg.open_ids.discard(request_id)
            self._completed[request_id] = (
                time.time(), None if void else outcome)
            self.completes += 1
            self._gc_locked()
            return True

    # -- GC / ledger balance ----------------------------------------------
    def _gc_locked(self) -> None:
        now = time.monotonic()
        wall_now = time.time()
        keep: List[_Segment] = []
        for seg in self._segments:
            is_current = seg is self._segments[-1]
            if not is_current and not seg.open_ids \
                    and now - seg.last_write > self.gc_ttl:
                try:
                    seg.path.unlink()
                except OSError as e:
                    logger.warning("journal: segment GC of %s failed: %s",
                                   seg.path.name, e)
                    keep.append(seg)
                    continue
                self.gc_segments += 1
            else:
                keep.append(seg)
        self._segments = keep
        # the in-memory completed ledger obeys the same horizon, or a
        # long-lived gateway grows it without bound
        expired = [rid for rid, (t, _) in self._completed.items()
                   if wall_now - t > self.gc_ttl]
        for rid in expired:
            del self._completed[rid]
            self._completed_methods.pop(rid, None)

    def gc(self) -> int:
        """Run a GC pass now; returns how many segments remain on disk."""
        with self._lock:
            self._gc_locked()
            return len(self._segments)

    # -- replay-side reads --------------------------------------------------
    def pending_records(self) -> List[dict]:
        """Admits with no complete, oldest first — the crash-recovery
        replay work list."""
        with self._lock:
            return sorted(self._pending.values(),
                          key=lambda r: int(r.get("seq", 0)))

    def completed_outcomes(self) -> Dict[str, dict]:
        """request_id -> durable outcome body for non-void completes —
        preloaded into the dedup ring at startup so a post-restart retry
        of an already-executed fit returns the original outcome."""
        with self._lock:
            return {rid: outcome
                    for rid, (_, outcome) in self._completed.items()
                    if outcome is not None}

    def completed_by_method(self) -> Dict[str, int]:
        """How many durable completes each method holds (the crash
        drill's exactly-once arithmetic: executions after restart +
        durable completes before it == total requests)."""
        with self._lock:
            out: Dict[str, int] = {}
            for m in self._completed_methods.values():
                out[m] = out.get(m, 0) + 1
            return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "pending": len(self._pending),
                "completed": len(self._completed),
                "segments": len(self._segments),
                "appends": self.appends,
                "completes": self.completes,
                "torn_skipped": self.torn_skipped,
                "corrupt_skipped": self.corrupt_skipped,
                "gc_segments": self.gc_segments,
                "loaded_pending": self.loaded_pending,
                "loaded_completed": self.loaded_completed,
            }

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError as e:
                    logger.warning("journal: close failed: %s", e)
                self._fh = None


# ---------------------------------------------------------------------------
# the door: one dedup gate + journal + replay, shared by every stamped RPC


class ExactlyOnceDoor:
    """The gateway's single dedup door.

    Handler contract (see `gateway.GatewayServer`): `admit` BEFORE
    dispatch; on "execute" the handler owns the request and must call
    `complete` with the response body (everything but "id") BEFORE the
    reply is written — so a client that disconnected mid-response can
    still `claim` the parked outcome. Outcomes carrying `retry_after`
    (sheds) resolve the ledger VOID and are never cached: the client's
    retry is a genuine new attempt.

    With `journal_dir`, admits of `JOURNALED_RPCS` hit the WAL before
    execution and durable completes preload the dedup ring at
    construction — at-most-once survives the process."""

    def __init__(self, journal_dir=None, capacity: int = 1024,
                 ttl: float = 300.0, pending_retry_after: float = 0.05,
                 journal_kwargs: Optional[dict] = None):
        self.cache = DedupCache(capacity=capacity, ttl=ttl,
                                pending_retry_after=pending_retry_after)
        self.journal: Optional[RequestJournal] = None
        self._lock = threading.Lock()
        self._replays = 0  # guarded by: _lock
        if journal_dir is not None:
            kw = dict(journal_kwargs or {})
            kw.setdefault("gc_ttl", ttl)
            self.journal = RequestJournal(journal_dir, **kw)
            for rid, outcome in self.journal.completed_outcomes().items():
                self.cache.load(rid, outcome)

    def admit(self, request_id: str, method: str,
              params: dict) -> Tuple[str, Any]:
        request_id = str(request_id)
        verdict, info = self.cache.begin(request_id)
        if verdict == "execute" and self.journal is not None \
                and method in JOURNALED_RPCS:
            self.journal.admit(request_id, method, params or {})
        return verdict, info

    def complete(self, request_id: str, outcome: dict,
                 retryable: bool = False) -> None:
        request_id = str(request_id)
        if retryable:
            self.cache.abandon(request_id)
            if self.journal is not None:
                self.journal.complete(request_id, None, void=True)
            return
        self.cache.complete(request_id, outcome)
        if self.journal is not None:
            self.journal.complete(request_id, outcome)

    def claim(self, request_id: str) -> dict:
        return self.cache.claim(str(request_id))

    def pending_records(self) -> List[dict]:
        if self.journal is None:
            return []
        return self.journal.pending_records()

    def replay(self, execute: Callable[[str, dict], dict],
               ready: Optional[Callable[[str, dict], bool]] = None) -> int:
        """Run unfinished journaled admits through
        ``execute(method, wire_params) -> wire outcome body``. `ready`
        (when given) defers records whose prerequisites — typically the
        named model — are not installed yet. Each replayed request rides
        the same dedup door as live traffic, so a reconnecting client's
        retry and the replay loop can never both execute one id."""
        done = 0
        for rec in self.pending_records():
            method = str(rec.get("method", ""))
            params = rec.get("params") or {}
            if ready is not None and not ready(method, params):
                continue
            rid = str(rec["request_id"])
            verdict, _ = self.cache.begin(rid)
            if verdict != "execute":
                continue  # a live retry beat us to it, or already done
            outcome = execute(method, params)
            retryable = isinstance(outcome, dict) and "error" in outcome \
                and "retry_after" in outcome
            self.complete(rid, outcome, retryable=retryable)
            with self._lock:
                self._replays += 1
            done += 1
        return done

    def stats(self) -> dict:
        with self._lock:
            replays = self._replays
        out = {"cache": self.cache.stats(), "replays": replays,
               "journal": self.journal.stats()
               if self.journal is not None else None}
        if self.journal is not None:
            out["completed_by_method"] = self.journal.completed_by_method()
        return out

    def close(self) -> None:
        if self.journal is not None:
            self.journal.close()
