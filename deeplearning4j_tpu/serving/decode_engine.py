"""Continuous-batching decode engine: slotted KV cache + in-flight
admission (iteration-level scheduling).

`models/transformer.generate` is a whole-batch synchronous sampler:
every request in a batch decodes the same number of tokens in lockstep,
so at mixed output lengths every request waits for the slowest sequence
and the chip idles between calls. The r4 decode profile concluded that
at serving shapes decode is dispatch+cache-bandwidth bound and
"throughput scales with batch, not with further kernel work" — the
batch dimension is therefore the scheduling resource. This engine turns
it into a pool of `n_slots` decode **slots** (Orca's iteration-level
scheduling, OSDI '22; the slot/block-managed cache family of
vLLM/PagedAttention, SOSP '23, minus paging — slots are fixed-length
rows of one contiguous cache):

- **one slotted KV cache** per block, allocated once and advanced
  in place (donated through the jitted step): K `(S, Hkv, hd, L)`,
  V `(S, Hkv, L, hd)` — the r4 decode layouts with the batch axis
  reinterpreted as the slot axis. Per-slot position and active mask
  make ONE compiled decode step correct for slots holding sequences of
  different lengths: `ops.attention.cached_attention_step` masks each
  slot's cache past its own position, inactive slots are carried
  through unchanged, so there is exactly one compiled decode shape no
  matter how requests arrive or retire.
- **a jitted decode step advances ALL active slots every iteration** —
  a request admitted mid-flight starts decoding on the very next step,
  and a request that finishes frees its slot immediately. No request
  ever waits on another request's tail.
- **a jitted prefill** writes a new prompt's KV into a freed slot at a
  small set of pow-2-padded prompt buckets (`prompt_buckets`), so the
  prefill compiles O(#buckets) shapes. Padding is harmless by
  construction: cache entries past a slot's position are never
  attended, and decode overwrites them before the position reaches
  them.
- **a host scheduler loop** admits queued requests into free slots,
  retires slots on EOS / max-tokens / expired deadlines, and delivers
  tokens per-request as they complete.

Robustness rides the PR-4 serving tier: a bounded queue sheds with the
typed `ServerOverloadedError` (+`retry_after`), a deadline expiring in
the queue sheds BEFORE prefill, a deadline expiring in flight frees its
slot for the next request, an optional `CircuitBreaker` gates admission
and counts device failures, and `drain_and_swap(net)` lets a hot reload
finish in-flight requests on the old weights, swap, and keep serving.

**Parity guarantee**: the engine traces the SAME per-block helpers as
`generate` (`models.transformer.GPTPlan`/`_block_heads`/`_block_ffn`/
`_final_logits`/`cached_attention_step`), so slotted greedy decode
reproduces whole-batch `generate` argmax-exactly at f32 for the same
prompts, regardless of admission order (asserted in
`tests/test_serving_generate.py`).
"""
from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.serving.model_server import (
    DeadlineExceededError,
    InferenceFailedError,
    ServerClosedError,
    ServerOverloadedError,
    ServiceUnavailableError,
    ServingError,
)

logger = logging.getLogger("deeplearning4j_tpu")


class _GenRequest:
    """One generation request's lifecycle: queued → (shed | prefilled
    into a slot) → decoding → (completed | expired | failed). `tokens`
    grows as the engine emits — tokens are delivered per-request as they
    complete, never held for a batch."""

    __slots__ = ("prompt", "n_tokens", "temperature", "seed", "deadline",
                 "event", "tokens", "error", "enqueued_at", "probe",
                 "slot", "completed_at")

    def __init__(self, prompt: np.ndarray, n_tokens: int,
                 temperature: float, seed: int,
                 deadline: Optional[float]):
        self.prompt = prompt
        self.n_tokens = n_tokens
        self.temperature = temperature
        self.seed = seed
        self.deadline = deadline
        self.event = threading.Event()
        self.tokens: List[int] = []
        self.error: Optional[BaseException] = None
        self.enqueued_at = time.monotonic()
        self.completed_at: Optional[float] = None
        self.probe = False
        self.slot: Optional[int] = None

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None and \
            (now if now is not None else time.monotonic()) >= self.deadline

    def finish(self, error: Optional[BaseException] = None) -> None:
        self.error = error
        self.completed_at = time.monotonic()
        self.event.set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until this request completes; the generated tokens
        (1-D int32, possibly shorter than n_tokens on EOS) or a typed
        `ServingError`."""
        wait = timeout
        if wait is None and self.deadline is not None:
            # belt-and-braces bound: the scheduler always finishes
            # deadline-stamped requests shortly after expiry
            wait = max(0.0, self.deadline - time.monotonic()) + 30.0
        if not self.event.wait(wait):
            raise InferenceFailedError(
                "generation request was never completed (engine stalled)")
        if self.error is not None:
            raise self.error
        return np.asarray(self.tokens, np.int32)


def _dispatched(thunk):
    """Run one compiled dispatch INCLUDING its host materialization,
    tagging any exception raised so the caller can tell a FAILED
    DISPATCH (which, under buffer donation, may have invalidated the
    donated cache buffers) apart from failures raised after the results
    landed (non-finite screens, hooks) — only the former justifies
    failing other slots. The device_get must live inside the thunk: on
    asynchronous backends a device-side error surfaces at
    materialization, not at the dispatch call."""
    try:
        return thunk()
    except BaseException as e:
        e._dispatch_failure = True
        raise


class DecodeEngine:
    """Continuous-batching generation over a fixed pool of decode slots
    (see module docstring).

    Parameters
    ----------
    net : a fitted `gpt_configuration` network (TokenEmbedding first).
    n_slots : decode slots = max concurrently-decoding requests; also
        the batch dimension of the one compiled decode step. Size it so
        slot_occupancy_pct stays high at your arrival rate.
    max_len : KV cache length L (prompt + generated tokens per request).
        Defaults to the embedding's max_length (clamped to it for
        learned-positional models).
    prompt_buckets : pow-2 prompt pad lengths the prefill compiles for;
        a longer prompt falls back to the next power of two ≤ max_len.
    max_queue : bounded admission queue; beyond it `submit` sheds with
        the typed `ServerOverloadedError`.
    eos_token : optional token id that retires a slot early.
    top_k : static top-k for sampled (temperature > 0) requests.
    breaker : optional `CircuitBreaker` shared with a `ModelServer` —
        admission is rejected while open, device failures count.
    step_hooks : chaos/observability seam — called as `hook(phase,
        info)` at pre/post_prefill and pre/post_decode.
    decode_chunk : fuse up to this many decode iterations into ONE
        dispatch (a `lax.scan` over the same step body — identical
        numerics) whenever no scheduling event can fall inside the
        chunk: every in-flight request needs ≥chunk more tokens, no
        deadline can expire within it, and no queued request is waiting
        on a free slot. Decode is dispatch-bound at serving shapes (r4
        profile), so this amortizes the per-iteration dispatch + host
        sync the same way `generate`'s scanned decode does, while
        keeping admission latency bounded by `decode_chunk` iterations.
        1 disables fusion.
    """

    def __init__(self, net, *, n_slots: int = 4,
                 max_len: Optional[int] = None,
                 prompt_buckets: Sequence[int] = (32, 64, 128),
                 max_queue: int = 64,
                 default_timeout: Optional[float] = None,
                 eos_token: Optional[int] = None,
                 top_k: int = 0,
                 breaker=None,
                 step_hooks: Sequence[Callable] = (),
                 decode_chunk: int = 4):
        if n_slots < 1:
            raise ValueError("n_slots must be >= 1")
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if decode_chunk < 1:
            raise ValueError("decode_chunk must be >= 1")
        self.n_slots = n_slots
        self.max_queue = max_queue
        self.default_timeout = default_timeout
        self.eos_token = eos_token
        self.top_k = top_k
        self.decode_chunk = decode_chunk
        self.breaker = breaker
        self.step_hooks: List[Callable] = list(step_hooks)
        self._requested_max_len = max_len
        self._prompt_buckets = tuple(sorted(set(int(b) for b in
                                                prompt_buckets)))
        self._cond = threading.Condition()
        self._queue: collections.deque = collections.deque()
        self._slots: List[Optional[_GenRequest]] = [None] * n_slots
        self._closed = False
        self._kill = False
        self._draining = False
        self._swap_net = None
        self._swap_in_progress = False
        self._swap_error: Optional[BaseException] = None
        self._swap_done = threading.Event()
        self._step_ewma = 0.01
        # counters (observable state for tests/telemetry)
        self.submitted = 0
        self.served = 0
        self.shed_overload = 0
        self.shed_deadline = 0
        self.shed_unavailable = 0
        self.failures = 0
        self.prefills = 0
        self.decode_steps = 0
        self.active_slot_steps = 0
        self.tokens_generated = 0
        self.swaps = 0
        self._build(net)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="decode-engine-scheduler")
        self._thread.start()

    # -- compiled machinery ------------------------------------------------
    def _build(self, net) -> None:
        """(Re)build the compiled prefill/decode pair and the slotted
        device state for `net`. Called at construction and after a
        drained weight swap; jit caches are per-engine closures, so a
        swap to a differently-shaped net recompiles cleanly."""
        import jax
        import jax.numpy as jnp
        from functools import partial

        from deeplearning4j_tpu.models.transformer import (
            GPTPlan,
            _block_ffn,
            _block_heads,
            _prefill_block_attention,
            _sample_logits,
        )
        from deeplearning4j_tpu.ops.attention import cached_attention_step

        plan = GPTPlan(net)
        L = self._requested_max_len or plan.emb.max_length
        if plan.emb.positional:
            L = min(L, plan.emb.max_length)
        if L < 2:
            raise ValueError(f"max_len {L} leaves no room to decode")
        S = self.n_slots
        emb_i, block_is = plan.emb_i, plan.block_is
        layers, emb, cdt = plan.layers, plan.emb, plan.cdt
        top_k = self.top_k
        buckets = tuple(b for b in self._prompt_buckets if b <= L) or \
            (min(32, L),)
        # buffer donation keeps the slotted cache in place in HBM instead
        # of copying ~S*L*layers of KV every step; CPU (the test backend)
        # does not support donation and would warn once per dispatch
        donate = jax.default_backend() != "cpu"
        self._donate = donate

        from deeplearning4j_tpu.models.transformer import _top_k_filter

        def scale_and_filter(logits, temps):
            """Dynamic-temperature scale + shared top-k truncation.
            `temps` broadcasts over the row dim; <= 0 rows are scaled by
            1 (their categorical draw is discarded for greedy argmax)."""
            safe_t = jnp.where(temps > 0, temps, 1.0).astype(logits.dtype)
            return _top_k_filter(logits / safe_t[..., None], top_k)

        def sample_slots(logits, keys, temps):
            """Per-slot sampling: greedy argmax where temps <= 0 (the
            parity-pinned path — identical to `_sample_logits` at
            temperature 0), per-slot-key categorical otherwise."""
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            ks = jax.vmap(jax.random.split)(keys)      # (S, 2, 2)
            new_keys, subs = ks[:, 0], ks[:, 1]
            scaled = scale_and_filter(logits, temps)
            sampled = jax.vmap(
                lambda k, lg: jax.random.categorical(k, lg))(subs, scaled)
            return jnp.where(temps > 0, sampled.astype(jnp.int32), greedy), \
                new_keys

        def logits_ok(logits, active):
            """Per-slot non-finite screen, the predict path's breaker
            discipline applied to generation: a slot whose logits go
            NaN/Inf must FAIL typed (and count toward the breaker), not
            'succeed' with garbage argmax tokens. Returns (S,) bool;
            inactive rows pass — freed slots hold stale state by
            design. Per-slot attribution means one poisoned sequence
            does not take healthy neighbors down with it."""
            row_ok = jnp.all(jnp.isfinite(logits.astype(jnp.float32)),
                             axis=-1)
            return jnp.where(active, row_ok, True)

        def step_math(bp, params, caches, tok, pos, keys, temps, active):
            """Advance ALL slots one token: inactive slots are masked
            (token/position carried through unchanged), so every
            iteration compiles to this single shape."""
            x = bp[emb_i]["W"][tok]
            if emb.positional:
                x = x + bp[emb_i]["P"][jnp.minimum(pos, emb.max_length - 1)]
            x = x.astype(cdt)
            wpos = jnp.minimum(pos, L - 1)
            rows = jnp.arange(S)
            new_caches = []
            for bi, i in enumerate(block_is):
                p = bp[i]
                layer = layers[i]
                # same operand ranks as generate's decode ((S,1,d) heads,
                # squeezed) so XLA picks the same accumulation order —
                # argmax parity is a numerics property, not just a logic
                # one. positions: a per-slot column vector
                q, k, v = _block_heads(layer, p, x[:, None, :],
                                       pos[:, None])
                q, k, v = q[:, 0], k[:, 0], v[:, 0]
                kc, vc = caches[bi]
                kc = kc.at[rows, :, :, wpos].set(k)
                vc = vc.at[rows, :, wpos, :].set(v)
                att = cached_attention_step(q, kc, vc, pos)
                att = att @ p["Wo"] + p["bo"]
                x = _block_ffn(layer, p, x + att)
                new_caches.append((kc, vc))
            logits = plan.final_logits(bp, params, x)
            nxt, new_keys = sample_slots(logits, keys, temps)
            nxt = jnp.where(active, nxt, tok)
            new_pos = jnp.where(active, pos + 1, pos)
            return new_caches, nxt, new_pos, new_keys, \
                logits_ok(logits, active)

        @partial(jax.jit, donate_argnums=(1,) if donate else ())
        def decode_step(params, caches, tok, pos, keys, temps, active):
            bp = plan.cast_blocks(params)
            return step_math(bp, params, caches, tok, pos, keys, temps,
                             active)

        @partial(jax.jit, donate_argnums=(1,) if donate else ())
        def decode_chunked(params, caches, tok, pos, keys, temps, active):
            """`decode_chunk` iterations of the SAME step body fused into
            one dispatch via lax.scan — used only when the scheduler
            proves no admission/retirement/deadline event can land inside
            the chunk. Returns every intermediate token (chunk, S)."""
            bp = plan.cast_blocks(params)

            def body(carry, _):
                caches, tok, pos, keys = carry
                caches, tok, pos, keys, step_ok = step_math(
                    bp, params, caches, tok, pos, keys, temps, active)
                return (caches, tok, pos, keys), (tok, step_ok)

            (caches, tok, pos, keys), (toks, oks) = jax.lax.scan(
                body, (caches, tok, pos, keys), None,
                length=self.decode_chunk)
            # per-STEP flags (chunk, S): the host attributes a poisoned
            # step to the right iteration, so a request that completed
            # via EOS before the bad step still succeeds
            return caches, tok, pos, keys, toks, oks

        @partial(jax.jit, donate_argnums=(1,) if donate else ())
        def prefill(params, caches, ids, t0, slot, tok, pos, keys, temps,
                    kp, kd, temp):
            """Write one prompt's KV into slot `slot` and emit its first
            token. `ids` is (1, bucket) — pow-2 padded; the pad region's
            KV entries are masked off by position until decode overwrites
            them, so padding never changes a real token's numerics."""
            bp = plan.cast_blocks(params)
            P = ids.shape[1]
            x = bp[emb_i]["W"][ids]
            if emb.positional:
                x = x + bp[emb_i]["P"][:P]
            x = x.astype(cdt)
            new_caches = []
            for bi, i in enumerate(block_is):
                p = bp[i]
                layer = layers[i]
                q, k, v = _block_heads(layer, p, x, jnp.arange(P))
                att = _prefill_block_attention(layer, q, k, v)
                d = x.shape[-1]
                att = att.reshape(1, P, d) @ p["Wo"] + p["bo"]
                x = _block_ffn(layer, p, x + att)
                kc, vc = caches[bi]
                kcol = jnp.transpose(k, (0, 2, 3, 1))   # (1, Hkv, hd, P)
                vrow = jnp.transpose(v, (0, 2, 1, 3))   # (1, Hkv, P, hd)
                z = jnp.zeros((), slot.dtype)  # match slot's index dtype
                kc = jax.lax.dynamic_update_slice(kc, kcol, (slot, z, z, z))
                vc = jax.lax.dynamic_update_slice(vc, vrow, (slot, z, z, z))
                new_caches.append((kc, vc))
            logits = plan.final_logits(bp, params, x[0, t0 - 1][None])
            # kp samples the prefill token, kd seeds the slot's decode
            # key — the same split generate() draws from PRNGKey(seed).
            # Temperature is dynamic per request, so the greedy/sampled
            # select mirrors sample_slots (same scale_and_filter core)
            greedy = _sample_logits(logits, kp, 0.0, 0)
            drawn = jax.random.categorical(
                kp, scale_and_filter(logits, temp[None]),
                axis=-1).astype(jnp.int32)
            tok0 = jnp.where(temp > 0, drawn, greedy)
            tok = tok.at[slot].set(tok0[0])
            pos = pos.at[slot].set(t0)
            keys = keys.at[slot].set(kd)
            temps = temps.at[slot].set(temp)
            return new_caches, tok, pos, keys, temps, tok0, \
                jnp.all(jnp.isfinite(logits.astype(jnp.float32)))

        self._plan = plan
        self._net = net
        self.max_len = L
        self.prompt_buckets = buckets
        self._decode_step = decode_step
        self._decode_chunked = decode_chunked
        self._prefill = prefill
        self._reset_device_state()

    def _reset_device_state(self) -> None:
        """Fresh slotted cache + per-slot state (construction, weight
        swap, or recovery after a failed device step — a raised dispatch
        may have invalidated donated buffers)."""
        import jax
        import jax.numpy as jnp

        plan, S, L = self._plan, self.n_slots, self.max_len
        caches = []
        for i in plan.block_is:
            layer = plan.layers[i]
            hd = layer.n_out // layer.n_heads
            Hkv = layer._kv_heads
            caches.append((jnp.zeros((S, Hkv, hd, L), plan.cdt),
                           jnp.zeros((S, Hkv, L, hd), plan.cdt)))
        self._caches = caches
        self._tok = jnp.zeros((S,), jnp.int32)
        self._pos = jnp.zeros((S,), jnp.int32)
        self._keys = jnp.stack([jax.random.PRNGKey(i) for i in range(S)])
        self._temps = jnp.zeros((S,), jnp.float32)
        self._active = np.zeros((S,), bool)

    # -- public surface ----------------------------------------------------
    def submit(self, prompt_ids, n_tokens: int, *,
               temperature: float = 0.0, seed: int = 0,
               timeout: Optional[float] = None) -> _GenRequest:
        """Admit one generation request (non-blocking). Typed give-ups:
        `ServerOverloadedError` (queue full), `ServiceUnavailableError`
        (breaker open), `ServerClosedError`. Returns the request handle;
        `request.result()` blocks for the tokens."""
        prompt = np.asarray(prompt_ids)
        if prompt.ndim == 2 and prompt.shape[0] == 1:
            prompt = prompt[0]
        if prompt.ndim != 1 or prompt.shape[0] < 1:
            raise ValueError(
                f"submit expects one 1-D prompt of token ids, got shape "
                f"{prompt.shape}")
        if n_tokens < 1:
            raise ValueError("n_tokens must be >= 1")
        T0 = prompt.shape[0]
        if T0 + n_tokens > self.max_len:
            raise ValueError(
                f"prompt ({T0}) + n_tokens ({n_tokens}) exceeds the "
                f"engine's max_len {self.max_len} — raise max_len or "
                "shorten the request")
        with self._cond:
            if self._closed:  # before the breaker door check: a closed
                # engine must say "closed" (terminal), not "retry later"
                raise ServerClosedError("decode engine is shut down")
        if self.breaker is not None:
            try:
                self.breaker.reject_if_open()
            except ServiceUnavailableError:
                with self._cond:
                    self.shed_unavailable += 1
                raise
        timeout = self.default_timeout if timeout is None else timeout
        deadline = None if timeout is None else time.monotonic() + timeout
        req = _GenRequest(prompt.astype(np.int32), int(n_tokens),
                          float(temperature), int(seed), deadline)
        with self._cond:
            if self._closed:
                raise ServerClosedError("decode engine is shut down")
            if len(self._queue) >= self.max_queue:
                self.shed_overload += 1
                retry = max(0.001, self._step_ewma
                            * (len(self._queue) / self.n_slots + 1))
                raise ServerOverloadedError(
                    f"generation queue full ({self.max_queue} pending); "
                    f"retry in {retry:.3f}s", retry_after=retry)
            self.submitted += 1
            self._queue.append(req)
            self._cond.notify_all()
        return req

    def generate(self, prompt_ids, n_tokens: int, *,
                 temperature: float = 0.0, seed: int = 0,
                 timeout: Optional[float] = None) -> np.ndarray:
        """Blocking convenience: submit + wait. Returns the generated
        tokens (1-D int32; shorter than `n_tokens` only on EOS)."""
        return self.submit(prompt_ids, n_tokens, temperature=temperature,
                           seed=seed, timeout=timeout).result()

    def stats(self) -> dict:
        with self._cond:
            queued = len(self._queue)
            active = sum(1 for r in self._slots if r is not None)
        occupancy = (100.0 * self.active_slot_steps
                     / (self.decode_steps * self.n_slots)
                     if self.decode_steps else 0.0)
        return {"submitted": self.submitted, "served": self.served,
                "shed_overload": self.shed_overload,
                "shed_deadline": self.shed_deadline,
                "shed_unavailable": self.shed_unavailable,
                "failures": self.failures, "prefills": self.prefills,
                "decode_steps": self.decode_steps,
                "tokens_generated": self.tokens_generated,
                "slot_occupancy_pct": round(occupancy, 1),
                "n_slots": self.n_slots, "active_slots": active,
                "queued": queued, "swaps": self.swaps,
                "max_len": self.max_len,
                "prompt_buckets": list(self.prompt_buckets)}

    def drain_and_swap(self, net, timeout: Optional[float] = None) -> None:
        """Hot-reload seam: pause admission, let every in-flight request
        FINISH on the current weights (KV caches were computed with
        them — mixing would corrupt numerics), swap to `net` (recompiling
        lazily), then resume admission. Queued requests survive the swap
        and decode on the new weights. Raises the swap-build error (e.g.
        `net` is not a gpt network) with the old weights still serving."""
        with self._cond:
            if self._closed:
                raise ServerClosedError("decode engine is shut down")
            self._swap_net = net
            self._swap_error = None
            self._swap_done.clear()
            self._draining = True
            self._cond.notify_all()
        if not self._swap_done.wait(timeout):
            with self._cond:
                # race guard: the scheduler may already be PAST the
                # _swap_net check and mid-build — abandoning then would
                # report "old weights serving" while the new ones land.
                # Only abandon a swap the scheduler has not picked up
                abandon = not self._swap_in_progress \
                    and not self._swap_done.is_set()
                if abandon:  # resume serving the old weights
                    self._swap_net = None
                    self._draining = False
                    self._cond.notify_all()
            if abandon:
                raise ServingError(
                    f"decode engine drain did not complete within "
                    f"{timeout}s (long in-flight generations); old "
                    "weights still serving")
            self._swap_done.wait()  # build already running: finish it out
        err = self._swap_error
        if err is not None:
            raise err

    def shutdown(self, drain_timeout: float = 10.0) -> bool:
        """Stop admission (typed `ServerClosedError` for queued + new
        requests), let in-flight generations finish for up to
        `drain_timeout` seconds, then fail the rest. Returns True on a
        clean drain. Idempotent."""
        deadline = time.monotonic() + drain_timeout
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        drained = True
        with self._cond:
            while any(r is not None for r in self._slots):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    drained = False
                    self._kill = True
                    self._cond.notify_all()
                    break
                self._cond.wait(min(remaining, 0.05))
        self._thread.join(max(0.0, deadline - time.monotonic()) + 5.0)
        if not drained:
            logger.warning("decode engine: shutdown drain timed out with "
                           "generations still in flight")
        return drained

    # -- scheduler ---------------------------------------------------------
    def _hook(self, phase: str, info: dict) -> None:
        for hook in self.step_hooks:
            hook(phase, info)

    def _bucket_for(self, t0: int) -> int:
        from deeplearning4j_tpu.serving.model_server import _bucket

        for b in self.prompt_buckets:
            if b >= t0:
                return b
        return _bucket(t0, self.max_len)  # pow-2 fallback past the buckets

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._closed and not self._kill \
                        and not self._work_pending():
                    self._cond.wait(0.05)
                if self._kill:
                    self._fail_all_locked(ServerClosedError(
                        "engine shut down before this request finished"))
                    self._abort_pending_swap_locked()
                    return
                if self._closed:
                    while self._queue:
                        self._queue.popleft().finish(ServerClosedError(
                            "engine shut down before this request "
                            "could be served"))
                    if not any(r is not None for r in self._slots):
                        self._abort_pending_swap_locked()
                        self._cond.notify_all()
                        return
            try:
                if not self._draining and not self._closed:
                    self._admit()
                self._expire_in_flight()
                self._step_active()
                self._maybe_swap()
            except BaseException:  # scheduler must never die silently
                logger.exception("decode engine: scheduler iteration "
                                 "failed; failing in-flight requests")
                with self._cond:
                    self._fail_all_locked(InferenceFailedError(
                        "decode engine scheduler failure"))
                self._reset_device_state()

    def _abort_pending_swap_locked(self) -> None:
        """A scheduler exit (shutdown/kill) with a drain pending must
        release the `drain_and_swap` caller — a reload blocked forever
        on a dead scheduler would also pin the ModelServer reload lock."""
        if self._draining or self._swap_net is not None:
            self._swap_net = None
            self._draining = False
            self._swap_error = ServerClosedError(
                "engine shut down while draining for a weight swap")
            self._swap_done.set()

    def _work_pending(self) -> bool:
        if any(r is not None for r in self._slots):
            return True
        if self._draining:
            return True  # reach _maybe_swap even with empty slots
        return bool(self._queue) and not self._draining

    def _fail_all_locked(self, err: BaseException) -> None:
        while self._queue:
            self._queue.popleft().finish(err)  # never acquired the breaker
        for s, req in enumerate(self._slots):
            if req is not None:
                self._slots[s] = None
                self._active[s] = False
                if self.breaker is not None:
                    # release the request's breaker token — a dropped
                    # half-open probe would wedge the shared breaker in
                    # half_open and reject ALL traffic until a reload
                    self.breaker.record_failure(req.probe)
                req.finish(err)
        self._cond.notify_all()

    def _admit(self) -> None:
        """Move queued requests into free slots (prefill each). Expired
        queued requests are shed BEFORE their prefill ever dispatches."""
        while True:
            with self._cond:
                free = [s for s in range(self.n_slots)
                        if self._slots[s] is None]
                if not free or not self._queue:
                    return
                req = self._queue.popleft()
            if req.expired():
                with self._cond:
                    self.shed_deadline += 1
                req.finish(DeadlineExceededError(
                    "deadline expired while queued; request shed before "
                    "prefill"))
                continue
            probe = False
            if self.breaker is not None:
                try:
                    probe = self.breaker.acquire()
                except ServiceUnavailableError as e:
                    with self._cond:
                        self.shed_unavailable += 1
                    req.finish(e)
                    continue
            req.probe = probe
            try:
                self._prefill_into(free[0], req)
            except BaseException as e:
                if self.breaker is not None:
                    self.breaker.record_failure(probe)
                with self._cond:
                    self.failures += 1
                err = e if isinstance(e, ServingError) else \
                    InferenceFailedError(
                        f"prefill failed: {type(e).__name__}: {e}")
                logger.warning("decode engine: prefill failure (%s)", err)
                req.finish(err)
                if self._donate and getattr(e, "_dispatch_failure", False):
                    # the raised DISPATCH may have invalidated the DONATED
                    # cache buffers — every in-flight slot's KV is gone
                    # with them, so those requests must fail too (queued
                    # ones survive: they hold no device state), then the
                    # state rebuilds. Post-dispatch failures (non-finite
                    # screen, hooks) and the no-donation CPU path leave
                    # the caches valid: only this request fails
                    cache_err = InferenceFailedError(
                        "slotted cache lost to a failed prefill dispatch "
                        "(donated buffers)")
                    with self._cond:
                        for s, r in enumerate(self._slots):
                            if r is not None:
                                self._slots[s] = None
                                self._active[s] = False
                                r.finish(cache_err)
                        self._cond.notify_all()
                    self._reset_device_state()

    def _prefill_into(self, slot: int, req: _GenRequest) -> None:
        import jax
        import jax.numpy as jnp

        t0 = req.prompt.shape[0]
        bucket = self._bucket_for(t0)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :t0] = req.prompt
        key = jax.random.PRNGKey(req.seed)
        kp, kd = jax.random.split(key)  # generate()'s prefill/decode split
        info = {"slot": slot, "bucket": bucket, "t0": t0}
        self._hook("pre_prefill", info)

        def run():
            (self._caches, self._tok, self._pos, self._keys, self._temps,
             tok0, ok) = self._prefill(
                self._net._params, self._caches, jnp.asarray(ids),
                jnp.asarray(t0, jnp.int32), jnp.asarray(slot, jnp.int32),
                self._tok, self._pos, self._keys, self._temps, kp, kd,
                jnp.asarray(req.temperature, jnp.float32))
            return jax.device_get((tok0, ok))

        first, ok = _dispatched(run)
        first = int(first[0])
        if not bool(ok):
            raise InferenceFailedError(
                "model produced non-finite logits during prefill "
                "(poisoned parameters or a numerically broken graph)")
        self._hook("post_prefill", info)
        with self._cond:
            self.prefills += 1
            self.tokens_generated += 1
        req.tokens.append(first)
        if req.n_tokens == 1 or first == self.eos_token:
            self._retire(slot, req, attached=False)
            return
        with self._cond:
            req.slot = slot
            self._slots[slot] = req
            self._active[slot] = True

    def _retire(self, slot: int, req: _GenRequest, *,
                attached: bool = True) -> None:
        """Successful completion: free the slot, credit the breaker,
        deliver the tokens."""
        with self._cond:
            if attached:
                self._slots[slot] = None
                self._active[slot] = False
            self.served += 1
            self._cond.notify_all()
        if self.breaker is not None:
            self.breaker.record_success(req.probe)
        req.finish()

    def _expire_in_flight(self) -> None:
        """An expired in-flight request frees its slot immediately — the
        next queued request takes it on the following iteration. Expired
        QUEUED requests are also swept here (not only at admission), so
        a doomed request behind long-running slots fails promptly."""
        now = time.monotonic()
        expired_queued = []
        with self._cond:
            keep = collections.deque()
            while self._queue:
                req = self._queue.popleft()
                if req.expired(now):
                    expired_queued.append(req)
                else:
                    keep.append(req)
            self._queue = keep
            self.shed_deadline += len(expired_queued)
        for req in expired_queued:
            req.finish(DeadlineExceededError(
                "deadline expired while queued; request shed before "
                "prefill"))
        for s in range(self.n_slots):
            req = self._slots[s]
            if req is not None and req.expired(now):
                with self._cond:
                    self._slots[s] = None
                    self._active[s] = False
                    self.shed_deadline += 1
                    self._cond.notify_all()
                if self.breaker is not None:
                    # the device work done so far was healthy; expiry is
                    # a deadline event, not a model failure
                    self.breaker.record_success(req.probe)
                req.finish(DeadlineExceededError(
                    f"deadline expired after {len(req.tokens)} of "
                    f"{req.n_tokens} tokens; slot freed"))

    def _chunk_eligible(self, live, now: float) -> bool:
        """A chunked dispatch is allowed only when no scheduling event
        can land inside it: every live request needs at least a full
        chunk more tokens, no deadline could expire before the chunk
        returns, and — when EOS can retire a slot mid-chunk — no queued
        request is waiting to take a freed slot (without an eos_token,
        the remaining-tokens bound already proves nothing retires
        mid-chunk). Admission waits at most one chunk — `_admit` runs
        before every dispatch."""
        if self.decode_chunk <= 1:
            return False
        if self.eos_token is not None:
            with self._cond:
                if self._queue:
                    return False  # a mid-chunk EOS would strand the slot
        margin = 2.0 * self.decode_chunk * max(self._step_ewma, 1e-4)
        for _, r in live:
            if r.n_tokens - len(r.tokens) < self.decode_chunk:
                return False
            if r.deadline is not None and r.deadline - now < margin:
                return False
        return True

    def _step_active(self) -> None:
        import jax.numpy as jnp

        live = [(s, r) for s, r in enumerate(self._slots) if r is not None]
        if not live:
            return
        now = time.monotonic()
        chunked = self._chunk_eligible(live, now)
        info = {"active": len(live), "step": self.decode_steps,
                "chunk": self.decode_chunk if chunked else 1}
        t0 = time.monotonic()
        try:
            import jax

            self._hook("pre_decode", info)

            def run():
                if chunked:
                    (self._caches, self._tok, self._pos, self._keys,
                     toks_d, oks_d) = self._decode_chunked(
                        self._net._params, self._caches, self._tok,
                        self._pos, self._keys, self._temps,
                        jnp.asarray(self._active))
                    # (chunk, S) tokens + per-step flags, ONE host sync
                    return jax.device_get((toks_d, oks_d))
                (self._caches, self._tok, self._pos, self._keys,
                 ok_d) = self._decode_step(
                    self._net._params, self._caches, self._tok,
                    self._pos, self._keys, self._temps,
                    jnp.asarray(self._active))
                # THE per-iteration host sync — the price of
                # iteration-level scheduling; chunking amortizes it
                t, o = jax.device_get((self._tok, ok_d))
                return t[None], o[None]

            toks, oks = _dispatched(run)
            self._hook("post_decode", info)
        except BaseException as e:
            err = e if isinstance(e, ServingError) else \
                InferenceFailedError(
                    f"decode step failed: {type(e).__name__}: {e}")
            logger.warning("decode engine: decode failure (%s)", err)
            with self._cond:
                self.failures += len(live)
            for s, req in live:
                if self.breaker is not None:
                    self.breaker.record_failure(req.probe)
                with self._cond:
                    self._slots[s] = None
                    self._active[s] = False
                    self._cond.notify_all()
                req.finish(err)
            if getattr(e, "_dispatch_failure", False):
                # only a failed DISPATCH can have invalidated the
                # donated cache buffers; hook failures leave them valid
                self._reset_device_state()
            return
        n_steps = toks.shape[0]
        with self._cond:
            self._step_ewma = (0.8 * self._step_ewma
                               + 0.2 * (time.monotonic() - t0) / n_steps)
            self.decode_steps += n_steps
            self.active_slot_steps += len(live) * n_steps
        for s, req in live:
            done = False
            poisoned = False
            for t in range(n_steps):
                # per-step, per-slot non-finite screen (predict's
                # breaker discipline): a poisoned step fails THIS
                # request typed — unless it already completed via EOS
                # at an earlier step of the chunk — and healthy
                # neighbors keep decoding (their cache rows are
                # untouched)
                if not bool(oks[t, s]):
                    poisoned = True
                    break
                tok = int(toks[t, s])
                req.tokens.append(tok)
                with self._cond:
                    self.tokens_generated += 1
                if len(req.tokens) >= req.n_tokens \
                        or tok == self.eos_token:
                    done = True  # EOS overshoot inside a chunk: tokens
                    break        # past EOS are dropped with the slot
            if poisoned:
                nf_err = InferenceFailedError(
                    "model produced non-finite logits during decode "
                    "(poisoned parameters or a numerically broken graph)")
                logger.warning("decode engine: %s", nf_err)
                with self._cond:
                    self.failures += 1
                    self._slots[s] = None
                    self._active[s] = False
                    self._cond.notify_all()
                if self.breaker is not None:
                    self.breaker.record_failure(req.probe)
                req.finish(nf_err)
            elif done:
                self._retire(s, req)

    def _maybe_swap(self) -> None:
        if not self._draining:
            return
        with self._cond:
            if any(r is not None for r in self._slots):
                return  # still draining: in-flight finish on old weights
            net = self._swap_net
            if net is None:  # drain abandoned (timeout in drain_and_swap)
                self._draining = False
                return
            # claimed: from here the swap WILL complete (or fail) and
            # set _swap_done — a timing-out drain_and_swap caller sees
            # this flag and waits it out instead of mis-reporting
            # "old weights still serving"
            self._swap_in_progress = True
        try:
            self._build(net)
            misfit = []
            with self._cond:
                self.swaps += 1
                # queued requests were validated against the OLD
                # max_len; the rebuilt engine may be tighter (smaller
                # emb.max_length). A request that no longer fits would
                # decode silently-wrong tail tokens past the new cache
                # length — fail it typed instead
                keep: collections.deque = collections.deque()
                while self._queue:
                    r = self._queue.popleft()
                    if r.prompt.shape[0] + r.n_tokens > self.max_len:
                        misfit.append(r)
                    else:
                        keep.append(r)
                self._queue = keep
            for r in misfit:
                r.finish(ServingError(
                    f"request (prompt {r.prompt.shape[0]} + n_tokens "
                    f"{r.n_tokens}) no longer fits the swapped engine's "
                    f"max_len {self.max_len}"))
        except BaseException as e:
            self._swap_error = e
            logger.warning("decode engine: weight swap rejected (%s); "
                           "old weights still serving", e)
        finally:
            with self._cond:
                self._swap_net = None
                self._draining = False
                self._swap_in_progress = False
                self._cond.notify_all()
            self._swap_done.set()
