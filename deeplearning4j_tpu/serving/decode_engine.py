"""Continuous-batching decode engine: PAGED KV cache + chunked prefill
over slotted iteration-level scheduling.

`models/transformer.generate` is a whole-batch synchronous sampler:
every request in a batch decodes the same number of tokens in lockstep,
so at mixed output lengths every request waits for the slowest sequence
and the chip idles between calls. The r4 decode profile concluded that
at serving shapes decode is dispatch+cache-bandwidth bound and
"throughput scales with batch, not with further kernel work" — the
batch dimension is therefore the scheduling resource. This engine turns
it into a pool of `n_slots` decode **slots** (Orca's iteration-level
scheduling, OSDI '22), with the KV memory behind the slots managed as
**pages** (PagedAttention, Kwon et al., SOSP '23) and long prompts
prefilled in **chunks interleaved with decode** (Sarathi-Serve,
Agrawal et al., 2024):

- **one paged KV pool** per block, allocated once and advanced in
  place (donated through the jitted step): K `(P, Hkv, hd, page)`,
  V `(P, Hkv, page, hd)` — the r4 decode layouts with the length axis
  cut into fixed-size pow-2 pages. Page 0 is a reserved trash page that
  absorbs masked writes from inactive slots; every other page is
  allocated to exactly one request at a time. A per-slot **page table**
  `(S, n_pages_max)` lives on device; attention dispatches through
  `ops.attention.paged_attention_step_auto` — on TPU the Pallas
  paged-attention kernel (`ops/pallas_paged_attention.py`) walks the
  page table IN PLACE, streaming pages from the pool with no dense
  transient; on CPU (and under the probe/kill-switch fallback)
  `ops.attention.paged_gather` reassembles each slot's logical cache
  in position order and the attention numerics are EXACTLY the dense
  slotted step's (`cached_attention_step` on the gathered view).
- **memory-side admission control**: a request needs
  `ceil(span/page)` pages (span = padded prefill width or
  prompt+output, whichever is larger). Pages are allocated at
  ADMISSION — queued requests hold no memory — and returned to the
  free list on retirement/expiry/failure, so slots-per-chip is bound
  by ACTUAL request lengths, not worst-case `max_len` per slot. When
  the pool is exhausted the queue head WAITS (FIFO) for a retirement
  to free pages, and the bounded queue gains a memory axis: beyond
  `max_queued_pages` of aggregate queued page demand, `submit` sheds
  with the typed `OutOfPagesError` (a `ServerOverloadedError`
  subclass, `retry_after` included) — the same at-the-door discipline
  as the count-bounded queue.
- **a jitted decode step advances ALL active slots every iteration** —
  per-slot position + active mask make ONE compiled decode shape
  correct for any mix of sequence lengths; inactive slots' cache
  writes are redirected to the trash page so a freed (and reallocated)
  page can never be corrupted by a stale lane.
- **prefill**: prompts up to the largest `prompt_buckets` entry
  prefill in ONE dispatch exactly as before (same
  `_prefill_block_attention` numerics as `generate`), now writing
  into the slot's pages. Prompts longer than every bucket AND longer
  than `prefill_chunk` prefill in fixed-size chunks of
  `prefill_chunk` tokens, at most `prefill_chunk_budget` chunk
  dispatches per scheduler iteration, INTERLEAVED with decode steps —
  admitting a 4096-token prompt no longer head-of-line-blocks every
  in-flight decode. Each chunk attends causally over
  [prior chunks ‖ itself] through the paged cache
  (`models.transformer._prefill_chunk_block_attention`); the final
  chunk samples the first token with the same kp/kd key discipline as
  `generate`.
- **a host scheduler loop** admits queued requests into free slots,
  drives pending prefill chunks, retires slots on EOS / max-tokens /
  expired deadlines, and delivers tokens per-request as they complete.

Robustness rides the PR-4 serving tier: a bounded queue sheds with the
typed `ServerOverloadedError` (+`retry_after`), the page ledger sheds
with `OutOfPagesError`, a deadline expiring in the queue sheds BEFORE
prefill, a deadline expiring in flight (mid-prefill or mid-decode)
frees its slot AND its pages, an optional `CircuitBreaker` gates
admission and counts device failures, and `drain_and_swap(net)` lets a
hot reload finish in-flight requests on the old weights, swap, and
keep serving.

**Parity guarantee**: the engine traces the SAME per-block helpers as
`generate` (`models.transformer.GPTPlan`/`_block_heads`/`_block_ffn`/
`_prefill_block_attention`/`cached_attention_step`-semantics via the
paged dispatch), and the paged storage is reassembled (fallback) or
walked (kernel) in logical-position order, so slotted greedy decode
reproduces whole-batch `generate` argmax-exactly at f32 for the same
prompts, regardless of admission order, page/slot reuse, or prefill
chunking (asserted in `tests/test_serving_generate.py`; the kernel-vs-
gather parity is pinned in `tests/test_pallas_paged_attention.py` and
by the dispatch probe itself, which checks numerics before trusting
the kernel).

**Latency tier (PR 8)** — two opt-in mechanisms compose on top:

- `prefix_cache={...}` (`serving.prefix_cache.PrefixCache`): prompts
  sharing a page-aligned prefix bind the SAME resident pool pages
  (refcounted, read-only; the first divergent page starts fresh — page-
  granular copy-on-write), skipping the shared prefill entirely. Under
  pool pressure, unreferenced cached pages are reclaimed LRU-first, so
  caching can never shrink effective capacity; every pool rebuild
  (weight swap, failure recovery) invalidates the cache wholesale.
- `speculative={"draft": ..., "k": ...}`
  (`serving.speculative.SpeculativeDecoder`): a draft model proposes k
  tokens per slot per iteration, verified in ONE batched target chunk
  through the paged cache; greedy emission stays argmax-exact and
  sampled emission distribution-exact for any draft (see that module's
  docstring). The draft keeps its own paged pools behind the same page
  table, so prefix hits skip the draft prefill too.
"""
from __future__ import annotations

import collections
import hashlib
import logging
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.serving import observability
from deeplearning4j_tpu.serving.model_server import (
    DeadlineExceededError,
    InferenceFailedError,
    OutOfPagesError,
    ServerClosedError,
    ServerOverloadedError,
    ServiceUnavailableError,
    ServingError,
    TenantQuotaExceededError,
)
from deeplearning4j_tpu.util.concurrency import assert_owned

logger = logging.getLogger("deeplearning4j_tpu")


class _GenRequest:
    """One generation request's lifecycle: queued → (shed | admitted
    into a slot, prefilled — one-shot or chunk by chunk) → decoding →
    (completed | expired | failed). `tokens` grows as the engine
    emits — tokens are delivered per-request as they complete, never
    held for a batch. `n_pages` is the page reservation taken at
    submit; `pages` the pool pages held from admission to
    retirement; `prefill_pos` the next chunk offset while a long
    prompt is mid-prefill (None once decoding)."""

    __slots__ = ("prompt", "n_tokens", "temperature", "seed", "deadline",
                 "event", "tokens", "error", "enqueued_at", "probe",
                 "slot", "completed_at", "n_pages", "pages",
                 "prefill_pos", "hit_len", "n_shared", "nodes", "digests",
                 "trace", "tenant", "priority", "resumed_at",
                 "preempted", "handoff", "import_state", "prefix_import",
                 "sink", "logprobs", "logprob_values")

    def __init__(self, prompt: np.ndarray, n_tokens: int,
                 temperature: float, seed: int,
                 deadline: Optional[float],
                 tenant: Optional[str] = None,
                 priority: str = "interactive"):
        self.prompt = prompt
        self.n_tokens = n_tokens
        self.temperature = temperature
        self.seed = seed
        self.deadline = deadline
        self.tenant = tenant
        self.priority = priority
        # preemption bookkeeping: a preempted batch request folds its
        # emitted tokens into the prompt for re-prefill (prefix-cached,
        # so the re-prefill mostly re-binds resident pages).
        # `resumed_at` = len(tokens) at the moment the current prompt
        # was formed (0 for a fresh request), so logical span math
        # stays exact: span = len(prompt) - resumed_at + n_tokens
        self.resumed_at = 0
        self.preempted = 0
        self.event = threading.Event()
        self.tokens: List[int] = []
        self.error: Optional[BaseException] = None
        self.enqueued_at = time.monotonic()
        self.completed_at: Optional[float] = None
        self.probe = False
        self.slot: Optional[int] = None
        self.n_pages = 0
        self.pages: Optional[List[int]] = None
        self.prefill_pos: Optional[int] = None
        # prefix-cache binding: hit_len prompt positions ride shared
        # pages (the first n_shared entries of `pages`, refcounted via
        # `nodes`); only pages[n_shared:] are this request's to free
        self.hit_len = 0
        self.n_shared = 0
        self.nodes: Optional[list] = None
        self.digests: list = []  # memoized per-chunk prompt digests
        # KV handoff (kv_transfer): `handoff` requests export their
        # slot state under a lease instead of entering/continuing the
        # decode loop; `import_state` carries a validated inbound
        # payload whose shipped pages re-bind at admission
        self.handoff = False
        self.import_state: Optional[dict] = None
        # cluster prefix fetch: a verified "prefix" payload fetched from
        # a directory holder on the SUBMIT thread; the scheduler binds
        # its pages at admission (or silently drops it and prefills
        # cold — the fetch is an optimization, never a dependency)
        self.prefix_import: Optional[dict] = None
        # streaming emission hook: `sink(cursor, token, logprob)` fires
        # per emitted token (serving.streaming.TokenStream.publish);
        # None = unary request, zero per-token overhead
        self.sink = None
        # per-step logprob returns: K > 0 asks for {token logprob +
        # top-K alternatives} per emitted token (requires an engine
        # built with logprobs=K'); entries accumulate alongside tokens
        self.logprobs = 0
        self.logprob_values: List[dict] = []
        # the request timeline, carried across the caller-thread →
        # scheduler-thread hop (thread-locals do not cross it)
        self.trace = observability.NULL_TRACE

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None and \
            (now if now is not None else time.monotonic()) >= self.deadline

    def finish(self, error: Optional[BaseException] = None) -> None:
        self.error = error
        self.completed_at = time.monotonic()
        self.event.set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until this request completes; the generated tokens
        (1-D int32, possibly shorter than n_tokens on EOS) or a typed
        `ServingError`."""
        wait = timeout
        if wait is None and self.deadline is not None:
            # belt-and-braces bound: the scheduler always finishes
            # deadline-stamped requests shortly after expiry
            wait = max(0.0, self.deadline - time.monotonic()) + 30.0
        if not self.event.wait(wait):
            raise InferenceFailedError(
                "generation request was never completed (engine stalled)")
        if self.error is not None:
            raise self.error
        return np.asarray(self.tokens, np.int32)


class _TenantState:
    """One tenant's QoS ledger: a token bucket over REQUESTED tokens
    (charged at submit, so a flood hits its own wall before consuming
    queue capacity) plus the per-tenant counters `stats()["tenants"]`
    publishes. Every field is synchronized by the owning engine's
    `_cond` — the ledger is only ever touched inside the engine's
    locked admission/retire sections."""

    __slots__ = ("rate", "burst", "tokens", "last_refill", "submitted",
                 "served", "shed_quota", "shed_page_quota",
                 "tokens_generated", "preemptions", "max_pages", "weight")

    def __init__(self, rate: Optional[float] = None,
                 burst: Optional[float] = None,
                 max_pages: Optional[int] = None,
                 weight: Optional[float] = None):
        self.rate = None if rate is None else float(rate)
        self.burst = float(burst) if burst is not None \
            else (self.rate if self.rate else 0.0)
        self.tokens = self.burst
        # page-pool ceiling: the sum of this tenant's page RESERVATIONS
        # (queued + resident) may not exceed max_pages — a tenant
        # inside its token-rate budget can still hoard the shared page
        # pool with a few huge-prompt requests; this caps that
        self.max_pages = None if max_pages is None else int(max_pages)
        # batch-lane stride-scheduling share: admissions charge
        # span/weight, so weight 2 gets twice the admitted work of
        # weight 1 under saturation (interactive traffic is unaffected)
        self.weight = 1.0 if weight is None else float(weight)
        self.last_refill = time.monotonic()
        self.submitted = 0
        self.served = 0
        self.shed_quota = 0
        self.shed_page_quota = 0
        self.tokens_generated = 0
        self.preemptions = 0

    def refill(self, now: float) -> None:
        # elapsed clamps at 0: a ledger created mid-admission carries a
        # `last_refill` stamped AFTER the door's `now`, and a negative
        # elapsed would start the bucket fractionally below burst —
        # spuriously rejecting a first-sight tenant's full-burst request
        if self.rate:
            self.tokens = min(
                self.burst,
                self.tokens + max(0.0, now - self.last_refill)
                * self.rate)
        self.last_refill = now

    def counters(self) -> dict:
        # rate/burst stay None (JSON null) for unquota'd tenants — a
        # 0.0 sentinel would read as "zero allowance"
        return {"submitted": self.submitted, "served": self.served,
                "shed_quota": self.shed_quota,
                "shed_page_quota": self.shed_page_quota,
                "tokens_generated": self.tokens_generated,
                "preemptions": self.preemptions,
                "rate": self.rate, "burst": self.burst or None,
                "tokens": round(self.tokens, 3),
                "max_pages": self.max_pages,
                "weight": self.weight}


def _write_pages(kp_, vp_, kcol, vrow, wpids, woff, page):
    """Scatter one contiguous prefill span (1, Hkv, hd, W) /
    (1, Hkv, W, hd) into the pool pages `wpids`: floor(W/page) aligned
    full-page writes, then a partial tail (a non-pow-2 fallback bucket,
    or a sub-page chunk) at in-page offset `woff` — which is nonzero
    only in the W < page chunked case, where chunk-aligned pow-2
    offsets guarantee the span never straddles a page boundary. Module
    level (not an engine closure) so the speculative draft's prefill
    mirrors the exact same write discipline into its own pools."""
    import jax
    import jax.numpy as jnp

    W = kcol.shape[3]
    z = jnp.zeros((), jnp.int32)
    nfull = W // page
    for j in range(nfull):
        kp_ = jax.lax.dynamic_update_slice(
            kp_, kcol[..., j * page:(j + 1) * page], (wpids[j], z, z, z))
        vp_ = jax.lax.dynamic_update_slice(
            vp_, vrow[:, :, j * page:(j + 1) * page, :], (wpids[j], z, z, z))
    if W % page:
        kp_ = jax.lax.dynamic_update_slice(
            kp_, kcol[..., nfull * page:], (wpids[nfull], z, z, woff))
        vp_ = jax.lax.dynamic_update_slice(
            vp_, vrow[:, :, nfull * page:, :], (wpids[nfull], z, woff, z))
    return kp_, vp_


def _dispatched(thunk, span=None):
    """Run one compiled dispatch INCLUDING its host materialization,
    tagging any exception raised so the caller can tell a FAILED
    DISPATCH (which, under buffer donation, may have invalidated the
    donated pool buffers) apart from failures raised after the results
    landed (non-finite screens, hooks) — only the former justifies
    failing other slots. The device_get must live inside the thunk: on
    asynchronous backends a device-side error surfaces at
    materialization, not at the dispatch call.

    `span` (tensor-parallel engines pass "tp-dispatch") wraps the
    dispatch in a trace annotation so `--trace` captures show which
    wall-time went to sharded dispatches + their collectives."""
    try:
        if span is not None:
            with observability.annotation(span):
                return thunk()
        return thunk()
    except BaseException as e:
        e._dispatch_failure = True
        raise


class DecodeEngine:
    """Continuous-batching generation over a fixed pool of decode slots
    backed by a paged KV pool (see module docstring).

    Parameters
    ----------
    net : a fitted `gpt_configuration` network (TokenEmbedding first).
    n_slots : decode slots = max concurrently-decoding requests; also
        the batch dimension of the one compiled decode step. With
        paging, KV memory is sized by `pool_pages`, not by
        `n_slots × max_len` — size `n_slots` for concurrency and the
        pool for memory.
    max_len : per-request length cap (prompt + generated tokens).
        Defaults to the embedding's max_length (clamped to it for
        learned-positional models). Also sizes the per-slot page-table
        width.
    page_size : pow-2 KV page length (positions per page). Clamped to
        the pow-2 ceiling of `max_len`. 128 matches the TPU lane width
        of the decode layouts; tests use small pages to force
        multi-page requests.
    pool_pages : allocatable KV pages shared by all slots (page 0, the
        trash page, is extra). Default `n_slots × ceil(max_len/page)` —
        the dense r5 slotted cache's exact memory budget, so the
        default cannot regress capacity. The real win runs the other
        way: on a fixed memory budget, raise `n_slots` well past
        `pool_pages × page / max_len` and let ACTUAL request lengths,
        not the worst case, decide how many decode concurrently.
    max_queued_pages : memory axis of the bounded queue: max aggregate
        page demand allowed to WAIT (queued requests hold no pages;
        this bounds how deep the page-wait room gets). Beyond it,
        `submit` sheds with the typed `OutOfPagesError` + retry_after.
        A lone waiter always queues regardless of the cap — only
        aggregate demand sheds, so any request that fits the pool is
        eventually servable. Default `4 × pool_pages` (~four pool
        turnovers of patience).
    prompt_buckets : pow-2 prompt pad lengths the one-shot prefill
        compiles for; a longer prompt falls back to the next power of
        two ≤ max_len, or to CHUNKED prefill when it is also longer
        than `prefill_chunk`.
    prefill_chunk : pow-2 chunk width for chunked prefill of long
        prompts. Chunking activates for prompts longer than both the
        largest bucket and this value (and only when it is < max_len).
    prefill_chunk_budget : max prefill-chunk dispatches per scheduler
        iteration — the knob trading admission latency of long prompts
        against decode latency of in-flight requests. 1 interleaves
        one chunk between consecutive decode steps.
    max_queue : bounded admission queue; beyond it `submit` sheds with
        the typed `ServerOverloadedError`.
    eos_token : optional token id that retires a slot early.
    top_k : static top-k for sampled (temperature > 0) requests.
    breaker : optional `CircuitBreaker` shared with a `ModelServer` —
        admission is rejected while open, device failures count.
    step_hooks : chaos/observability seam — called as `hook(phase,
        info)` at pre/post_prefill (info carries `chunk_off`/`final`
        for chunked prefill) and pre/post_decode.
    decode_chunk : fuse up to this many decode iterations into ONE
        dispatch (a `lax.scan` over the same step body — identical
        numerics) whenever no scheduling event can fall inside the
        chunk: every in-flight request needs ≥chunk more tokens, no
        deadline can expire within it, no prompt is mid-prefill, and no
        queued request is waiting on a free slot. 1 disables fusion.
        Ignored while `speculative` is active (the verify step is the
        fused dispatch then).
    prefix_cache : None (off), True, or a dict of
        `serving.prefix_cache.PrefixCache` kwargs (`max_pages`): share
        page-aligned prompt-prefix KV pages across requests —
        admission binds the longest cached prefix into the slot's page
        table (refcounts bumped, prefill skipped for those positions),
        retirement frees only refcount-zero pages, and cached pages are
        reclaimed LRU-first under pool pressure. Invalidated on every
        weight swap / pool rebuild.
    speculative : None (off) or a dict: `{"draft": <gpt net | "self" |
        config json dict>, "k": 4}` — draft-verify speculative decoding
        (`serving.speculative.SpeculativeDecoder`): up to k+1 tokens
        per scheduler iteration in two dispatches, greedy argmax-exact
        and sampled distribution-exact for any draft.
    recorder, metrics : optional shared
        `serving.observability.FlightRecorder` / `MetricsRegistry` — a
        `ModelServer`-owned engine passes its own so one
        ``flight_record`` / ``metrics`` surface covers both layers;
        a standalone engine builds private instances. Request
        timelines (queue-wait, admission, prefix-bind, prefill chunks,
        decode/spec-verify dispatches) ride `_GenRequest.trace`; all
        recording is host-side and kill-switched by
        ``DL4J_TPU_NO_TRACING=1``.
    quantize : None or ``{"kv": "int8"}`` — the quantized KV tier
        (`serving/quantize.py`): pools allocate int8 elements plus
        per-(head, position) f32 scale pools riding the same page
        table/free list, K/V quantize symmetrically per head at every
        cache write, and attention dequantizes at the read site (the
        Pallas page loop on TPU, `paged_gather_quant` on CPU/fallback).
        Halves KV bytes per token — the decode path's bandwidth
        bound — at the price of bounded numeric drift, which the
        `ModelServer` drift gates police. ``DL4J_TPU_NO_INT8_KV=1``
        overrides to full-precision pools (the bench's A/B lever).
    excursion : p99-excursion auto-dump config: None (on, defaults),
        False (off), or ``{"quantile": 0.99, "min_count": 50}`` — a
        generate-latency observation past the histogram's live
        quantile bound pins that request's timeline in the flight
        recorder's failures ring with an ``excursion`` event.
    parallel : None or ``{"tp": N}`` — tensor-parallel decode
        (`serving/tp_engine.py`): shard THIS engine Megatron-style over
        a named `tp` mesh axis — attention heads and FFN width
        partitioned, head-sharded paged K/V pools (each device owns
        Hkv/N heads of every page), two all-reduces per block. The
        page table, free list, refcounts, prefix cache, speculative
        verify and int8 KV tier all ride unchanged; per-device
        weight+KV residency drops ~1/N so a model too big for one
        chip's HBM can serve. Geometry is validated at construction
        (N must divide every block's head counts and FFN width; MoE
        rejected) — a bad config is a typed ValueError, never a trace
        error. ``{"tp": 1}``/None is the single-device engine.
    """

    def __init__(self, net, *, n_slots: int = 4,
                 max_len: Optional[int] = None,
                 page_size: int = 128,
                 pool_pages: Optional[int] = None,
                 max_queued_pages: Optional[int] = None,
                 prompt_buckets: Sequence[int] = (32, 64, 128),
                 prefill_chunk: int = 256,
                 prefill_chunk_budget: int = 1,
                 max_queue: int = 64,
                 default_timeout: Optional[float] = None,
                 eos_token: Optional[int] = None,
                 top_k: int = 0,
                 breaker=None,
                 step_hooks: Sequence[Callable] = (),
                 decode_chunk: int = 4,
                 prefix_cache=None,
                 speculative: Optional[dict] = None,
                 recorder=None,
                 metrics=None,
                 quantize: Optional[dict] = None,
                 excursion=None,
                 parallel: Optional[dict] = None,
                 qos: Optional[dict] = None,
                 role: str = "both",
                 handoff_ttl: float = 30.0,
                 logprobs: int = 0):
        if role not in ("both", "prefill", "decode"):
            raise ValueError(
                'role must be "both", "prefill" or "decode", got %r'
                % (role,))
        if handoff_ttl <= 0:
            raise ValueError("handoff_ttl must be > 0")
        if n_slots < 1:
            raise ValueError("n_slots must be >= 1")
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if decode_chunk < 1:
            raise ValueError("decode_chunk must be >= 1")
        if page_size < 1 or page_size & (page_size - 1):
            raise ValueError("page_size must be a power of two")
        if prefill_chunk < 1 or prefill_chunk & (prefill_chunk - 1):
            raise ValueError("prefill_chunk must be a power of two")
        if prefill_chunk_budget < 1:
            raise ValueError("prefill_chunk_budget must be >= 1")
        if pool_pages is not None and pool_pages < 1:
            raise ValueError("pool_pages must be >= 1")
        if max_queued_pages is not None and max_queued_pages < 0:
            raise ValueError("max_queued_pages must be >= 0")
        if quantize is not None:
            unknown = set(quantize) - {"kv"}
            if unknown:
                raise ValueError("unknown quantize keys: %s"
                                 % sorted(unknown))
            if quantize.get("kv") not in (None, "int8"):
                raise ValueError("quantize['kv'] must be 'int8', got %r"
                                 % (quantize.get("kv"),))
        if logprobs < 0:
            raise ValueError("logprobs must be >= 0")
        if logprobs and speculative:
            raise ValueError(
                "logprobs=K cannot combine with speculative decoding: "
                "accepted draft tokens have no single per-step target "
                "distribution to report")
        if logprobs and parallel and parallel.get("tp", 1) > 1:
            raise ValueError(
                "logprobs=K cannot combine with tensor parallelism yet "
                "(the top-K gather is not sharded)")
        self._logprobs_k = int(logprobs)
        self._quantize_cfg = dict(quantize) if quantize else None
        if excursion not in (None, False) and not isinstance(excursion, dict):
            raise ValueError("excursion must be None, False, or a dict")
        if qos is not None:
            if not isinstance(qos, dict):
                raise ValueError(
                    'qos must be a dict like {"tenants": {...}, '
                    '"default": {...}, "preempt": bool, "slo_shed": bool}')
            unknown = set(qos) - {"tenants", "default", "preempt",
                                  "slo_shed"}
            if unknown:
                raise ValueError("unknown qos keys: %s" % sorted(unknown))
            for name, spec in {**(qos.get("tenants") or {}),
                               "default": qos.get("default") or {}}.items():
                bad = set(spec) - {"rate", "burst", "max_pages", "weight"}
                if bad:
                    raise ValueError(
                        "unknown qos tenant keys for %r: %s"
                        % (name, sorted(bad)))
                if "rate" in spec and spec["rate"] is not None \
                        and float(spec["rate"]) <= 0:
                    raise ValueError(
                        "qos tenant %r rate must be > 0" % (name,))
                if "max_pages" in spec and spec["max_pages"] is not None \
                        and int(spec["max_pages"]) < 1:
                    raise ValueError(
                        "qos tenant %r max_pages must be >= 1" % (name,))
                if "weight" in spec and spec["weight"] is not None \
                        and float(spec["weight"]) <= 0:
                    raise ValueError(
                        "qos tenant %r weight must be > 0" % (name,))
        self._qos_cfg = dict(qos) if qos else None
        tp_degree = 1
        if parallel is not None:
            if not isinstance(parallel, dict):
                raise ValueError('parallel must be a dict like {"tp": N}')
            unknown = set(parallel) - {"tp"}
            if unknown:
                raise ValueError("unknown parallel keys: %s"
                                 % sorted(unknown))
            tp_degree = parallel.get("tp", 1)
            if not isinstance(tp_degree, int) or tp_degree < 1:
                raise ValueError("parallel['tp'] must be a positive int, "
                                 "got %r" % (tp_degree,))
        self._tp_degree = tp_degree
        self._tp = None  # TPPlan, built per (re)build when tp_degree > 1
        self.n_slots = n_slots
        self.max_queue = max_queue
        self.default_timeout = default_timeout
        self.eos_token = eos_token
        self.top_k = top_k
        self.decode_chunk = decode_chunk
        self.prefill_chunk_budget = prefill_chunk_budget
        self.breaker = breaker
        self.step_hooks: List[Callable] = list(step_hooks)
        self._requested_max_len = max_len
        self._requested_page_size = page_size
        self._requested_pool_pages = pool_pages
        self._requested_max_queued_pages = max_queued_pages
        self._requested_prefill_chunk = prefill_chunk
        self._prefix_cache_cfg = prefix_cache
        self._speculative_cfg = dict(speculative) if speculative else None
        self._draft_net = None  # resolved once; "self" re-resolves per swap
        self._prompt_buckets = tuple(sorted(set(int(b) for b in
                                                prompt_buckets)))
        self._cond = threading.Condition()
        self._queue: collections.deque = collections.deque()  # guarded by: _cond
        self._slots: List[Optional[_GenRequest]] = [None] * n_slots  # guarded by: _cond
        self._closed = False  # guarded by: _cond
        self._kill = False  # guarded by: _cond
        self._draining = False  # guarded by: _cond
        self._swap_net = None  # guarded by: _cond
        self._swap_in_progress = False  # guarded by: _cond
        self._swap_error: Optional[BaseException] = None  # guarded by: _cond
        self._swap_done = threading.Event()
        self._step_ewma = 0.01  # guarded by: _cond
        self._pages_demand_queued = 0  # guarded by: _cond
        # QoS control plane (armed by `qos={...}`): per-tenant token
        # buckets, the batch→interactive preemption switch, and the
        # SLO-shed estimators (queue-wait + prefill-chunk EWMAs; the
        # decode-step EWMA above is shared with retry_after estimates)
        _q = self._qos_cfg or {}
        self._preempt_enabled = self._qos_cfg is not None \
            and _q.get("preempt", True) is not False
        self._slo_shed_enabled = self._qos_cfg is not None \
            and _q.get("slo_shed", True) is not False
        self._default_quota = dict(_q.get("default") or {}) or None
        self._tenants: dict = {}  # guarded by: _cond
        for _name, _spec in (_q.get("tenants") or {}).items():
            self._tenants[_name] = _TenantState(
                rate=_spec.get("rate"), burst=_spec.get("burst"),
                max_pages=_spec.get("max_pages"),
                weight=_spec.get("weight"))
        # batch-lane weighted-fair queueing (stride scheduling): each
        # tenant's pass value advances by admitted-span/weight; the
        # floor tracks the last admitted tenant's pre-charge pass so an
        # idle tenant rejoins AT the floor instead of banking credit
        self._wfq_pass: dict = {}  # guarded by: _cond
        self._wfq_floor = 0.0  # guarded by: _cond
        self._queue_wait_ewma = 0.0  # guarded by: _cond
        self._chunk_ewma = 0.0  # guarded by: _cond
        # KV handoff plane (kv_transfer): disagg role, the sender-side
        # lease ledger, and the scheduler's migrate-everything switch
        from deeplearning4j_tpu.serving.kv_transfer import LeaseTable
        self._role = role
        self._leases = LeaseTable(ttl=handoff_ttl)  # guarded by: _cond
        self._migrate_all = False  # guarded by: _cond
        # counters (observable state for tests/telemetry)
        self.submitted = 0  # guarded by: _cond
        self.served = 0  # guarded by: _cond
        self.shed_overload = 0  # guarded by: _cond
        self.shed_out_of_pages = 0  # guarded by: _cond
        self.shed_deadline = 0  # guarded by: _cond
        self.shed_unavailable = 0  # guarded by: _cond
        self.failures = 0  # guarded by: _cond
        self.prefills = 0  # guarded by: _cond
        self.prefill_chunks = 0  # guarded by: _cond
        self.decode_steps = 0  # guarded by: _cond
        self.active_slot_steps = 0  # guarded by: _cond
        self.tokens_generated = 0  # guarded by: _cond
        self.pages_in_use_peak = 0  # guarded by: _cond
        self.swaps = 0  # guarded by: _cond
        # QoS counters: batch-lane slots yielded to interactive
        # pressure, SLO-estimator door sheds, per-tenant quota sheds
        self.preemptions = 0  # guarded by: _cond
        self.slo_sheds = 0  # guarded by: _cond
        self.shed_quota = 0  # guarded by: _cond
        self.shed_page_quota = 0  # guarded by: _cond
        # KV migration counters: slots exported under lease / imported
        # and resumed, lease resolutions, and outbound KV wire bytes
        self.migrations_out = 0  # guarded by: _cond
        self.migrations_in = 0  # guarded by: _cond
        self.handoffs_committed = 0  # guarded by: _cond
        self.handoffs_aborted = 0  # guarded by: _cond
        self.handoffs_expired = 0  # guarded by: _cond
        self.kv_transfer_bytes = 0  # guarded by: _cond
        # latency-tier counters (prefix cache + speculative decoding)
        self.prompt_tokens = 0  # guarded by: _cond
        self.prefix_hits = 0  # guarded by: _cond
        self.prefix_misses = 0  # guarded by: _cond
        self.prefix_hit_tokens = 0  # guarded by: _cond
        # cluster prefix tier (`bind_prefix_directory`): the directory,
        # this engine's holder id, and the peers resolver are all None
        # until bound — every cluster path is a no-op without them
        self._prefix_directory = None
        self._holder_id: Optional[str] = None
        self._prefix_peers = None  # holder_id -> peer handle, or None
        self._prefix_fetch_frame_pages = 8
        self._prefix_fetch_timeout = 5.0
        self._prefix_min_fetch_pages = 1
        # scheduler-serviced prefix export queue: RPC threads park an
        # export request here and wait; the scheduler thread — the only
        # thread allowed to touch device pools under donation — fills
        # it between dispatches
        # guarded by: _cond
        self._prefix_exports: collections.deque = collections.deque()
        # single-flight: chains with a cluster fetch in progress, so a
        # burst of same-prefix admits pulls the pages over the wire
        # ONCE — the rest wait and re-check the local cache
        # guarded by: _cond
        self._prefix_fetching: set = set()
        # fetched bundles still riding the queue toward the cache
        # (bound at ADMISSION, not at submit): waiters share the
        # winner's bundle instead of re-fetching; TTL'd by the fetch
        # timeout, duplicate binds dropped by admission's stale-check
        # guarded by: _cond
        self._prefix_fetch_ready: dict = {}
        self.prefix_fetches = 0  # guarded by: _cond
        self.prefix_fetch_fallbacks = 0  # guarded by: _cond
        self.prefix_fetch_bytes = 0  # guarded by: _cond
        self.prefix_fetch_seconds = 0.0  # guarded by: _cond
        self.prefix_exports_served = 0  # guarded by: _cond
        self.cluster_prefix_hit_tokens = 0  # guarded by: _cond
        self.spec_steps = 0  # guarded by: _cond
        self.spec_proposed = 0  # guarded by: _cond
        self.spec_accepted = 0  # guarded by: _cond
        self.spec_emitted = 0  # guarded by: _cond
        # observability: a ModelServer-owned engine shares the server's
        # recorder + registry (one flight_record / metrics surface per
        # replica); a standalone engine gets its own
        self.recorder = recorder if recorder is not None \
            else observability.FlightRecorder()
        self.metrics = metrics if metrics is not None \
            else observability.MetricsRegistry()
        self.metrics.register_stats("decode_engine", self.stats)
        self._gen_latency_hist = self.metrics.histogram(
            "decode_engine_generate_latency_ms")
        # time-to-first-token: observed at the first emitted token of
        # every FRESH request (resumed/migrated requests already paid
        # their TTFT on the original replica)
        self._ttft_hist = self.metrics.histogram(
            "decode_engine_ttft_ms")
        if excursion is not False:
            exc_cfg = dict(excursion) if excursion else {}
            self._gen_latency_hist.enable_excursion(
                quantile=float(exc_cfg.get("quantile", 0.99)),
                min_count=int(exc_cfg.get("min_count", 50)),
                hook=lambda v, bound, trace: self.recorder.pin(
                    trace, "excursion", latency_ms=round(v, 3),
                    bound_ms=round(bound, 3)))
        self.metrics.gauge("decode_engine_queued",
                           lambda: len(self._queue))
        self.metrics.gauge(
            "decode_engine_pages_in_use",
            lambda: self.pool_pages - len(self._free_pages))
        if self._tp_degree > 1:
            # per-shard gauges carry a {tp_rank} label (parsed out of
            # the series name by MetricsRegistry.exposition — one
            # metric name, degree series on the gateway scrape page);
            # shards are symmetric by construction, so every rank
            # reports the same per-shard KV residency
            for _r in range(self._tp_degree):
                self.metrics.gauge(
                    'decode_engine_tp_shard_kv_bytes_per_token'
                    '{tp_rank="%d"}' % _r,
                    lambda: self._kv_bytes_per_token // self._tp_degree)
        if self.breaker is not None \
                and getattr(self.breaker, "on_event", None) is None:
            # standalone engines wire breaker transitions themselves; a
            # server-owned breaker already feeds the shared recorder
            self.breaker.on_event = lambda state: self.recorder.event(
                "breaker", state=state)
        self._build(net)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="decode-engine-scheduler")
        self._thread.start()

    # -- compiled machinery ------------------------------------------------
    def _build(self, net) -> None:
        """(Re)build the compiled prefill/decode machinery and the paged
        device state for `net`. Called at construction and after a
        drained weight swap; jit caches are per-engine closures, so a
        swap to a differently-shaped net recompiles cleanly."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.transformer import (
            GPTPlan,
            _block_ffn,
            _block_heads,
            _block_out_proj,
            _prefill_block_attention,
            _sample_logits,
        )
        from deeplearning4j_tpu.ops.attention import (
            paged_attention_chunk_auto,
            paged_attention_step_auto,
        )

        plan = GPTPlan(net)
        # tensor-parallel plan: geometry validated HERE (construction /
        # weight swap), so a bad tp config is a typed ValueError before
        # any device work; None means the single-device engine
        tp = None
        if self._tp_degree > 1:
            from deeplearning4j_tpu.serving.tp_engine import TPPlan

            tp = TPPlan(net, plan, self._tp_degree)
        self._tp = tp
        tp_axis = tp.axis if tp is not None else None
        tp_shard = tp.degree if tp is not None else None
        L = self._requested_max_len or plan.emb.max_length
        if plan.emb.positional:
            L = min(L, plan.emb.max_length)
        if L < 2:
            raise ValueError(f"max_len {L} leaves no room to decode")
        S = self.n_slots
        emb_i, block_is = plan.emb_i, plan.block_is
        layers, emb, cdt = plan.layers, plan.emb, plan.cdt
        top_k = self.top_k
        buckets = tuple(b for b in self._prompt_buckets if b <= L) or \
            (min(32, L),)
        from deeplearning4j_tpu.serving.model_server import _bucket

        # page geometry: the logical per-slot cache length is max_len
        # rounded up to a whole number of pages AND (when chunking can
        # activate) a whole number of prefill chunks, so every padded
        # prefill width fits the slot's page-table row. A page longer
        # than max_len is clamped to max_len's pow-2 ceiling (one page
        # per slot)
        page = _bucket(L, self._requested_page_size)
        C = self._requested_prefill_chunk
        chunk_enabled = C < L
        M = max(page, C) if chunk_enabled else page
        L_logical = -(-L // M) * M
        n_pages_max = L_logical // page
        pool_pages = self._requested_pool_pages
        if pool_pages is None:
            # default: the dense r5 slotted cache's exact KV budget
            pool_pages = S * n_pages_max
        max_queued_pages = self._requested_max_queued_pages
        if max_queued_pages is None:
            max_queued_pages = 4 * pool_pages
        # buffer donation keeps the page pools in place in HBM instead
        # of copying ~pool_pages*page*layers of KV every step; CPU (the
        # test backend) does not support donation and would warn once
        # per dispatch
        donate = jax.default_backend() != "cpu"
        self._donate = donate

        # quantized-KV tier: resolved at BUILD time so the kill switch
        # (DL4J_TPU_NO_INT8_KV) flips the pool dtypes themselves, not
        # just the kernel dispatch — the bench A/B compares genuinely
        # different cache residency, and a killed build serves the
        # exact full-precision numerics
        from deeplearning4j_tpu.serving import quantize as _qz
        kv_quant = "int8" if (self._quantize_cfg is not None
                              and self._quantize_cfg.get("kv") == "int8"
                              and _qz.int8_kv_enabled()) else None
        quantize_heads = _qz.quantize_heads
        write_scale_pages = _qz._write_scale_pages

        from deeplearning4j_tpu.models.transformer import _top_k_filter

        def scale_and_filter(logits, temps):
            """Dynamic-temperature scale + shared top-k truncation.
            `temps` broadcasts over the row dim; <= 0 rows are scaled by
            1 (their categorical draw is discarded for greedy argmax)."""
            safe_t = jnp.where(temps > 0, temps, 1.0).astype(logits.dtype)
            return _top_k_filter(logits / safe_t[..., None], top_k)

        def sample_slots(logits, keys, temps):
            """Per-slot sampling: greedy argmax where temps <= 0 (the
            parity-pinned path — identical to `_sample_logits` at
            temperature 0), per-slot-key categorical otherwise."""
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            ks = jax.vmap(jax.random.split)(keys)      # (S, 2, 2)
            new_keys, subs = ks[:, 0], ks[:, 1]
            scaled = scale_and_filter(logits, temps)
            sampled = jax.vmap(
                lambda k, lg: jax.random.categorical(k, lg))(subs, scaled)
            return jnp.where(temps > 0, sampled.astype(jnp.int32), greedy), \
                new_keys

        def logits_ok(logits, active):
            """Per-slot non-finite screen, the predict path's breaker
            discipline applied to generation: a slot whose logits go
            NaN/Inf must FAIL typed (and count toward the breaker), not
            'succeed' with garbage argmax tokens. Returns (S,) bool;
            inactive rows pass — freed slots hold stale state by
            design. Per-slot attribution means one poisoned sequence
            does not take healthy neighbors down with it."""
            row_ok = jnp.all(jnp.isfinite(logits.astype(jnp.float32)),
                             axis=-1)
            return jnp.where(active, row_ok, True)

        def write_pages(kp_, vp_, kcol, vrow, wpids, woff):
            return _write_pages(kp_, vp_, kcol, vrow, wpids, woff, page)

        # logprob returns (ROADMAP 5(c)): K > 0 makes every sampler
        # site also emit (chosen logprob, top-K logprobs, top-K ids)
        # from the UNSCALED model distribution — the values are a
        # report on the model, not on the temperature/top-k sampling
        # transform, so greedy and sampled requests read the same
        # per-token numbers. Incompatible with speculative decoding
        # and TP (validated at construction), so when K > 0 the extra
        # tuple never has to cross a shard_map boundary.
        K = self._logprobs_k

        def lp_math(logits, chosen_tok):
            lsm = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            chosen = jnp.take_along_axis(
                lsm, chosen_tok[..., None].astype(jnp.int32),
                axis=-1)[..., 0]
            top_v, top_i = jax.lax.top_k(lsm, K)
            return chosen, top_v, top_i.astype(jnp.int32)

        def _shard(fn, n_in, n_out):
            """Identity on one device; under TP the body becomes the
            per-shard program of a `shard_map` over the tp mesh
            (serving/tp_engine.py) — params head/width-sharded, pools
            head-sharded, page table and slot state replicated."""
            if tp is None:
                return fn
            return tp.shard(fn, n_in=n_in, n_out=n_out)

        def step_math(bp, params, caches, page_table, tok, pos, keys,
                      temps, active):
            """Advance ALL slots one token: inactive slots are masked
            (token/position carried through unchanged, cache writes
            redirected to the trash page so a reallocated page is never
            corrupted), so every iteration compiles to this single
            shape."""
            x = bp[emb_i]["W"][tok]
            if emb.positional:
                x = x + bp[emb_i]["P"][jnp.minimum(pos, emb.max_length - 1)]
            x = x.astype(cdt)
            wpos = jnp.minimum(pos, L_logical - 1)
            lpage = wpos // page
            loff = wpos % page
            rows = jnp.arange(S)
            # inactive lanes write to the reserved trash page 0
            pids = jnp.where(active, page_table[rows, lpage], 0)
            new_caches = []
            for bi, i in enumerate(block_is):
                p = bp[i]
                layer = layers[i]
                # same operand ranks as generate's decode ((S,1,d) heads,
                # squeezed) so XLA picks the same accumulation order —
                # argmax parity is a numerics property, not just a logic
                # one. positions: a per-slot column vector
                q, k, v = _block_heads(layer, p, x[:, None, :],
                                       pos[:, None], shard=tp_shard)
                q, k, v = q[:, 0], k[:, 0], v[:, 0]
                if kv_quant:
                    # quantize the single-position (S, Hkv, hd) write
                    # per head; the scale lands at the SAME
                    # (page, head, offset) the payload does, so trash-
                    # page redirection masks both together
                    kp_, vp_, ks_, vs_ = caches[bi]
                    kq, ksc = quantize_heads(k)
                    vq, vsc = quantize_heads(v)
                    kp_ = kp_.at[pids, :, :, loff].set(kq)
                    vp_ = vp_.at[pids, :, loff, :].set(vq)
                    ks_ = ks_.at[pids, :, loff].set(ksc)
                    vs_ = vs_.at[pids, :, loff].set(vsc)
                else:
                    kp_, vp_ = caches[bi]
                    ks_ = vs_ = None
                    kp_ = kp_.at[pids, :, :, loff].set(k)
                    vp_ = vp_.at[pids, :, loff, :].set(v)
                # kernel-dispatched paged attention: on TPU the Pallas
                # kernel streams pages straight from the pool (no dense
                # gather transient — the decode path's dominant cache-
                # byte cost halves); on CPU/fallback the gather + dense
                # step reference numerics run unchanged
                att = paged_attention_step_auto(q, kp_, vp_, page_table,
                                                pos, active,
                                                k_scale=ks_, v_scale=vs_)
                att = _block_out_proj(p, att, tp_axis)
                x = _block_ffn(layer, p, x + att, axis_name=tp_axis)
                new_caches.append((kp_, vp_, ks_, vs_) if kv_quant
                                  else (kp_, vp_))
            logits = plan.final_logits(bp, params, x)
            nxt, new_keys = sample_slots(logits, keys, temps)
            nxt = jnp.where(active, nxt, tok)
            new_pos = jnp.where(active, pos + 1, pos)
            if K:
                return new_caches, nxt, new_pos, new_keys, \
                    logits_ok(logits, active), lp_math(logits, nxt)
            return new_caches, nxt, new_pos, new_keys, \
                logits_ok(logits, active)

        def decode_step(params, caches, page_table, tok, pos, keys, temps,
                        active):
            bp = plan.cast_blocks(params)
            return step_math(bp, params, caches, page_table, tok, pos,
                             keys, temps, active)

        def decode_chunked(params, caches, page_table, tok, pos, keys,
                           temps, active):
            """`decode_chunk` iterations of the SAME step body fused into
            one dispatch via lax.scan — used only when the scheduler
            proves no admission/retirement/deadline/prefill event can
            land inside the chunk (page tables are therefore invariant
            across it). Returns every intermediate token (chunk, S)."""
            bp = plan.cast_blocks(params)

            def body(carry, _):
                caches, tok, pos, keys = carry
                out = step_math(bp, params, caches, page_table, tok,
                                pos, keys, temps, active)
                if K:
                    caches, tok, pos, keys, step_ok, lp = out
                    return (caches, tok, pos, keys), (tok, step_ok, lp)
                caches, tok, pos, keys, step_ok = out
                return (caches, tok, pos, keys), (tok, step_ok)

            if K:
                (caches, tok, pos, keys), (toks, oks, lps) = jax.lax.scan(
                    body, (caches, tok, pos, keys), None,
                    length=self.decode_chunk)
                return caches, tok, pos, keys, toks, oks, lps
            (caches, tok, pos, keys), (toks, oks) = jax.lax.scan(
                body, (caches, tok, pos, keys), None,
                length=self.decode_chunk)
            # per-STEP flags (chunk, S): the host attributes a poisoned
            # step to the right iteration, so a request that completed
            # via EOS before the bad step still succeeds
            return caches, tok, pos, keys, toks, oks

        def prefill(params, caches, ids, t0, slot, wpids, tok, pos, keys,
                    temps, kp, kdec, temp):
            """One-shot prefill: write one prompt's KV into the slot's
            pages and emit its first token. `ids` is (1, bucket) — pow-2
            padded; the pad region's KV entries land in the request's
            own pages and are masked off by position until decode
            overwrites them, so padding never changes a real token's
            numerics. The block math is IDENTICAL to `generate`'s
            prefill (`_prefill_block_attention`) — only the cache
            write targets pages instead of a slot row."""
            bp = plan.cast_blocks(params)
            P = ids.shape[1]
            x = bp[emb_i]["W"][ids]
            if emb.positional:
                x = x + bp[emb_i]["P"][:P]
            x = x.astype(cdt)
            new_caches = []
            for bi, i in enumerate(block_is):
                p = bp[i]
                layer = layers[i]
                q, k, v = _block_heads(layer, p, x, jnp.arange(P),
                                       shard=tp_shard)
                att = _prefill_block_attention(layer, q, k, v)
                att = _block_out_proj(p, att.reshape(1, P, -1), tp_axis)
                x = _block_ffn(layer, p, x + att, axis_name=tp_axis)
                kcol = jnp.transpose(k, (0, 2, 3, 1))   # (1, Hkv, hd, P)
                vrow = jnp.transpose(v, (0, 2, 1, 3))   # (1, Hkv, P, hd)
                z0 = jnp.zeros((), jnp.int32)
                if kv_quant:
                    # the prompt span quantizes per (head, position):
                    # abs-max over the hd axis of each lane-last layout
                    kp_, vp_, ks_, vs_ = caches[bi]
                    kcol, kscol = quantize_heads(kcol, axis=2)
                    vrow, vscol = quantize_heads(vrow, axis=3)
                    ks_ = write_scale_pages(ks_, kscol, wpids, z0, page)
                    vs_ = write_scale_pages(vs_, vscol, wpids, z0, page)
                    kp_, vp_ = write_pages(kp_, vp_, kcol, vrow, wpids, z0)
                    new_caches.append((kp_, vp_, ks_, vs_))
                else:
                    kp_, vp_ = caches[bi]
                    kp_, vp_ = write_pages(kp_, vp_, kcol, vrow, wpids, z0)
                    new_caches.append((kp_, vp_))
            logits = plan.final_logits(bp, params, x[0, t0 - 1][None])
            # kp samples the prefill token, kdec seeds the slot's decode
            # key — the same split generate() draws from PRNGKey(seed).
            # Temperature is dynamic per request, so the greedy/sampled
            # select mirrors sample_slots (same scale_and_filter core)
            greedy = _sample_logits(logits, kp, 0.0, 0)
            drawn = jax.random.categorical(
                kp, scale_and_filter(logits, temp[None]),
                axis=-1).astype(jnp.int32)
            tok0 = jnp.where(temp > 0, drawn, greedy)
            tok = tok.at[slot].set(tok0[0])
            pos = pos.at[slot].set(t0)
            keys = keys.at[slot].set(kdec)
            temps = temps.at[slot].set(temp)
            ok0 = jnp.all(jnp.isfinite(logits.astype(jnp.float32)))
            if K:
                return new_caches, tok, pos, keys, temps, tok0, ok0, \
                    lp_math(logits, tok0)
            return new_caches, tok, pos, keys, temps, tok0, ok0

        def prefill_chunk_fn(params, caches, page_row, ids, off, woff,
                             t0, slot, wpids, tok, pos, keys, temps, kp,
                             kdec, temp):
            """One prefill CHUNK: embed `ids` (1, prefill_chunk) at
            absolute positions off..off+C-1, write its KV into pages
            `wpids`, attend causally over [prior chunks ‖ this chunk]
            through the slot's gathered page row, and emit logits at
            prompt position t0-1 (only meaningful — and only consumed
            by the host — on the FINAL chunk). Slot token/position/key
            state is set every chunk; the final chunk's values are the
            ones that stick before decode starts."""
            bp = plan.cast_blocks(params)
            Cw = ids.shape[1]
            qpos = off + jnp.arange(Cw)
            x = bp[emb_i]["W"][ids]
            if emb.positional:
                # gather (not dynamic_slice): a padded final chunk may
                # run past the positional table, and dynamic_slice's
                # start-clamping would silently shift REAL positions —
                # the per-position clamp only garbles the masked pad
                # tail
                x = x + bp[emb_i]["P"][jnp.minimum(qpos,
                                                   emb.max_length - 1)]
            x = x.astype(cdt)
            new_caches = []
            for bi, i in enumerate(block_is):
                p = bp[i]
                layer = layers[i]
                q, k, v = _block_heads(layer, p, x, qpos, shard=tp_shard)
                kcol = jnp.transpose(k, (0, 2, 3, 1))   # (1, Hkv, hd, C)
                vrow = jnp.transpose(v, (0, 2, 1, 3))   # (1, Hkv, C, hd)
                if kv_quant:
                    kp_, vp_, ks_, vs_ = caches[bi]
                    kcol, kscol = quantize_heads(kcol, axis=2)
                    vrow, vscol = quantize_heads(vrow, axis=3)
                    ks_ = write_scale_pages(ks_, kscol, wpids, woff, page)
                    vs_ = write_scale_pages(vs_, vscol, wpids, woff, page)
                else:
                    kp_, vp_ = caches[bi]
                    ks_ = vs_ = None
                kp_, vp_ = write_pages(kp_, vp_, kcol, vrow, wpids, woff)
                # attend AFTER the write: the chunk attends to itself
                # through the cache, which is exactly causal with the
                # <= qpos mask; the auto path walks the slot's page row
                # in place on TPU and falls back to gather + chunk
                # (`_prefill_chunk_block_attention` numerics) elsewhere
                att = paged_attention_chunk_auto(q, kp_, vp_,
                                                 page_row[None],
                                                 off[None],
                                                 k_scale=ks_, v_scale=vs_)
                att = _block_out_proj(p, att.reshape(1, Cw, -1), tp_axis)
                x = _block_ffn(layer, p, x + att, axis_name=tp_axis)
                new_caches.append((kp_, vp_, ks_, vs_) if kv_quant
                                  else (kp_, vp_))
            r = jnp.clip(t0 - 1 - off, 0, Cw - 1)
            logits = plan.final_logits(bp, params, x[0, r][None])
            greedy = _sample_logits(logits, kp, 0.0, 0)
            drawn = jax.random.categorical(
                kp, scale_and_filter(logits, temp[None]),
                axis=-1).astype(jnp.int32)
            tok0 = jnp.where(temp > 0, drawn, greedy)
            tok = tok.at[slot].set(tok0[0])
            pos = pos.at[slot].set(t0)
            keys = keys.at[slot].set(kdec)
            temps = temps.at[slot].set(temp)
            # screen the whole chunk's hidden states, not only the
            # logits row: a non-finite mid-prompt chunk poisons the
            # cache it just wrote, and must fail HERE, typed
            ok = jnp.all(jnp.isfinite(logits.astype(jnp.float32))) \
                & jnp.all(jnp.isfinite(x.astype(jnp.float32)))
            if K:
                return new_caches, tok, pos, keys, temps, tok0, ok, \
                    lp_math(logits, tok0)
            return new_caches, tok, pos, keys, temps, tok0, ok

        # jit OUTSIDE the shard_map (donation must alias the sharded
        # pool buffers, and an inner jit would be inlined by the
        # per-shard trace) — the literal jax.jit assign keeps
        # graftlint's donation rule pointed at these call sites
        decode_step = jax.jit(_shard(decode_step, 8, 5),
                              donate_argnums=(1,) if donate else ())
        decode_chunked = jax.jit(_shard(decode_chunked, 8, 6),
                                 donate_argnums=(1,) if donate else ())
        prefill = jax.jit(_shard(prefill, 13, 7),
                          donate_argnums=(1,) if donate else ())
        prefill_chunk_fn = jax.jit(_shard(prefill_chunk_fn, 16, 7),
                                   donate_argnums=(1,) if donate else ())
        # params placed once per (re)build: permuted + head/width-
        # sharded over the mesh under TP (a weight swap reshards from
        # the swapped net's clean host copy), the net's own tree
        # otherwise
        self._dparams = tp.shard_params(net._params) if tp is not None \
            else net._params
        self._tp_span = "tp-dispatch" if tp is not None else None
        self._plan = plan
        self._net = net
        self.max_len = L
        self.page_size = page
        self.pool_pages = pool_pages
        self.max_queued_pages = max_queued_pages
        self.prefill_chunk = C
        self._chunk_enabled = chunk_enabled
        self._n_pages_max = n_pages_max
        self._L_logical = L_logical
        self.prompt_buckets = buckets
        self._decode_step = decode_step
        self._decode_chunked = decode_chunked
        self._prefill = prefill
        self._prefill_chunk_fn = prefill_chunk_fn
        self._kv_quant = kv_quant
        self._kv_quant_bits = 8 if kv_quant \
            else 8 * jnp.dtype(cdt).itemsize
        self._kv_bytes_per_token = _qz.kv_bytes_per_token(
            plan.kv_geometry(), kv_quant, jnp.dtype(cdt).itemsize)
        # content digest of the served weights: KV handoffs are stamped
        # with the sender's digest and refused typed on mismatch — a
        # page of KV computed under other weights must never re-bind
        # here (and never seed this engine's prefix cache)
        _wh = hashlib.blake2b(digest_size=8)
        for _leaf in jax.tree_util.tree_leaves(net._params):
            _arr = np.ascontiguousarray(np.asarray(_leaf))
            _wh.update(str(_arr.dtype).encode())
            _wh.update(str(_arr.shape).encode())
            _wh.update(_arr.tobytes())
        self._weight_version = _wh.hexdigest()
        # latency tier: prefix cache + speculative decoder are rebuilt
        # with the geometry on every (re)build, so a weight swap always
        # starts them cold — stale pages can never serve new weights
        self._prefix_cache = None
        if self._prefix_cache_cfg is not None \
                and self._prefix_cache_cfg is not False:
            from deeplearning4j_tpu.serving.prefix_cache import PrefixCache

            pc_kw = {} if self._prefix_cache_cfg is True \
                else dict(self._prefix_cache_cfg)
            self._prefix_cache = PrefixCache(page, **pc_kw) \
                .bind_guard(self._cond).bind_recorder(self.recorder) \
                .bind_version(self._weight_version)
            if self._prefix_directory is not None:
                # a rebuild keeps the engine's cluster membership: the
                # fresh cache re-publishes under the NEW weight version
                # as it warms (old entries age out / were dropped)
                self._prefix_cache.bind_directory(
                    self._prefix_directory, self._holder_id)
        self._spec = None
        if self._speculative_cfg is not None:
            from deeplearning4j_tpu.serving.speculative import (
                SpeculativeDecoder,
                resolve_draft_net,
            )

            cfg = dict(self._speculative_cfg)
            draft = cfg.pop("draft", None)
            if draft is None:
                draft = cfg.pop("net", None)  # alias; both given ->
                # "net" survives into the unknown-option check below
            k = int(cfg.pop("k", 4))
            if cfg:
                raise ValueError(
                    f"unknown speculative options {sorted(cfg)}")
            if draft == "self" or self._draft_net is None:
                self._draft_net = resolve_draft_net(draft, net)
            self._spec = SpeculativeDecoder(
                target_plan=plan, target_net=net,
                draft_net=self._draft_net, k=k, n_slots=S, page=page,
                L_logical=L_logical, pool_pages=pool_pages,
                top_k=self.top_k, donate=donate, kv_quant=kv_quant,
                tp=tp, tp_params=self._dparams if tp is not None else None)
        self._reset_device_state()

    def _reset_device_state(self) -> None:
        """Fresh page pools + page table + per-slot state (construction,
        weight swap, or recovery after a failed device step — a raised
        dispatch may have invalidated donated buffers). Callers
        guarantee no slot holds a request when this runs, so the free
        list rebuilds to the full pool; queued requests keep their
        reservations (they hold no device state)."""
        import jax
        import jax.numpy as jnp

        plan, S = self._plan, self.n_slots
        page, P = self.page_size, self.pool_pages
        caches = []
        for i in plan.block_is:
            layer = plan.layers[i]
            hd = layer.n_out // layer.n_heads
            Hkv = layer._kv_heads
            # +1: page 0 is the reserved trash page for masked writes
            if self._kv_quant:
                # int8 payload pools + f32 per-(head, position) scale
                # pools riding the same page table; zero scales never
                # dequantize stale garbage (0 * s == 0 either way), but
                # 1.0 keeps the trash page's dequant exactly 0.0 in one
                # multiply like a real all-zero write would
                caches.append(
                    (jnp.zeros((P + 1, Hkv, hd, page), jnp.int8),
                     jnp.zeros((P + 1, Hkv, page, hd), jnp.int8),
                     jnp.ones((P + 1, Hkv, page), jnp.float32),
                     jnp.ones((P + 1, Hkv, page), jnp.float32)))
            else:
                caches.append(
                    (jnp.zeros((P + 1, Hkv, hd, page), plan.cdt),
                     jnp.zeros((P + 1, Hkv, page, hd), plan.cdt)))
        if self._tp is not None:
            # head axis (axis 1 in every pool + scale-sidecar layout)
            # over `tp`: each device owns Hkv/N heads of EVERY page, so
            # the page table / free list / refcounts below stay
            # host-global and byte-identical to the single-device engine
            caches = [tuple(self._tp.shard_pool(x) for x in c)
                      for c in caches]
        self._caches = caches
        self._page_table = jnp.zeros((S, self._n_pages_max), jnp.int32)
        self._tok = jnp.zeros((S,), jnp.int32)
        self._pos = jnp.zeros((S,), jnp.int32)
        self._keys = jnp.stack([jax.random.PRNGKey(i) for i in range(S)])
        self._temps = jnp.zeros((S,), jnp.float32)
        # the free list and the active mask are read by submit()/stats()
        # on caller threads — publish the rebuilt state under the lock
        # (the device arrays above are scheduler-thread-owned)
        with self._cond:
            self._free_pages = list(range(P, 0, -1))  # guarded by: _cond
            self._active = np.zeros((S,), bool)  # guarded by: _cond
            if self._prefix_cache is not None:
                # the pools just rebuilt: every cached page id is stale
                self._prefix_cache.clear()
            # leased page ids index into the pools that just vanished:
            # void the ownership (the free list above is already whole)
            # but keep payloads fetchable — a receiver mid-resume holds
            # host copies and must still be able to finish
            self._leases.invalidate_pages()
        if self._spec is not None:
            self._spec.reset_state()

    # -- paging arithmetic -------------------------------------------------
    def _bucket_for(self, t0: int) -> int:
        from deeplearning4j_tpu.serving.model_server import _bucket

        for b in self.prompt_buckets:
            if b >= t0:
                return b
        return _bucket(t0, self.max_len)  # pow-2 fallback past the buckets

    def _is_chunked(self, t0: int) -> bool:
        return self._chunk_enabled and t0 > self.prompt_buckets[-1] \
            and t0 > self.prefill_chunk

    def _prefill_width(self, t0: int) -> int:
        C = self.prefill_chunk
        return -(-t0 // C) * C if self._is_chunked(t0) \
            else self._bucket_for(t0)

    def _pages_for(self, t0: int, n_tokens: int) -> int:
        """Pages a request must hold: its padded prefill width (pad-
        tail KV lands in owned pages) or prompt+output KV span,
        whichever is larger. The last generated token is never written
        back, hence n_tokens - 1. This is the COLD cost — reservations
        and queue demand always use it, so a cache hit can only shrink
        the allocation at admission, never under-reserve."""
        span = max(self._prefill_width(t0), t0 + n_tokens - 1)
        return -(-span // self.page_size)

    def _pages_for_hit(self, t0: int, n_tokens: int) -> int:
        """Total LOGICAL pages of a prefix-hit request (shared + owned):
        the hit path suffix-prefills in chunks whose padded tail never
        runs past page·ceil(t0/page), so the span is just the KV the
        request actually writes — always <= the cold `_pages_for`."""
        return -(-(t0 + n_tokens - 1) // self.page_size)

    def _free_request_pages_locked(self, req: _GenRequest) -> None:
        """Drop the request's page references: owned pages return to the
        free list; shared (cached) pages only lose this request's
        refcount — the cache keeps them resident until LRU reclaim, and
        a prefix another slot still shares is never freed here."""
        assert_owned(self._cond, "DecodeEngine._free_request_pages_locked")
        if req.nodes:
            self._prefix_cache.release(req.nodes)
            req.nodes = None
        if req.pages:
            self._free_pages.extend(req.pages[req.n_shared:])
        req.pages = None

    def _promote_prefix_locked(self, req: _GenRequest) -> None:
        """After a successful prefill, publish the prompt's fully-
        covered pages into the prefix cache so the NEXT same-prefix
        request shares them (the request itself keeps decoding on them;
        page ownership moves to the cache, refcounted)."""
        assert_owned(self._cond, "DecodeEngine._promote_prefix_locked")
        if self._prefix_cache is None or req.pages is None:
            return
        req.nodes, freed = self._prefix_cache.insert(req.prompt, req.pages,
                                                     req.nodes or [],
                                                     tenant=req.tenant)
        req.n_shared = len(req.nodes)
        # pages evicted to respect the cache's max_pages cap go straight
        # back to the pool — a cap-driven eviction must never leak
        self._free_pages.extend(freed)

    # -- observability -----------------------------------------------------
    # graftlint: hot-loop
    def _finish_obs(self, req: _GenRequest,
                    err: Optional[BaseException] = None, **attrs) -> None:
        """Terminal path for one generation request: stamp the
        timeline's decision, attach it to the typed error (in-process
        callers and the gateway payload both carry it), ring the flight
        recorder, deliver. Pure host-side work — safe inside hot-loop
        scopes. A batch-shared error instance is stamped last-writer-
        wins (see `observability.attach_trace`)."""
        decision = "served" if err is None else type(err).__name__
        req.trace.finish(decision)
        if err is not None:
            observability.attach_trace(err, req.trace)
        self.recorder.record(req.trace, decision, kind="generate",
                             tokens=len(req.tokens), **attrs)
        req.finish(err)

    # graftlint: hot-loop
    def _shed_obs(self, trace, err: BaseException, **attrs) -> None:
        """Door-shed path (no request handle yet): finish the timeline
        with the typed decision and pin it in the failures ring."""
        decision = type(err).__name__
        trace.finish(decision)
        observability.attach_trace(err, trace)
        self.recorder.record(trace, decision, kind="generate", **attrs)

    # graftlint: hot-loop
    def _emit_token(self, req: _GenRequest, lp=None,
                    lp_idx: int = 0) -> None:
        """Per-emitted-token bookkeeping, called right after a token is
        appended to `req.tokens`: record the request's logprob entry
        (when it asked for K > 0; `lp` is the device-fetched
        (chosen, top_values, top_ids) batch, `lp_idx` this token's row),
        observe TTFT on a fresh request's first token, and publish into
        the request's stream sink (`streaming.TokenStream.publish` —
        O(1), never blocks on a consumer). A raising sink is a consumer
        bug: it is disarmed loudly so it can never poison the scheduler
        loop — the unary result still delivers."""
        if lp is not None and req.logprobs:
            kk = req.logprobs
            chosen, top_v, top_i = lp
            req.logprob_values.append({
                "token": int(req.tokens[-1]),
                "logprob": float(chosen[lp_idx]),
                "top_tokens": [int(t) for t in top_i[lp_idx][:kk]],
                "top_logprobs": [float(v) for v in top_v[lp_idx][:kk]],
            })
        if len(req.tokens) == 1 and req.resumed_at == 0 \
                and not req.preempted:
            self._ttft_hist.observe(
                1e3 * (time.monotonic() - req.enqueued_at),
                trace=req.trace)
        sink = req.sink
        if sink is not None:
            entry = req.logprob_values[-1] \
                if req.logprobs and req.logprob_values else None
            try:
                sink(len(req.tokens), req.tokens[-1], entry)
            # graftlint: disable=typed-error  scheduler protection: a
            # broken stream sink must cost the CONSUMER its stream, not
            # the engine its loop — logged + disarmed, decode continues
            except Exception:
                logger.exception(
                    "decode engine: stream sink failed; detaching it")
                req.sink = None

    def flight_record(self) -> dict:
        """Dump the flight recorder (request timelines + scheduler
        events) — shared with the owning `ModelServer` when there is
        one."""
        return self.recorder.dump()

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    def metrics_text(self, labels=None) -> str:
        return self.metrics.exposition(labels=labels)

    # -- public surface ----------------------------------------------------
    def submit(self, prompt_ids, n_tokens: int, *,
               temperature: float = 0.0, seed: int = 0,
               timeout: Optional[float] = None,
               tenant: Optional[str] = None,
               priority: str = "interactive",
               logprobs: int = 0,
               on_token: Optional[Callable] = None) -> _GenRequest:
        """Admit one generation request (non-blocking). Typed give-ups:
        `ServerOverloadedError` (queue full), `OutOfPagesError` (the
        paged KV pool cannot reserve this request's pages right now),
        `TenantQuotaExceededError` (THIS tenant's token-rate budget is
        spent — never another tenant's overload), `DeadlineExceededError`
        (already expired, or the SLO estimator proves the deadline
        cannot be met), `ServiceUnavailableError` (breaker open),
        `ServerClosedError`. `priority` is `"interactive"` (default) or
        `"batch"` — the batch lane fills otherwise-idle slots and
        yields them (preemption, `qos={...}`) under interactive
        pressure. Returns the request handle; `request.result()` blocks
        for the tokens. `logprobs=K` (K > 0; requires an engine built
        with `logprobs >= K`) asks for per-token logprob entries
        alongside the tokens; `on_token(cursor, token, logprob)` is the
        streaming emission hook — called from the scheduler thread per
        emitted token, it must be O(1) and non-blocking
        (`serving.streaming.TokenStream.publish` is the intended
        sink)."""
        if priority not in ("interactive", "batch"):
            raise ValueError(
                f"priority must be 'interactive' or 'batch', got "
                f"{priority!r}")
        if logprobs < 0:
            raise ValueError("logprobs must be >= 0")
        if logprobs > self._logprobs_k:
            raise ValueError(
                f"logprobs={logprobs} exceeds the engine's configured "
                f"logprobs={self._logprobs_k} — build the engine with "
                "logprobs=K to enable per-token logprob returns")
        prompt = np.asarray(prompt_ids)
        if prompt.ndim == 2 and prompt.shape[0] == 1:
            prompt = prompt[0]
        if prompt.ndim != 1 or prompt.shape[0] < 1:
            raise ValueError(
                f"submit expects one 1-D prompt of token ids, got shape "
                f"{prompt.shape}")
        if n_tokens < 1:
            raise ValueError("n_tokens must be >= 1")
        T0 = prompt.shape[0]
        if T0 + n_tokens > self.max_len:
            raise ValueError(
                f"prompt ({T0}) + n_tokens ({n_tokens}) exceeds the "
                f"engine's max_len {self.max_len} — raise max_len or "
                "shorten the request")
        need = self._pages_for(T0, n_tokens)
        if need > self.pool_pages:
            raise ValueError(
                f"request needs {need} KV pages of {self.page_size} "
                f"tokens but the pool holds only {self.pool_pages} — "
                "raise pool_pages or shorten the request")
        if self._role == "decode":
            from deeplearning4j_tpu.serving.kv_transfer import (
                KVTransferError,
            )

            raise KVTransferError(
                "decode-role engine accepts only resume_generate "
                "handoffs, not fresh prompts — route prefills to a "
                "prefill-role replica")
        trace = observability.maybe_trace()
        with self._cond:
            if self._closed:  # before the breaker door check: a closed
                # engine must say "closed" (terminal), not "retry later"
                err = ServerClosedError("decode engine is shut down")
                self._shed_obs(trace, err)
                raise err
        if self.breaker is not None:
            try:
                self.breaker.reject_if_open()
            except ServiceUnavailableError as e:
                with self._cond:
                    self.shed_unavailable += 1
                self._shed_obs(trace, e)
                raise
        timeout = self.default_timeout if timeout is None else timeout
        deadline = None if timeout is None else time.monotonic() + timeout
        req = _GenRequest(prompt.astype(np.int32), int(n_tokens),
                          float(temperature), int(seed), deadline,
                          tenant=tenant, priority=priority)
        req.n_pages = need
        req.trace = trace
        req.logprobs = int(logprobs)
        req.sink = on_token
        # a prefill-role engine never decodes: the finished prefill is
        # exported under a lease and the caller redirected
        req.handoff = self._role == "prefill"
        if self._prefix_directory is not None \
                and self._prefix_peers is not None:
            # cluster prefix fetch rides the SUBMIT thread — wire I/O
            # must never stall the scheduler. `_admit` binds the
            # verified payload under the lock, or drops it and
            # prefills cold (a fetch wasted on a door refusal below is
            # accepted; it touched no engine state)
            req.prefix_import = self._fetch_prefix_for(req.prompt, tenant)
        with self._cond:
            if self._closed:
                err = ServerClosedError("decode engine is shut down")
                self._shed_obs(trace, err)
                raise err
            now = time.monotonic()
            # door-order contract (pinned by tests): expired corpses are
            # swept and the incoming request's own deadline is judged
            # BEFORE any capacity verdict — a dead request must hear
            # DeadlineExceededError, and a queue padded with dead
            # entries is not real backpressure. Then the tenant's OWN
            # quota, then the SLO estimate, and only then the shared
            # queue/page limits.
            if len(self._queue) >= self.max_queue \
                    or (self._pages_demand_queued
                        and self._pages_demand_queued + need
                        > self.max_queued_pages):
                self._sweep_expired_locked(now)
            if deadline is not None and deadline <= now:
                self.shed_deadline += 1
                err = DeadlineExceededError(
                    "deadline expired before admission; request shed at "
                    "the door")
                self._shed_obs(trace, err)
                raise err
            tstate = self._tenant_locked(tenant)
            if tstate is not None and tstate.rate:
                tstate.refill(now)
                if tstate.tokens < n_tokens:
                    tstate.shed_quota += 1
                    self.shed_quota += 1
                    retry = max(0.001,
                                (n_tokens - tstate.tokens) / tstate.rate)
                    err = TenantQuotaExceededError(
                        f"tenant {tenant!r} token-rate quota exhausted "
                        f"({tstate.tokens:.0f} of {n_tokens} tokens "
                        f"available at {tstate.rate:.0f} tok/s); retry "
                        f"in {retry:.3f}s", retry_after=retry)
                    self._shed_obs(trace, err, tenant=tenant,
                                   bucket_tokens=round(tstate.tokens, 1),
                                   rate=tstate.rate, n_tokens=int(n_tokens))
                    self.recorder.event(
                        "quota-shed", tenant=tenant,
                        bucket_tokens=round(tstate.tokens, 1),
                        rate=tstate.rate, n_tokens=int(n_tokens))
                    raise err
            if tstate is not None and tstate.max_pages is not None:
                # page-pool ceiling: this tenant's RESERVATIONS (queued
                # demand + resident requests) may not exceed max_pages.
                # Reservation accounting (n_pages, the cold cost) is
                # leak-proof by construction — it is recomputed from the
                # live queue/slots, never an incremental ledger
                live = self._tenant_pages_locked(tenant)
                if live + need > tstate.max_pages:
                    tstate.shed_page_quota += 1
                    self.shed_page_quota += 1
                    retry = max(0.001, self._step_ewma
                                * (len(self._queue) + 1))
                    err = TenantQuotaExceededError(
                        f"tenant {tenant!r} KV page quota exhausted "
                        f"({live} of {tstate.max_pages} pages reserved; "
                        f"{need} more needed); retry in {retry:.3f}s",
                        retry_after=retry)
                    self._shed_obs(trace, err, tenant=tenant,
                                   pages_reserved=live,
                                   max_pages=tstate.max_pages,
                                   pages_needed=need)
                    self.recorder.event(
                        "quota-shed", tenant=tenant, resource="pages",
                        pages_reserved=live,
                        max_pages=tstate.max_pages, pages_needed=need)
                    raise err
            if self._slo_shed_enabled and deadline is not None \
                    and self.decode_steps:
                # can this request provably not meet its deadline? The
                # estimate is grounded in OBSERVED EWMAs (hence the
                # decode_steps gate): expected queue wait + its prefill
                # chunks at the chunk EWMA + its tokens at the decode-
                # step EWMA. Shedding here costs nothing; admitting it
                # costs prefill the deadline then throws away.
                n_chunks = -(-T0 // self.prefill_chunk) \
                    if self._is_chunked(T0) else 1
                est = self._queue_wait_ewma \
                    + n_chunks * self._chunk_ewma \
                    + n_tokens * self._step_ewma
                if now + est > deadline:
                    self.slo_sheds += 1
                    err = DeadlineExceededError(
                        f"deadline unmeetable: needs ~{est:.3f}s "
                        f"(queue {self._queue_wait_ewma:.3f}s + "
                        f"{n_chunks} prefill chunks + {n_tokens} decode "
                        f"steps) but only "
                        f"{max(0.0, deadline - now):.3f}s remain; shed "
                        "before prefill")
                    self._shed_obs(trace, err,
                                   estimate_s=round(est, 4),
                                   queue_wait_ewma_s=round(
                                       self._queue_wait_ewma, 4),
                                   prefill_chunks=n_chunks,
                                   step_ewma_s=round(self._step_ewma, 5))
                    self.recorder.event(
                        "slo-shed", tenant=tenant,
                        estimate_s=round(est, 4),
                        queue_wait_ewma_s=round(self._queue_wait_ewma, 4),
                        prefill_chunks=n_chunks,
                        step_ewma_s=round(self._step_ewma, 5),
                        budget_s=round(max(0.0, deadline - now), 4))
                    raise err
            if len(self._queue) >= self.max_queue:
                self.shed_overload += 1
                retry = max(0.001, self._step_ewma
                            * (len(self._queue) / self.n_slots + 1))
                err = ServerOverloadedError(
                    f"generation queue full ({self.max_queue} pending); "
                    f"retry in {retry:.3f}s", retry_after=retry)
                self._shed_obs(trace, err, queue_depth=len(self._queue))
                raise err
            if self._pages_demand_queued \
                    and self._pages_demand_queued + need \
                    > self.max_queued_pages:
                # memory-side admission control: queued requests hold
                # no pages, but their aggregate DEMAND is bounded —
                # beyond `max_queued_pages` of page-wait-room, shed at
                # the door, typed, instead of queueing work the pool
                # cannot turn over soon. A LONE waiter always queues
                # (first clause): a request that fits the pool must
                # never be permanently shed by the aggregate cap, and
                # its retry_after would otherwise promise a retry that
                # could never succeed
                self.shed_out_of_pages += 1
                held = self.pool_pages - len(self._free_pages)
                n_live = sum(1 for r in self._slots if r is not None)
                retry = max(0.001, self._step_ewma
                            * (len(self._queue) + n_live + 1))
                err = OutOfPagesError(
                    f"KV page pool exhausted ({held}/{self.pool_pages} "
                    f"pages in use, {self._pages_demand_queued} queued "
                    f"demand of {self.max_queued_pages} allowed; {need} "
                    f"more needed); retry in {retry:.3f}s",
                    retry_after=retry)
                # the shed timeline AND the events ring both name the
                # page-demand decision — a flight_record dump after an
                # OutOfPages burst shows exactly which reservation the
                # door refused and what the pool looked like
                demand = self._pages_demand_queued
                self._shed_obs(trace, err, pages_needed=need,
                               pages_in_use=held,
                               queued_page_demand=demand,
                               max_queued_pages=self.max_queued_pages)
                self.recorder.event(
                    "shed", error="OutOfPagesError", pages_needed=need,
                    pages_in_use=held, queued_page_demand=demand,
                    max_queued_pages=self.max_queued_pages)
                raise err
            # debit the tenant's bucket only once EVERY door has passed:
            # a request shed by the shared queue/page limits above must
            # not also burn its tenant's budget
            if tstate is not None:
                if tstate.rate:
                    tstate.tokens -= n_tokens
                tstate.submitted += 1
            self._pages_demand_queued += need
            self.submitted += 1
            self._queue.append(req)
            trace.event("enqueue", queue_depth=len(self._queue),
                        pages_reserved=need,
                        prompt_len=int(T0), n_tokens=int(n_tokens))
            self._cond.notify_all()
        return req

    def _tenant_locked(self, tenant: Optional[str]):
        """This tenant's ledger (created on first sight, `default` quota
        applied), or None for untenanted traffic — which is untracked
        and unlimited, so pre-QoS callers see zero behavior change."""
        assert_owned(self._cond, "DecodeEngine._tenant_locked")
        if tenant is None:
            return None
        state = self._tenants.get(tenant)
        if state is None:
            spec = self._default_quota or {}
            state = _TenantState(rate=spec.get("rate"),
                                 burst=spec.get("burst"),
                                 max_pages=spec.get("max_pages"),
                                 weight=spec.get("weight"))
            self._tenants[tenant] = state
        return state

    def _tenant_pages_locked(self, tenant: str) -> int:
        """Pages currently reserved by `tenant`: queued demand plus
        every resident request's reservation."""
        assert_owned(self._cond, "DecodeEngine._tenant_pages_locked")
        return sum(r.n_pages for r in self._queue if r.tenant == tenant) \
            + sum(r.n_pages for r in self._slots
                  if r is not None and r.tenant == tenant)

    def _sweep_expired_locked(self, now: float) -> None:
        """Shed every already-expired QUEUED request with ITS truth
        (`DeadlineExceededError`), releasing its page reservation — so
        a queue padded with dead entries can never be the reason a live
        request hears `ServerOverloadedError`/`OutOfPagesError`."""
        assert_owned(self._cond, "DecodeEngine._sweep_expired_locked")
        if not any(r.expired(now) for r in self._queue):
            return
        keep: collections.deque = collections.deque()
        for req in self._queue:
            if req.expired(now):
                self._pages_demand_queued -= req.n_pages
                self._free_request_pages_locked(req)  # delta-pin release
                self.shed_deadline += 1
                req.trace.add_timed("queue-wait", req.enqueued_at, now,
                                    decision="expired")
                self._finish_obs(req, DeadlineExceededError(
                    "deadline expired while queued; request shed before "
                    "prefill"))
            else:
                keep.append(req)
        self._queue = keep

    def set_tenant_quota(self, tenant: str, rate: Optional[float] = None,
                         burst: Optional[float] = None,
                         max_pages: Optional[int] = None,
                         weight: Optional[float] = None) -> None:
        """Install (or with `rate=None` clear) tenant `tenant`'s
        token-rate quota — and with `max_pages` its KV page ceiling
        (`None` clears it), with `weight` its batch-lane fair-queueing
        share (`None` keeps the current weight; default 1.0) — at
        runtime; the seam the gateway's `set_tenant_quota` RPC lands
        on. The bucket restarts full at the new burst; counters survive
        the change."""
        if weight is not None and float(weight) <= 0:
            raise ValueError("tenant weight must be > 0")
        with self._cond:
            state = self._tenant_locked(tenant)
            state.rate = None if rate is None else float(rate)
            state.burst = float(burst) if burst is not None \
                else (state.rate if state.rate else 0.0)
            state.tokens = state.burst
            state.max_pages = None if max_pages is None else int(max_pages)
            if weight is not None:
                state.weight = float(weight)
            state.last_refill = time.monotonic()
        self.recorder.event("quota-set", tenant=tenant, rate=rate,
                            burst=burst, max_pages=max_pages,
                            weight=weight)

    # -- cluster-global prefix cache (prefix_directory) --------------------
    def bind_prefix_directory(self, directory, holder_id: str,
                              peers: Optional[Callable] = None, *,
                              fetch_timeout: float = 5.0,
                              frame_pages: int = 8,
                              min_fetch_pages: int = 1) -> "DecodeEngine":
        """Join a cluster-wide `PrefixDirectory`: this engine's prefix
        cache publishes its promoted chains under `holder_id` (and
        retracts on evict/clear), and — when `peers` is given — a
        local prefix miss with a directory hit FETCHES the chain's
        pages from the holder instead of re-prefilling them.
        `peers(holder_id)` resolves a holder name to an engine-shaped
        handle exposing `export_prefix` / `fetch_handoff_frame` /
        `commit_handoff` / `abort_handoff` (an in-process engine, a
        `ModelServer`, or a `RemoteReplica` — the deployment seam);
        returning None skips the fetch. Every wire failure degrades to
        cold prefill — the fetch path is never load-bearing.
        Chainable."""
        with self._cond:
            self._prefix_directory = directory
            self._holder_id = str(holder_id)
            self._prefix_peers = peers
            self._prefix_fetch_timeout = float(fetch_timeout)
            self._prefix_fetch_frame_pages = max(1, int(frame_pages))
            self._prefix_min_fetch_pages = max(1, int(min_fetch_pages))
            if self._prefix_cache is not None:
                self._prefix_cache.bind_directory(directory,
                                                  self._holder_id)
                chains = self._prefix_cache.chains()
                if chains:  # late bind: announce what is already warm
                    directory.publish(self._weight_version,
                                      self.page_size, chains,
                                      self._holder_id)
        return self

    def prefix_depth(self, prompt_ids,
                     tenant: Optional[str] = None) -> int:
        """Fully-covered resident prefix pages this engine holds for
        `prompt_ids` at its CURRENT weight version — the receiver-side
        answer a delta sender asks before choosing `skip_pages`."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        with self._cond:
            if self._prefix_cache is None:
                return 0
            return len(self._prefix_cache.match(prompt, tenant=tenant))

    def prefix_chains(self) -> dict:
        """Snapshot of every resident chain key at the current weight
        version — the pull-mode directory refresh for remote replicas
        whose promotions cannot ride a shared in-process directory."""
        with self._cond:
            chains = [] if self._prefix_cache is None \
                else self._prefix_cache.chains()
            return {"weight_version": self._weight_version,
                    "page_size": self.page_size, "chains": chains}

    def export_prefix(self, prompt_ids, have_pages: int = 0,
                      tenant: Optional[str] = None,
                      frame_pages: Optional[int] = None,
                      timeout: Optional[float] = None) -> dict:
        """Holder-side cluster-prefix export: serialize this engine's
        resident chain pages for `prompt_ids` (beyond the receiver's
        `have_pages`) into a leased `kind="prefix"` handoff and return
        its framed HEADER — the receiver then drains
        `fetch_handoff_frame` and commits. The device read runs on the
        scheduler thread via a parked work item (only that thread may
        touch the pools between dispatches under donation); this
        caller blocks up to `timeout`. Typed `KVTransferError` when
        the chain is no longer resident deeper than `have_pages` (the
        directory entry was stale)."""
        from deeplearning4j_tpu.serving.kv_transfer import KVTransferError

        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        item = {"prompt": prompt, "have": max(0, int(have_pages)),
                "tenant": tenant, "frame_pages": frame_pages,
                "done": threading.Event(), "result": None, "error": None}
        with self._cond:
            if self._closed:
                raise ServerClosedError("decode engine is shut down")
            self._prefix_exports.append(item)
            self._cond.notify_all()
        wait = self._prefix_fetch_timeout if timeout is None \
            else float(timeout)
        if not item["done"].wait(wait):
            raise KVTransferError(
                f"prefix export timed out after {wait:.1f}s (scheduler "
                "busy); fall back to cold prefill")
        if item["error"] is not None:
            raise item["error"]
        return item["result"]

    def fetch_handoff_header(self, handoff_id: str, skip_pages: int = 0,
                             frame_pages: Optional[int] = None) -> dict:
        """Framed-transfer entry for ANY leased handoff (migration or
        prefix export): the blockless header, advanced by `skip_pages`
        pages the receiver proved it holds (delta transfer). Extends
        the lease TTL. Typed `KVTransferError` on an unknown lease."""
        from deeplearning4j_tpu.serving import kv_transfer

        with self._cond:
            lease = self._leases.touch(handoff_id)
            if lease is None:
                raise kv_transfer.KVTransferError(
                    f"unknown or expired handoff lease {handoff_id!r}; "
                    "fall back to re-prefill from the prompt")
            return kv_transfer.payload_header(
                lease.payload, skip_pages=skip_pages,
                frame_pages=frame_pages)

    def fetch_handoff_frame(self, handoff_id: str, frame: int,
                            skip_pages: int = 0,
                            frame_pages: Optional[int] = None) -> dict:
        """One bounded frame of a leased handoff (host-side numpy
        slicing — safe on any RPC thread). Extends the lease TTL, so a
        receiver mid-drain cannot lose the race against the orphan
        sweep."""
        from deeplearning4j_tpu.serving import kv_transfer

        with self._cond:
            lease = self._leases.touch(handoff_id)
            if lease is None:
                raise kv_transfer.KVTransferError(
                    f"unknown or expired handoff lease {handoff_id!r}; "
                    "fall back to re-prefill from the prompt")
            return kv_transfer.slice_frame(
                lease.payload, frame, skip_pages=skip_pages,
                frame_pages=frame_pages)

    def _fetch_prefix_for(self, prompt: np.ndarray,
                          tenant: Optional[str]) -> Optional[dict]:
        """Submit-thread cluster-prefix fetch: on a local miss with a
        directory hit, pull the chain's missing pages from a holder
        and return a verified ``{"payload", "have", "depth",
        "source"}`` bundle for `_admit` to bind. Returns None — never
        raises — on any miss, skew, or wire failure: the request then
        cold-prefills exactly as it would today (the never-slower
        contract)."""
        from deeplearning4j_tpu.serving import kv_transfer

        t0 = int(prompt.shape[0])
        page = self.page_size
        cap = max(0, (t0 - 1) // page)
        if cap < self._prefix_min_fetch_pages:
            return None
        with self._cond:
            if self._prefix_cache is None:
                return None
            local = len(self._prefix_cache.match(prompt, tenant=tenant))
        if cap - local < self._prefix_min_fetch_pages:
            return None
        hit = self._prefix_directory.best_holder(
            prompt, tenant, exclude=(self._holder_id,))
        if hit is None or hit["weight_version"] != self._weight_version \
                or int(hit["page_size"]) != page:
            return None
        depth = min(int(hit["depth"]), cap)
        if depth - local < self._prefix_min_fetch_pages:
            return None
        holder = hit["holders"][0]
        # single-flight per chain: a same-prefix burst on a cold engine
        # must not become a thundering herd of identical wire fetches —
        # one admit pulls the pages, the rest wait (bounded by the
        # fetch timeout) and re-check the cache the winner filled
        sf_key = (hit["weight_version"], tenant,
                  prompt[:depth * page].tobytes())
        sf_deadline = time.monotonic() + self._prefix_fetch_timeout
        with self._cond:
            while sf_key in self._prefix_fetching:
                remaining = sf_deadline - time.monotonic()
                if remaining <= 0:
                    return None  # waited out: cold prefill, never slower
                self._cond.wait(remaining)
            if self._prefix_cache is None:
                return None
            local = len(self._prefix_cache.match(prompt, tenant=tenant))
            if depth - local < self._prefix_min_fetch_pages:
                return None  # the winner's bind covers us: warm admit
            ready = self._prefix_fetch_ready.get(sf_key)
            if ready is not None:
                bundle, expires = ready
                if time.monotonic() < expires:
                    # the winner's bundle is still queued toward the
                    # cache (binding happens at admission, on the
                    # scheduler thread) — share it instead of pulling
                    # the same pages over the wire again; every bind
                    # after the first is dropped by the stale-check
                    self.recorder.event("prefix-fetch",
                                        decision="reused", depth=depth)
                    return dict(bundle)
                del self._prefix_fetch_ready[sf_key]
            self._prefix_fetching.add(sf_key)
        bundle = None
        try:
            bundle = self._fetch_prefix_chain(
                prompt, tenant, hit, depth, local, holder)
            return bundle
        finally:
            with self._cond:
                if bundle is not None:
                    now = time.monotonic()
                    stale = [k for k, (_, exp)
                             in self._prefix_fetch_ready.items()
                             if exp <= now]
                    for k in stale:
                        del self._prefix_fetch_ready[k]
                    self._prefix_fetch_ready[sf_key] = (
                        bundle, now + self._prefix_fetch_timeout)
                self._prefix_fetching.discard(sf_key)
                self._cond.notify_all()

    def _fetch_prefix_chain(self, prompt, tenant, hit, depth, local,
                            holder) -> Optional[dict]:
        """The wire leg of `_fetch_prefix_for`, run under the chain's
        single-flight slot: export → frames → verify → commit."""
        from deeplearning4j_tpu.serving import kv_transfer

        page = self.page_size
        start = time.monotonic()
        header = None
        try:
            peer = self._prefix_peers(holder)
            if peer is None:
                return None
            header = peer.export_prefix(
                [int(x) for x in prompt[:depth * page]],
                have_pages=local, tenant=tenant,
                frame_pages=self._prefix_fetch_frame_pages,
                timeout=self._prefix_fetch_timeout)
            frames = [peer.fetch_handoff_frame(
                          header["handoff_id"], i, skip_pages=0,
                          frame_pages=header["frame_pages"])
                      for i in range(int(header["n_frames"]))]
            payload = kv_transfer.assemble_payload(header, frames)
            payload = kv_transfer.verify_payload(
                payload, weight_version=self._weight_version,
                kv_quant=self._kv_quant, page_size=page,
                n_blocks=len(self._caches), max_len=self.max_len,
                kinds=("prefix",))
        # graftlint: disable=typed-error  never-slower contract: ANY
        # fetch-path failure (wire fault, refusal, corruption) degrades
        # to cold prefill; the typed cause is recorded, not raised
        except BaseException as e:
            if header is not None:
                try:
                    peer.abort_handoff(header["handoff_id"])
                # graftlint: disable=typed-error  best-effort abort of
                # a lease on a peer that may already be dead — its TTL
                # sweep unpins regardless
                except BaseException:
                    pass
            with self._cond:
                self.prefix_fetch_fallbacks += 1
            self.recorder.event(
                "prefix-fetch", decision="fallback", holder=holder,
                depth=depth, have=local, error=type(e).__name__)
            logger.warning(
                "cluster prefix fetch from %s failed (%s: %s); cold "
                "prefill", holder, type(e).__name__, e)
            return None
        try:
            peer.commit_handoff(header["handoff_id"])
        # graftlint: disable=typed-error  commit is an optimization
        # (early unpin on the holder); its lease TTL unpins regardless
        except BaseException:
            logger.warning(
                "prefix fetch commit_handoff(%s) failed; the holder's "
                "lease sweep will unpin", header["handoff_id"])
        dt = time.monotonic() - start
        nbytes = kv_transfer.payload_nbytes(payload)
        with self._cond:
            self.prefix_fetches += 1
            self.prefix_fetch_bytes += nbytes
            self.prefix_fetch_seconds += dt
        omitted = int(payload.get("pages_omitted", 0))
        self.recorder.event(
            "prefix-fetch", decision="fetched", holder=holder,
            depth=depth, have=local,
            pages=int(payload["pages_shipped"]), skipped=omitted,
            bytes=nbytes, ms=round(1e3 * dt, 2))
        return {"payload": payload, "have": omitted, "depth": depth,
                "source": holder}

    # -- KV handoff public surface (kv_transfer) ---------------------------
    def migrate_slots(self, wait: Optional[float] = 5.0) -> int:
        """Export EVERY in-flight request (queued, mid-prefill,
        decoding) as a leased handoff: each waiter's `result()` raises
        the `SlotMigratedError` redirect and the pool/coordinator
        resumes it on a peer. Returns the number of requests marked.
        Blocks up to `wait` seconds for the scheduler's migration pass
        to drain the engine (pass `wait=None`/0 for fire-and-forget).
        Idempotent — an empty engine migrates nothing."""
        with self._cond:
            if self._closed:
                raise ServerClosedError("decode engine is shut down")
            n = len(self._queue) \
                + sum(1 for r in self._slots if r is not None)
            if n == 0:
                return 0
            self._migrate_all = True
            self._cond.notify_all()
            if wait:
                deadline = time.monotonic() + wait
                while self._migrate_all or self._queue \
                        or any(r is not None for r in self._slots):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(min(remaining, 0.05))
        return n

    def fetch_handoff(self, handoff_id: str) -> dict:
        """The leased payload for `handoff_id` (extends the lease TTL,
        so an actively-resuming receiver cannot lose the race against
        the orphan sweep). Typed `KVTransferError` for an unknown or
        already-expired lease."""
        from deeplearning4j_tpu.serving.kv_transfer import KVTransferError

        with self._cond:
            lease = self._leases.touch(handoff_id)
            if lease is None:
                raise KVTransferError(
                    f"unknown or expired handoff lease {handoff_id!r}; "
                    "fall back to re-prefill from the prompt")
            return lease.payload

    def commit_handoff(self, handoff_id: str) -> bool:
        """The receiver resumed successfully: release the lease and
        free the shipped pages on this side. Idempotent (False when the
        lease is already resolved or expired)."""
        with self._cond:
            lease = self._leases.resolve(handoff_id)
            if lease is None:
                return False
            self._release_lease_locked(lease)
            self.handoffs_committed += 1
            self._cond.notify_all()
        self.recorder.event("handoff-commit", handoff_id=handoff_id)
        return True

    def abort_handoff(self, handoff_id: str) -> bool:
        """The transfer failed downstream: reclaim the leased pages now
        instead of waiting out the TTL. Idempotent."""
        with self._cond:
            lease = self._leases.resolve(handoff_id)
            if lease is None:
                return False
            self._release_lease_locked(lease)
            self.handoffs_aborted += 1
            self._cond.notify_all()
        self.recorder.event("handoff-abort", handoff_id=handoff_id)
        return True

    def resume_submit(self, payload: dict,
                      timeout: Optional[float] = None, *,
                      on_token: Optional[Callable] = None) -> _GenRequest:
        """Admit a fetched handoff payload: validate it against this
        engine's weights/geometry (typed `KVTransferError` on ANY
        mismatch or corruption — nothing is touched), then enqueue a
        request whose shipped pages re-bind at admission (warm) or that
        re-prefills from the prompt (cold). The deadline is the
        SMALLER of the sender's remaining budget and `timeout`.
        `on_token` re-attaches a stream sink so a mid-stream migration
        keeps publishing under the sender's cursor."""
        from deeplearning4j_tpu.serving.kv_transfer import (
            KVTransferError,
            verify_payload,
        )

        if self._role == "prefill":
            raise KVTransferError(
                "prefill-role engine does not accept KV handoffs — "
                "route resumes to a decode-capable replica")
        payload = verify_payload(
            payload, weight_version=self._weight_version,
            kv_quant=self._kv_quant, page_size=self.page_size,
            n_blocks=len(self._caches), max_len=self.max_len)
        prompt = np.asarray(payload["prompt"], np.int32)
        n_tokens = int(payload["n_tokens"])
        rems = [t for t in (payload.get("deadline_remaining"), timeout)
                if t is not None]
        if not rems and self.default_timeout is not None:
            rems = [self.default_timeout]
        deadline = time.monotonic() + min(rems) if rems else None
        req = _GenRequest(prompt, n_tokens,
                          float(payload["temperature"]),
                          int(payload["seed"]), deadline,
                          tenant=payload.get("tenant"),
                          priority=payload.get("priority") or "interactive")
        req.trace = observability.maybe_trace()
        req.tokens = [int(t) for t in payload["tokens"]]
        req.resumed_at = int(payload["resumed_at"])
        req.preempted = int(payload["preempted"])
        req.logprobs = int(payload.get("logprobs", 0) or 0)
        if req.logprobs > self._logprobs_k:
            raise KVTransferError(
                f"handoff requests logprobs={req.logprobs} but the "
                f"receiving engine was built with logprobs="
                f"{self._logprobs_k}")
        req.logprob_values = list(payload.get("logprob_values") or [])
        req.sink = on_token
        omitted = 0
        if payload["kind"] == "cold":
            # fold emitted tokens into the prompt exactly like a
            # preemption resume: re-prefill reproduces the sequence
            if len(req.tokens) > req.resumed_at:
                req.prompt = np.concatenate(
                    [req.prompt, np.asarray(req.tokens[req.resumed_at:],
                                            np.int32)])
                req.resumed_at = len(req.tokens)
            t0 = req.prompt.shape[0]
            req.n_pages = self._pages_for(
                t0, max(1, n_tokens - req.resumed_at))
        else:
            req.import_state = payload
            omitted = int(payload.get("pages_omitted", 0))
            t0 = prompt.shape[0]
            span = t0 + max(1, n_tokens - req.resumed_at) - 1
            req.n_pages = max(-(-span // self.page_size),
                              omitted + int(payload["pages_shipped"]))
        if req.n_pages > self.pool_pages:
            raise KVTransferError(
                f"handoff needs {req.n_pages} KV pages but the "
                f"receiving pool holds only {self.pool_pages}")
        with self._cond:
            if self._closed:
                err = ServerClosedError("decode engine is shut down")
                self._shed_obs(req.trace, err)
                raise err
            now = time.monotonic()
            if deadline is not None and deadline <= now:
                self.shed_deadline += 1
                err = DeadlineExceededError(
                    "deadline expired before handoff admission")
                self._shed_obs(req.trace, err)
                raise err
            if len(self._queue) >= self.max_queue:
                self.shed_overload += 1
                retry = max(0.001, self._step_ewma
                            * (len(self._queue) / self.n_slots + 1))
                err = ServerOverloadedError(
                    f"generation queue full ({self.max_queue} pending); "
                    f"retry in {retry:.3f}s", retry_after=retry)
                self._shed_obs(req.trace, err)
                raise err
            tstate = self._tenant_locked(req.tenant)
            if tstate is not None:
                # no token-rate debit: the sender already charged this
                # request's tokens at original submission — migrating
                # must not bill a tenant twice. The page ceiling still
                # applies: resident pages are resident pages
                if tstate.max_pages is not None:
                    live = self._tenant_pages_locked(req.tenant)
                    if live + req.n_pages > tstate.max_pages:
                        tstate.shed_page_quota += 1
                        self.shed_page_quota += 1
                        err = TenantQuotaExceededError(
                            f"tenant {req.tenant!r} KV page quota "
                            f"exhausted ({live} of {tstate.max_pages} "
                            f"pages reserved; {req.n_pages} more needed)",
                            retry_after=max(0.001, self._step_ewma))
                        self._shed_obs(req.trace, err, tenant=req.tenant)
                        self.recorder.event(
                            "quota-shed", tenant=req.tenant, resource="pages",
                            pages_reserved=live,
                            max_pages=tstate.max_pages,
                            pages_needed=req.n_pages)
                        raise err
                tstate.submitted += 1
            if omitted:
                # delta handoff: the sender elided the first `omitted`
                # chain pages because this engine's directory entry
                # claimed them resident — pin them NOW (refcounted), so
                # eviction cannot race the bind; refused typed when the
                # chain is no longer deep enough (the sender's ladder
                # re-sends without skip_pages)
                have = [] if self._prefix_cache is None else \
                    self._prefix_cache.match(prompt, tenant=req.tenant)
                if len(have) < omitted:
                    err = KVTransferError(
                        f"delta handoff omits {omitted} prefix pages "
                        f"but only {len(have)} are resident here; "
                        "re-send without skip_pages")
                    self._shed_obs(req.trace, err, tenant=req.tenant)
                    raise err
                have = have[:omitted]
                self._prefix_cache.acquire(have)
                req.nodes = have
                req.n_shared = omitted
            self.submitted += 1
            self._pages_demand_queued += req.n_pages
            self._queue.append(req)
            req.trace.event("resume-enqueue", kind=payload["kind"],
                            handoff_id=payload["handoff_id"],
                            pages_shipped=int(payload["pages_shipped"]),
                            emitted=len(req.tokens))
            self._cond.notify_all()
        return req

    def resume_generate(self, payload: dict,
                        timeout: Optional[float] = None, *,
                        on_token: Optional[Callable] = None):
        """Blocking `resume_submit`: returns only the TAIL tokens this
        engine generates — the caller splices them after the redirect's
        already-emitted `tokens`. When the handoff carries logprobs, a
        dict `{"tokens", "logprobs"}` holding only the tail's share."""
        req = self.resume_submit(payload, timeout=timeout,
                                 on_token=on_token)
        already = len(req.tokens)
        already_lp = len(req.logprob_values)
        out = req.result()
        if req.logprobs:
            return {"tokens": out[already:],
                    "logprobs": list(req.logprob_values[already_lp:])}
        return out[already:]

    def generate(self, prompt_ids, n_tokens: int, *,
                 temperature: float = 0.0, seed: int = 0,
                 timeout: Optional[float] = None,
                 tenant: Optional[str] = None,
                 priority: str = "interactive",
                 logprobs: int = 0,
                 on_token: Optional[Callable] = None):
        """Blocking convenience: submit + wait. Returns the generated
        tokens (1-D int32; shorter than `n_tokens` only on EOS) — or,
        with `logprobs=K > 0`, a dict `{"tokens", "logprobs"}` where
        `logprobs` carries one per-step entry (chosen-token logprob +
        top-K) per generated token."""
        req = self.submit(prompt_ids, n_tokens, temperature=temperature,
                          seed=seed, timeout=timeout, tenant=tenant,
                          priority=priority, logprobs=logprobs,
                          on_token=on_token)
        out = req.result()
        if logprobs:
            return {"tokens": out, "logprobs": list(req.logprob_values)}
        return out

    def pending(self) -> int:
        """Queued + in-slot generation requests — the engine's share of
        the load number least-loaded routing compares (folded into
        `ModelServer.pending()`)."""
        with self._cond:
            return len(self._queue) \
                + sum(1 for r in self._slots if r is not None)

    def stats(self) -> dict:
        with self._cond:
            queued = len(self._queue)
            active = sum(1 for r in self._slots if r is not None)
            held = self.pool_pages - len(self._free_pages)
            demand = self._pages_demand_queued
            used_positions = 0
            for r in self._slots:
                if r is None:
                    continue
                t0 = r.prompt.shape[0]
                used_positions += min(r.prefill_pos, t0) \
                    if r.prefill_pos is not None \
                    else t0 + len(r.tokens) - r.resumed_at
            tenants = {name: state.counters()
                       for name, state in sorted(self._tenants.items())}
            for name, counters in tenants.items():
                counters["pages_reserved"] = self._tenant_pages_locked(name)
            leases = len(self._leases)
            unfetched = self._leases.unfetched()
        occupancy = (100.0 * self.active_slot_steps
                     / (self.decode_steps * self.n_slots)
                     if self.decode_steps else 0.0)
        # internal fragmentation of pages actually held by slots: the
        # tail of each request's last page (and not-yet-filled growth
        # room) is allocated-but-unused
        frag = (100.0 * (1.0 - used_positions
                         / (held * self.page_size))
                if held else 0.0)
        out = {"submitted": self.submitted, "served": self.served,
               "shed_overload": self.shed_overload,
               "shed_out_of_pages": self.shed_out_of_pages,
               "shed_deadline": self.shed_deadline,
               "shed_unavailable": self.shed_unavailable,
               "failures": self.failures, "prefills": self.prefills,
               "prefill_chunks": self.prefill_chunks,
               "decode_steps": self.decode_steps,
               "tokens_generated": self.tokens_generated,
               "slot_occupancy_pct": round(occupancy, 1),
               "n_slots": self.n_slots, "active_slots": active,
               "queued": queued, "swaps": self.swaps,
               "max_len": self.max_len,
               "page_size": self.page_size,
               "pool_pages": self.pool_pages,
               "pages_in_use": held,
               "pages_in_use_peak": self.pages_in_use_peak,
               "queued_page_demand": demand,
               "max_queued_pages": self.max_queued_pages,
               "page_fragmentation_pct": round(frag, 1),
               "prefill_chunk": self.prefill_chunk,
               # quantized-KV tier: numeric (not string) so the keys
               # survive `_flatten_numeric` into Prometheus exposition;
               # bits reflect the BUILT pools (kill switch included)
               "kv_quant_bits": self._kv_quant_bits,
               "kv_bytes_per_token": self._kv_bytes_per_token,
               # tensor-parallel tier: degree 1 when off, so dashboards
               # can chart capacity without branching on key presence;
               # per-shard KV bytes is the per-chip residency claim
               "tp_degree": self._tp_degree,
               "tp_kv_bytes_per_token_per_shard":
                   self._kv_bytes_per_token // self._tp_degree,
               # QoS control plane: unconditional (zero / empty when
               # qos is off) so dashboards and the stats-schema
               # contract never branch on key presence
               "preemptions": self.preemptions,
               "slo_sheds": self.slo_sheds,
               "shed_quota": self.shed_quota,
               "shed_page_quota": self.shed_page_quota,
               "tenants": tenants,
               # KV handoff plane: slots exported under lease /
               # imported, lease resolutions, live leases, wire bytes
               "migrations_out": self.migrations_out,
               "migrations_in": self.migrations_in,
               "handoffs_committed": self.handoffs_committed,
               "handoffs_aborted": self.handoffs_aborted,
               "handoffs_expired": self.handoffs_expired,
               "handoff_leases": leases,
               "handoffs_unfetched": unfetched,
               "kv_transfer_bytes": self.kv_transfer_bytes,
               # cluster prefix plane: unconditional (all zero while no
               # directory is bound) so the stats-schema contract and
               # dashboards never branch on key presence
               "prefix_fetches": self.prefix_fetches,
               "prefix_fetch_fallbacks": self.prefix_fetch_fallbacks,
               "prefix_fetch_bytes": self.prefix_fetch_bytes,
               "prefix_fetch_ms": round(
                   1e3 * self.prefix_fetch_seconds, 2),
               "prefix_exports": self.prefix_exports_served,
               "cluster_prefix_hit_tokens":
                   self.cluster_prefix_hit_tokens,
               "cluster_prefix_hit_tokens_pct": round(
                   100.0 * self.cluster_prefix_hit_tokens
                   / self.prompt_tokens, 1) if self.prompt_tokens
                   else 0.0,
               "prompt_buckets": list(self.prompt_buckets)}
        if self._prefix_cache is not None:
            hit_pct = (100.0 * self.prefix_hit_tokens / self.prompt_tokens
                       if self.prompt_tokens else 0.0)
            out["prefix_hit_tokens_pct"] = round(hit_pct, 1)
            out["prefix_cache"] = dict(
                self._prefix_cache.stats(),
                hits=self.prefix_hits, misses=self.prefix_misses,
                hit_tokens=self.prefix_hit_tokens,
                prompt_tokens=self.prompt_tokens)
        if self._spec is not None:
            rate = (100.0 * self.spec_accepted / self.spec_proposed
                    if self.spec_proposed else 0.0)
            per_step = (self.spec_emitted / self.spec_steps
                        if self.spec_steps else 0.0)
            out["spec_accept_rate"] = round(rate, 1)
            out["spec_tokens_per_step"] = round(per_step, 3)
            out["speculative"] = dict(
                self._spec.stats(), verify_steps=self.spec_steps,
                proposed=self.spec_proposed, accepted=self.spec_accepted,
                emitted=self.spec_emitted)
        return out

    def model_bytes_per_chip(self) -> int:
        """Per-chip residency (weights + KV pools + scale sidecars), the
        bench's `tp_max_model_bytes_per_chip` capacity claim: under
        parallel={"tp": N} the sharded matmul slices and the pools' head
        axis each divide by N (replicated tensors — embeddings, LNs,
        biases, logits head — don't), so the largest servable model
        grows ~N× per chip. Array `.nbytes` is the GLOBAL size, hence
        the explicit division."""
        import jax

        pool_bytes = sum(x.nbytes
                         for c in self._caches
                         for x in c) // self._tp_degree
        if self._tp is not None:
            return self._tp.weight_bytes_per_chip(self._net._params) \
                + pool_bytes
        weight_bytes = sum(
            x.nbytes
            for p in self._net._params
            for x in jax.tree_util.tree_leaves(p))
        return weight_bytes + pool_bytes

    def drain_and_swap(self, net, timeout: Optional[float] = None) -> None:
        """Hot-reload seam: pause admission, let every in-flight request
        FINISH on the current weights (KV caches were computed with
        them — mixing would corrupt numerics), swap to `net` (recompiling
        lazily), then resume admission. Queued requests survive the swap
        and decode on the new weights. Raises the swap-build error (e.g.
        `net` is not a gpt network) with the old weights still serving."""
        with self._cond:
            if self._closed:
                raise ServerClosedError("decode engine is shut down")
            self._swap_net = net
            self._swap_error = None
            self._swap_done.clear()
            self._draining = True
            self._cond.notify_all()
        self.recorder.event("drain", reason="weight-swap")
        if not self._swap_done.wait(timeout):
            with self._cond:
                # race guard: the scheduler may already be PAST the
                # _swap_net check and mid-build — abandoning then would
                # report "old weights serving" while the new ones land.
                # Only abandon a swap the scheduler has not picked up
                abandon = not self._swap_in_progress \
                    and not self._swap_done.is_set()
                if abandon:  # resume serving the old weights
                    self._swap_net = None
                    self._draining = False
                    self._cond.notify_all()
            if abandon:
                raise ServingError(
                    f"decode engine drain did not complete within "
                    f"{timeout}s (long in-flight generations); old "
                    "weights still serving")
            self._swap_done.wait()  # build already running: finish it out
        err = self._swap_error
        if err is not None:
            raise err

    def shutdown(self, drain_timeout: float = 10.0) -> bool:
        """Stop admission (typed `ServerClosedError` for queued + new
        requests), let in-flight generations finish for up to
        `drain_timeout` seconds, then fail the rest. Returns True on a
        clean drain. Idempotent."""
        deadline = time.monotonic() + drain_timeout
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        drained = True
        with self._cond:
            while any(r is not None for r in self._slots):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    drained = False
                    self._kill = True
                    self._cond.notify_all()
                    break
                self._cond.wait(min(remaining, 0.05))
        self._thread.join(max(0.0, deadline - time.monotonic()) + 5.0)
        if not drained:
            logger.warning("decode engine: shutdown drain timed out with "
                           "generations still in flight")
        return drained

    # -- scheduler ---------------------------------------------------------
    def _hook(self, phase: str, info: dict) -> None:
        for hook in self.step_hooks:
            hook(phase, info)

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._closed and not self._kill \
                        and not self._work_pending():
                    self._cond.wait(0.05)
                if self._kill:
                    self._fail_all_locked(ServerClosedError(
                        "engine shut down before this request finished"))
                    self._abort_pending_swap_locked()
                    return
                if self._closed:
                    while self._queue:
                        req = self._queue.popleft()
                        self._pages_demand_queued -= req.n_pages
                        self._free_request_pages_locked(req)
                        self._finish_obs(req, ServerClosedError(
                            "engine shut down before this request "
                            "could be served"))
                    self._drain_prefix_exports_locked(ServerClosedError(
                        "decode engine is shut down"))
                    if not any(r is not None for r in self._slots):
                        self._abort_pending_swap_locked()
                        self._cond.notify_all()
                        return
            try:
                if not self._draining and not self._closed:
                    self._admit()
                self._expire_in_flight()
                self._step_migrations()
                self._serve_prefix_exports()
                self._sweep_leases()
                self._step_prefills()
                self._step_active()
                self._maybe_swap()
            # graftlint: disable=typed-error  scheduler firewall: the
            # iteration's failure is converted to InferenceFailedError and
            # fails all in-flight requests; the loop itself must survive
            except BaseException:  # scheduler must never die silently
                logger.exception("decode engine: scheduler iteration "
                                 "failed; failing in-flight requests")
                with self._cond:
                    self._fail_all_locked(InferenceFailedError(
                        "decode engine scheduler failure"))
                self._reset_device_state()

    def _abort_pending_swap_locked(self) -> None:
        """A scheduler exit (shutdown/kill) with a drain pending must
        release the `drain_and_swap` caller — a reload blocked forever
        on a dead scheduler would also pin the ModelServer reload lock."""
        assert_owned(self._cond, "DecodeEngine._abort_pending_swap_locked")
        if self._draining or self._swap_net is not None:
            self._swap_net = None
            self._draining = False
            self._swap_error = ServerClosedError(
                "engine shut down while draining for a weight swap")
            self._swap_done.set()

    def _work_pending(self) -> bool:
        if any(r is not None for r in self._slots):
            return True
        if self._draining:
            return True  # reach _maybe_swap even with empty slots
        if self._migrate_all or self._leases.expired_pending():
            return True  # reach the migration pass / lease sweep
        if self._prefix_exports:
            return True  # a peer is waiting on a prefix export
        return bool(self._queue) and not self._draining

    def _fail_all_locked(self, err: BaseException) -> None:
        assert_owned(self._cond, "DecodeEngine._fail_all_locked")
        self._drain_prefix_exports_locked(err)
        while self._queue:
            req = self._queue.popleft()
            self._pages_demand_queued -= req.n_pages
            self._free_request_pages_locked(req)
            self._finish_obs(req, err)  # never acquired the breaker
        for s, req in enumerate(self._slots):
            if req is not None:
                self._slots[s] = None
                self._active[s] = False
                self._free_request_pages_locked(req)
                if self.breaker is not None:
                    # release the request's breaker token — a dropped
                    # half-open probe would wedge the shared breaker in
                    # half_open and reject ALL traffic until a reload
                    self.breaker.record_failure(req.probe)
                self._finish_obs(req, err)
        self._cond.notify_all()

    def _select_head_locked(self) -> int:
        """Index of the next request to admit: the FIRST queued
        interactive request when one exists (an interactive request
        jumps a page-blocked batch head, so the batch lane only
        consumes capacity interactive traffic is not asking for; under
        sustained interactive saturation the batch lane starves by
        design, its deadline sweep still failing batch requests typed).
        The batch lane itself is weighted-fair, not FIFO: the queued
        batch request whose tenant holds the LOWEST stride-scheduling
        pass value wins, so two equal-weight tenants split admitted
        work ~50/50 under saturation instead of one backlog serializing
        in front of the other — and a weight-2 tenant gets twice the
        admitted span of a weight-1 peer. FIFO within one tenant
        (earliest queued wins the tie on equal pass values);
        untenanted batch traffic rides one shared implicit ledger."""
        assert_owned(self._cond, "DecodeEngine._select_head_locked")
        best = 0
        best_pass = None
        for i, r in enumerate(self._queue):
            if r.priority == "interactive":
                return i
            p = self._wfq_pass.get(r.tenant, self._wfq_floor)
            if best_pass is None or p < best_pass:
                best, best_pass = i, p
        return best

    def _wfq_charge_locked(self, req: "_GenRequest") -> None:
        """Advance the admitted batch request's tenant pass: virtual
        start = max(own pass, floor) — an idle tenant rejoins AT the
        floor, never banking credit — charged by the request's logical
        decode span over the tenant's weight. The floor then advances
        to the winner's pre-charge pass, keeping every ledger within
        one span of each other (bounded unfairness, O(1) state)."""
        assert_owned(self._cond, "DecodeEngine._wfq_charge_locked")
        state = self._tenant_locked(req.tenant)
        weight = state.weight if state is not None else 1.0
        start = max(self._wfq_pass.get(req.tenant, self._wfq_floor),
                    self._wfq_floor)
        span = float(max(1, int(req.n_tokens)))
        self._wfq_pass[req.tenant] = start + span / max(weight, 1e-9)
        self._wfq_floor = start

    def _maybe_preempt_locked(self, head: _GenRequest, reason: str):
        """Retire-to-queue one DECODING batch-lane slot so a blocked
        interactive head can take its slot and pages. The victim's
        emitted tokens fold into its prompt (`resumed_at` marks the
        fold point, keeping the logical span constant), its prompt's
        fully-covered pages are promoted into the prefix cache so the
        re-prefill re-binds them instead of recomputing, and it rejoins
        the queue FRONT with its position preserved. Mid-prefill slots
        are never preempted: their pages hold partial KV, which must
        not reach the prefix cache. Returns ``(victim, old_probe,
        reason, slot)`` or None (caller releases the breaker token
        outside the lock)."""
        assert_owned(self._cond, "DecodeEngine._maybe_preempt_locked")
        if not self._preempt_enabled or head.priority != "interactive" \
                or head.expired():
            return None
        best = None
        for s in range(self.n_slots):
            v = self._slots[s]
            if v is None or v.priority != "batch":
                continue
            if v.prefill_pos is not None or not self._active[s]:
                continue  # mid-prefill KV is partial: not promotable
            if v.n_tokens - len(v.tokens) < 1:
                continue  # retiring on its own this iteration
            if best is None or \
                    len(v.tokens) < len(self._slots[best].tokens):
                best = s  # least progress = least re-prefill to redo
        if best is None:
            return None
        v = self._slots[best]
        old_probe = v.probe
        # promote only the CURRENT prompt's fully-covered pages: the
        # latest decoded token's KV is not written yet, so pages
        # touching the decoded tail are not provably complete
        self._promote_prefix_locked(v)
        self._free_request_pages_locked(v)
        self._slots[best] = None
        self._active[best] = False
        emitted = len(v.tokens)
        if emitted > v.resumed_at:
            v.prompt = np.concatenate(
                [v.prompt, np.asarray(v.tokens[v.resumed_at:], np.int32)])
        v.resumed_at = emitted
        v.prefill_pos = None
        v.slot = None
        v.hit_len = 0
        v.n_shared = 0
        v.nodes = None
        v.digests = []
        v.probe = False
        v.preempted += 1
        v.n_pages = self._pages_for(v.prompt.shape[0],
                                    max(1, v.n_tokens - emitted))
        self._pages_demand_queued += v.n_pages
        # queue FRONT: the victim was admitted before anything queued,
        # so it keeps seniority within the batch lane (interactive
        # selection still jumps it)
        self._queue.appendleft(v)
        self.preemptions += 1
        ts = self._tenants.get(v.tenant)
        if ts is not None:
            ts.preemptions += 1
        self.recorder.event(
            "preempt", slot=best, reason=reason, tenant=v.tenant,
            victim_emitted=emitted, victim_remaining=v.n_tokens - emitted,
            head_tenant=head.tenant, free_pages=len(self._free_pages),
            head_need_pages=head.n_pages)
        self._cond.notify_all()
        return (v, old_probe, reason, best)

    # graftlint: hot-loop
    def _admit(self) -> None:
        """Move queued requests into free slots. Expired queued requests
        are shed BEFORE any device work. Head selection is
        priority-aware: the first queued INTERACTIVE request goes
        first (FIFO within a class), and when it is slot- or
        page-blocked a decoding batch-lane slot is preempted
        (retire-to-queue) to make room. The selected head otherwise
        waits when the free list cannot cover its pages — a retirement
        frees them in bounded time, and unreferenced prefix-cache pages
        are reclaimed LRU-first before waiting (caching never shrinks
        effective capacity). With a prefix hit, the longest cached
        chain binds into the slot's page table (refcounts bumped), only
        the uncached tail allocates fresh pages, and prefill starts at
        the first uncached page boundary. A short cold prompt prefills
        one-shot immediately; a long or prefix-hit one is parked
        mid-prefill and chunk-prefilled by `_step_prefills` interleaved
        with decode."""
        import jax.numpy as jnp

        while True:
            preempt = None
            with self._cond:
                if not self._queue:
                    return
                free = [s for s in range(self.n_slots)
                        if self._slots[s] is None]
                head_idx = self._select_head_locked()
                head = self._queue[head_idx]
                nodes: list = []
                pim = None
                pre_pinned = False
                need = head.n_pages
                if not free:
                    # every slot taken, an interactive head waiting: the
                    # batch lane yields a slot (retire-to-queue) or we
                    # wait for a retirement like any full house
                    preempt = self._maybe_preempt_locked(head, "slots")
                    if preempt is None:
                        return
                elif not head.expired():
                    if head.import_state is not None and head.nodes:
                        # delta handoff: its prefix-chain pages were
                        # pinned at resume_submit — they bind as shared
                        # pages, only the shipped tail allocates fresh
                        nodes = head.nodes
                        pre_pinned = True
                        need = head.n_pages - len(nodes)
                    elif self._prefix_cache is not None \
                            and head.import_state is None:
                        # only the scheduler thread mutates the cache,
                        # so this lookup stays valid through the bind;
                        # a page-blocked head retries every iteration —
                        # its chunk digests are memoized on the request
                        nodes = self._prefix_cache.lookup(
                            head.prompt, head.digests,
                            tenant=head.tenant)
                        pim = head.prefix_import
                        if pim is not None:
                            pay = pim["payload"]
                            if pay["weight_version"] \
                                    != self._weight_version \
                                    or int(pay["page_size"]) \
                                    != self.page_size \
                                    or not (int(pim["have"])
                                            <= len(nodes)
                                            < int(pim["depth"])):
                                # the fetched bundle went stale between
                                # submit and admission (weight swap,
                                # seed-chain eviction, or the local
                                # cache caught up) — drop it; prefill
                                # covers the request regardless
                                head.prefix_import = pim = None
                                self.recorder.event(
                                    "prefix-fetch", decision="dropped",
                                    have=len(nodes))
                        if nodes or pim is not None:
                            # resumed (preempted) requests span only
                            # their REMAINING tokens past the extended
                            # prompt
                            need = self._pages_for_hit(
                                head.prompt.shape[0],
                                max(1, head.n_tokens - head.resumed_at)) \
                                - len(nodes)
                    if need > len(self._free_pages) \
                            and self._prefix_cache is not None:
                        # pool pressure: release idle cached pages
                        # (LRU, leaf-first) — the head's own hit chain
                        # is pinned so reclaim cannot eat it
                        self._prefix_cache.acquire(nodes)
                        try:
                            reclaimed = self._prefix_cache.reclaim(
                                need - len(self._free_pages))
                        finally:
                            self._prefix_cache.release(nodes)
                        self._free_pages.extend(reclaimed)
                        if reclaimed:
                            self.recorder.event(
                                "page-reclaim", pages=len(reclaimed),
                                free_after=len(self._free_pages))
                    if need > len(self._free_pages):
                        # page-blocked: a batch slot's pages can cover
                        # an interactive head (preemption), else wait
                        # for a retirement to free pages
                        preempt = self._maybe_preempt_locked(head,
                                                             "pages")
                        if preempt is None:
                            return
                if preempt is None:
                    req = head
                    del self._queue[head_idx]
                    self._pages_demand_queued -= req.n_pages
                    if req.priority != "interactive":
                        # charge the batch lane's fair-queueing ledger
                        # at the admission that actually consumed
                        # capacity (preempted re-admissions re-charge:
                        # they consume capacity again)
                        self._wfq_charge_locked(req)
            if preempt is not None:
                victim, old_probe, reason, vslot = preempt
                if self.breaker is not None:
                    # the victim's device work so far was healthy —
                    # preemption is a scheduling decision, not sickness
                    self.breaker.record_success(old_probe)
                victim.trace.event("preempt", reason=reason, slot=vslot,
                                   emitted=len(victim.tokens))
                continue
            now = time.monotonic()
            if req.expired(now):
                with self._cond:
                    self.shed_deadline += 1
                req.trace.add_timed("queue-wait", req.enqueued_at, now,
                                    decision="expired")
                self._finish_obs(req, DeadlineExceededError(
                    "deadline expired while queued; request shed before "
                    "prefill"))
                continue
            req.trace.add_timed("queue-wait", req.enqueued_at, now)
            with self._cond:
                # ground the SLO estimator's queue-wait term on every
                # admission (preempted re-admissions fold in too: their
                # requeue wait is real interactive-pressure wait)
                self._queue_wait_ewma = 0.8 * self._queue_wait_ewma \
                    + 0.2 * (now - req.enqueued_at)
            probe = False
            if self.breaker is not None:
                try:
                    probe = self.breaker.acquire()
                except ServiceUnavailableError as e:
                    with self._cond:
                        self.shed_unavailable += 1
                    self._finish_obs(req, e)
                    continue
            req.probe = probe
            slot = free[0]
            with self._cond:
                if pre_pinned:
                    # acquired at resume_submit — only account here
                    req.n_shared = len(nodes)
                elif nodes:
                    self._prefix_cache.acquire(nodes)
                    req.nodes = nodes
                    req.n_shared = len(nodes)
                    req.hit_len = len(nodes) * self.page_size
                    self.prefix_hits += 1
                    self.prefix_hit_tokens += req.hit_len
                elif self._prefix_cache is not None:
                    self.prefix_misses += 1
                self.prompt_tokens += int(req.prompt.shape[0])
                req.pages = [n.page_id for n in nodes] + \
                    [self._free_pages.pop() for _ in range(need)]
                held = self.pool_pages - len(self._free_pages)
                self.pages_in_use_peak = max(self.pages_in_use_peak, held)
            if nodes:
                req.trace.event("prefix-bind", shared_pages=req.n_shared,
                                hit_tokens=req.hit_len)
            req.trace.event("admission", slot=slot, pages=len(req.pages),
                            shared_pages=req.n_shared,
                            pages_in_use=held)
            self.recorder.event("admit", slot=slot, pages=len(req.pages),
                                hit_tokens=req.hit_len,
                                pages_in_use=held, tenant=req.tenant,
                                priority=req.priority)
            row = np.zeros((self._n_pages_max,), np.int32)
            row[:len(req.pages)] = req.pages
            self._page_table = self._page_table.at[slot].set(
                jnp.asarray(row))
            if req.prefix_import is not None:
                # fetched cluster-prefix pages scatter into the freshly
                # allocated tail pages and promote into the local cache
                # as if prefilled here; ANY failure falls back to
                # prefilling from the local hit (or cold)
                self._bind_prefix_import(req)
            if req.import_state is not None:
                # shipped KV re-binds directly into the slot: no
                # prefill — the pages already hold the sender's state
                try:
                    self._import_into(slot, req)
                # graftlint: disable=typed-error  converts to a typed
                # failure: _import_failure maps the cause to
                # KVTransferError and fails only the one request
                except BaseException as e:
                    self._import_failure(slot, req, e)
                continue
            t0 = req.prompt.shape[0]
            if req.hit_len or pim is not None or self._is_chunked(t0):
                # `pim is not None` forces the chunk path even when the
                # bind failed with no local hit: the hit-style page
                # allocation cannot cover a one-shot prefill's padded
                # bucket width
                with self._cond:
                    # hit requests always ride the chunk path: suffix
                    # prefill starts at the first uncached page
                    # boundary and attends over the shared pages
                    # through the slot's page row
                    req.prefill_pos = req.hit_len
                    req.slot = slot
                    self._slots[slot] = req
                    # _active stays False until the final chunk lands
                continue
            try:
                self._prefill_into(slot, req)
            # graftlint: disable=typed-error  converts to a typed failure:
            # _prefill_failure wraps non-ServingError causes in
            # InferenceFailedError and fails only the one request
            except BaseException as e:
                self._prefill_failure(slot, req, e, attached=False)

    # graftlint: hot-loop
    def _prefill_into(self, slot: int, req: _GenRequest) -> None:
        import jax
        import jax.numpy as jnp

        page = self.page_size
        t0 = req.prompt.shape[0]
        bucket = self._bucket_for(t0)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :t0] = req.prompt
        n_w = -(-bucket // page)
        wpids = jnp.asarray(np.asarray(req.pages[:n_w], np.int32))
        key = jax.random.PRNGKey(req.seed)
        kp, kdec = jax.random.split(key)  # generate()'s prefill/decode split
        info = {"slot": slot, "bucket": bucket, "t0": t0}
        self._hook("pre_prefill", info)

        def run():
            args = (self._dparams, self._caches, jnp.asarray(ids),
                    jnp.asarray(t0, jnp.int32),
                    jnp.asarray(slot, jnp.int32),
                    wpids, self._tok, self._pos, self._keys, self._temps,
                    kp, kdec, jnp.asarray(req.temperature, jnp.float32))
            if self._logprobs_k:
                (self._caches, self._tok, self._pos, self._keys,
                 self._temps, tok0, ok, lp0) = self._prefill(*args)
            else:
                (self._caches, self._tok, self._pos, self._keys,
                 self._temps, tok0, ok) = self._prefill(*args)
                lp0 = None
            return jax.device_get((tok0, ok, lp0))

        tp0 = time.monotonic()
        first, ok, lp0 = _dispatched(run, span=self._tp_span)
        tp1 = time.monotonic()
        # host clock around the dispatch+materialization — already
        # synced, so the span costs no extra device round-trip
        req.trace.add_timed("prefill", tp0, tp1,
                            bucket=bucket, prompt_len=t0)
        first = int(first[0])
        if not bool(ok):
            raise InferenceFailedError(
                "model produced non-finite logits during prefill "
                "(poisoned parameters or a numerically broken graph)")
        if self._spec is not None:
            # mirror the prompt into the draft's pools (same pages, same
            # padded ids) so proposing can start from a complete context
            _dispatched(lambda: self._spec.prefill_one_shot(ids, wpids))
        self._hook("post_prefill", info)
        with self._cond:
            self.prefills += 1
            self.tokens_generated += 1
            # a one-shot prefill grounds the SLO estimator as a single
            # chunk observation (same dispatch scale as a chunk)
            self._chunk_ewma = 0.8 * self._chunk_ewma + 0.2 * (tp1 - tp0)
            self._promote_prefix_locked(req)
        if self._spec is not None:
            self._spec.seed_slot(slot, req.seed)
        req.tokens.append(first)
        self._emit_token(req, lp0)
        # >= len comparison, not n_tokens == 1: a preempted request
        # re-prefills with its emitted tokens folded into the prompt,
        # so this "first" token may already be its last
        if len(req.tokens) >= req.n_tokens or first == self.eos_token:
            self._retire(slot, req, attached=False)
            return
        if req.handoff:
            # prefill-role (disagg): the freshly computed KV leaves
            # under a lease instead of entering this engine's decode loop
            self._export_slot(slot, req, attached=False, reason="disagg")
            return
        with self._cond:
            req.slot = slot
            self._slots[slot] = req
            self._active[slot] = True

    # graftlint: hot-loop
    def _step_prefills(self) -> None:
        """Drive pending chunked prefills, at most
        `prefill_chunk_budget` chunk dispatches per scheduler
        iteration — the interleaving that keeps a long prompt from
        head-of-line-blocking in-flight decodes."""
        budget = self.prefill_chunk_budget
        for s in range(self.n_slots):
            if budget <= 0:
                return
            req = self._slots[s]
            if req is None or req.prefill_pos is None:
                continue
            self._prefill_chunk_into(s, req)
            budget -= 1

    # graftlint: hot-loop
    def _prefill_chunk_into(self, slot: int, req: _GenRequest) -> None:
        import jax
        import jax.numpy as jnp

        C, page = self.prefill_chunk, self.page_size
        off = req.prefill_pos
        t0 = req.prompt.shape[0]
        rem = t0 - off
        final = rem <= C
        if not final:
            W = C
        elif C < page:
            W = C  # C divides page: the padded tail never straddles
        else:
            # final chunk padded only to the next PAGE multiple (<= C):
            # a prefix-hit suffix must never write past
            # page*ceil(t0/page), which its reservation covers
            W = -(-rem // page) * page
        ids = np.zeros((1, W), np.int32)
        take = min(W, rem)
        ids[0, :take] = req.prompt[off:off + take]
        if W >= page:
            pids = req.pages[off // page: off // page + W // page]
            woff = 0
        else:
            pids = [req.pages[off // page]]
            woff = off % page
        key = jax.random.PRNGKey(req.seed)
        kp, kdec = jax.random.split(key)
        info = {"slot": slot, "t0": t0, "chunk": W, "chunk_off": off,
                "final": final}
        self._hook("pre_prefill", info)

        def run():
            args = (self._dparams, self._caches, self._page_table[slot],
                    jnp.asarray(ids), jnp.asarray(off, jnp.int32),
                    jnp.asarray(woff, jnp.int32),
                    jnp.asarray(t0, jnp.int32),
                    jnp.asarray(slot, jnp.int32),
                    jnp.asarray(np.asarray(pids, np.int32)),
                    self._tok, self._pos, self._keys, self._temps, kp,
                    kdec, jnp.asarray(req.temperature, jnp.float32))
            if self._logprobs_k:
                (self._caches, self._tok, self._pos, self._keys,
                 self._temps, tok0, ok, lp0) = self._prefill_chunk_fn(
                    *args)
            else:
                (self._caches, self._tok, self._pos, self._keys,
                 self._temps, tok0, ok) = self._prefill_chunk_fn(*args)
                lp0 = None
            return jax.device_get((tok0, ok, lp0))

        tp0 = time.monotonic()
        try:
            first, ok, lp0 = _dispatched(run, span=self._tp_span)
            tp1 = time.monotonic()
            req.trace.add_timed("prefill-chunk", tp0, tp1,
                                chunk_off=off, width=W, final=final)
            if not bool(ok):
                raise InferenceFailedError(
                    "model produced non-finite activations during chunked "
                    "prefill (poisoned parameters or a numerically broken "
                    "graph)")
            if self._spec is not None:
                _dispatched(lambda: self._spec.prefill_chunk(
                    self._page_table[slot], ids, off, woff, pids))
        # graftlint: disable=typed-error  converts to a typed failure:
        # _prefill_failure wraps non-ServingError causes in
        # InferenceFailedError and fails only the one request
        except BaseException as e:
            self._prefill_failure(slot, req, e, attached=True)
            return
        self._hook("post_prefill", info)
        with self._cond:
            self.prefill_chunks += 1
            self._chunk_ewma = 0.8 * self._chunk_ewma + 0.2 * (tp1 - tp0)
        if not final:
            req.prefill_pos = off + C
            return
        req.prefill_pos = None
        with self._cond:
            self.prefills += 1
            self.tokens_generated += 1
            self._promote_prefix_locked(req)
        if self._spec is not None:
            self._spec.seed_slot(slot, req.seed)
        first = int(first[0])
        req.tokens.append(first)
        self._emit_token(req, lp0)
        # >= len, not n_tokens == 1: a resumed (preempted) request may
        # complete on its re-prefill token
        if len(req.tokens) >= req.n_tokens or first == self.eos_token:
            self._retire(slot, req)
            return
        if req.handoff:
            self._export_slot(slot, req, attached=True, reason="disagg")
            return
        with self._cond:
            self._active[slot] = True

    def _prefill_failure(self, slot: int, req: _GenRequest,
                         e: BaseException, *, attached: bool) -> None:
        """Shared give-up path for one-shot and chunked prefill: free
        the slot + pages, count the failure, and — on a failed DISPATCH
        under donation — fail every in-flight slot (the donated pool
        buffers may be gone with it) and rebuild device state."""
        if self.breaker is not None:
            self.breaker.record_failure(req.probe)
        with self._cond:
            self.failures += 1
            if attached:
                self._slots[slot] = None
                self._active[slot] = False
            self._free_request_pages_locked(req)
            self._cond.notify_all()
        err = e if isinstance(e, ServingError) else \
            InferenceFailedError(
                f"prefill failed: {type(e).__name__}: {e}")
        logger.warning("decode engine: prefill failure (%s)", err)
        self._finish_obs(req, err, phase="prefill")
        if self._donate and getattr(e, "_dispatch_failure", False):
            # the raised DISPATCH may have invalidated the DONATED page
            # pools — every in-flight slot's KV is gone with them, so
            # those requests must fail too (queued ones survive: they
            # hold no device state), then the state rebuilds.
            # Post-dispatch failures (non-finite screen, hooks) and the
            # no-donation CPU path leave the pools valid: only this
            # request fails
            self._fail_occupied_slots(InferenceFailedError(
                "paged KV pool lost to a failed prefill dispatch "
                "(donated buffers)"))
            self._reset_device_state()

    def _fail_occupied_slots(self, err: BaseException) -> None:
        """Fail EVERY slot-holding request (decoding or mid-prefill) —
        used when a failed dispatch may have invalidated the donated
        pools, which back all of them."""
        with self._cond:
            for s, r in enumerate(self._slots):
                if r is not None:
                    self._slots[s] = None
                    self._active[s] = False
                    r.pages = None  # pools rebuild wholesale after this
                    r.nodes = None  # ... and the prefix cache clears
                    if self.breaker is not None:
                        self.breaker.record_failure(r.probe)
                    self._finish_obs(r, err)
            self._cond.notify_all()

    def _retire(self, slot: int, req: _GenRequest, *,
                attached: bool = True) -> None:
        """Successful completion: free the slot AND its pages, credit
        the breaker, deliver the tokens."""
        with self._cond:
            if attached:
                self._slots[slot] = None
                self._active[slot] = False
            self._free_request_pages_locked(req)
            self.served += 1
            ts = self._tenants.get(req.tenant)
            if ts is not None:
                ts.served += 1
                ts.tokens_generated += len(req.tokens)
            self._cond.notify_all()
        if self.breaker is not None:
            self.breaker.record_success(req.probe)
        # trace rides along so a p99 excursion can pin THIS request's
        # timeline in the failure ring (observability excursion hook)
        self._gen_latency_hist.observe(
            1e3 * (time.monotonic() - req.enqueued_at), trace=req.trace)
        self.recorder.event("retire", slot=slot, tokens=len(req.tokens))
        self._finish_obs(req)

    # -- KV handoff / live migration (kv_transfer) -------------------------
    def _export_slot(self, slot: int, req: _GenRequest, *,
                     attached: bool = True,
                     reason: str = "migrate") -> None:
        """Scheduler-thread export: serialize this slot's decode state
        (used KV pages of every block + scale sidecars, page span,
        position/last-token registers, the LIVE per-slot PRNG key, the
        emitted transcript) into a leased handoff payload, release the
        slot, and finish the request with the `SlotMigratedError`
        redirect. Page ownership moves to the lease — freed exactly
        once by commit, abort, or TTL expiry. Must run on the scheduler
        thread: the registers it reads are replaced functionally by
        every dispatch."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.serving import kv_transfer

        pos_, tok_, key_, temp_ = jax.device_get(
            (self._pos[slot], self._tok[slot], self._keys[slot],
             self._temps[slot]))
        pos = int(pos_)
        page = self.page_size
        used = min(-(-pos // page), len(req.pages))
        jidx = jnp.asarray(np.asarray(req.pages[:used], np.int32))
        names = ("k", "v", "ks", "vs") if self._kv_quant else ("k", "v")
        blocks = []
        for c in self._caches:
            blocks.append({name: np.asarray(jax.device_get(arr[jidx]))
                           for name, arr in zip(names, c)})
        handoff_id = kv_transfer.LeaseTable.new_id()
        payload = kv_transfer.build_payload(
            handoff_id=handoff_id, kind="warm",
            weight_version=self._weight_version,
            kv_quant=self._kv_quant, page_size=page,
            n_blocks=len(self._caches), prompt=req.prompt,
            n_tokens=req.n_tokens, temperature=req.temperature,
            seed=req.seed, resumed_at=req.resumed_at,
            tokens=req.tokens, blocks=blocks, pages_shipped=used,
            pos=pos, tok=int(tok_), key=np.asarray(key_, np.uint32),
            temp=float(temp_), tenant=req.tenant, priority=req.priority,
            preempted=req.preempted, logprobs=req.logprobs,
            logprob_values=list(req.logprob_values),
            deadline_remaining=None if req.deadline is None
            else max(0.0, req.deadline - time.monotonic()))
        nbytes = kv_transfer.payload_nbytes(payload)
        with self._cond:
            self._leases.grant(payload, pages=req.pages,
                               n_shared=req.n_shared, nodes=req.nodes)
            req.pages = None  # ownership moved to the lease
            req.nodes = None
            if attached:
                self._slots[slot] = None
                self._active[slot] = False
            self.migrations_out += 1
            self.kv_transfer_bytes += nbytes
            self._cond.notify_all()
        if self.breaker is not None:
            # an export is a routing decision, not sickness: the device
            # work so far was healthy, and the token must not be dropped
            self.breaker.record_success(req.probe)
        req.trace.event("migrate-out", handoff_id=handoff_id, slot=slot,
                        pos=pos, pages_shipped=used, bytes=nbytes,
                        reason=reason)
        self.recorder.event("migrate-out", handoff_id=handoff_id,
                            slot=slot, pos=pos, pages_shipped=used,
                            bytes=nbytes, reason=reason)
        self._finish_obs(req, kv_transfer.SlotMigratedError(
            f"slot exported under lease {handoff_id} ({reason}); fetch "
            "the handoff and resume on a peer",
            handoff_id=handoff_id, tokens=list(req.tokens)))

    def _export_cold(self, req: _GenRequest, *, reason: str) -> None:
        """Export a request that holds no (complete) KV — queued, or
        parked mid-prefill — as a cold handoff: the peer re-prefills
        from the prompt with the same seed, reproducing the exact
        output. No pages ride the lease (there is nothing complete to
        ship), but the payload stays fetchable until resolution."""
        from deeplearning4j_tpu.serving import kv_transfer

        handoff_id = kv_transfer.LeaseTable.new_id()
        payload = kv_transfer.build_payload(
            handoff_id=handoff_id, kind="cold",
            weight_version=self._weight_version,
            kv_quant=self._kv_quant, page_size=self.page_size,
            n_blocks=len(self._caches), prompt=req.prompt,
            n_tokens=req.n_tokens, temperature=req.temperature,
            seed=req.seed, resumed_at=req.resumed_at,
            tokens=req.tokens, blocks=[], pages_shipped=0,
            tenant=req.tenant, priority=req.priority,
            preempted=req.preempted, logprobs=req.logprobs,
            logprob_values=list(req.logprob_values),
            deadline_remaining=None if req.deadline is None
            else max(0.0, req.deadline - time.monotonic()))
        with self._cond:
            self._leases.grant(payload)
            self.migrations_out += 1
            self._cond.notify_all()
        req.trace.event("migrate-out", handoff_id=handoff_id,
                        kind="cold", reason=reason)
        self.recorder.event("migrate-out", handoff_id=handoff_id,
                            handoff_kind="cold", reason=reason)
        self._finish_obs(req, kv_transfer.SlotMigratedError(
            f"request exported cold under lease {handoff_id} ({reason});"
            " resume re-prefills from the prompt on a peer",
            handoff_id=handoff_id, tokens=list(req.tokens)))

    def _step_migrations(self) -> None:
        """One-shot migrate-everything pass (armed by
        `migrate_slots()`): decoding slots export warm (their KV pages
        ship), queued and mid-prefill requests export cold (partial KV
        is never shipped — it is not provably complete)."""
        with self._cond:
            if not self._migrate_all:
                return
            self._migrate_all = False
            queued = list(self._queue)
            self._queue.clear()
            for r in queued:
                self._pages_demand_queued -= r.n_pages
                self._free_request_pages_locked(r)  # delta-pin release
            parked = []
            decoding = []
            for s, r in enumerate(self._slots):
                if r is None:
                    continue
                if self._active[s] and r.prefill_pos is None:
                    decoding.append((s, r))
                else:
                    parked.append((s, r))
            for s, r in parked:
                self._slots[s] = None
                self._active[s] = False
                self._free_request_pages_locked(r)
            self._cond.notify_all()
        for r in queued:
            self._export_cold(r, reason="migrate")
        for s, r in parked:
            if self.breaker is not None:
                self.breaker.record_success(r.probe)
            self._export_cold(r, reason="migrate")
        for s, r in decoding:
            self._export_slot(s, r, attached=True, reason="migrate")

    def _drain_prefix_exports_locked(self, err: BaseException) -> None:
        """Release every parked `export_prefix` waiter with `err` — a
        scheduler exiting (shutdown/kill) must not leave RPC threads
        blocked until their timeout."""
        assert_owned(self._cond,
                     "DecodeEngine._drain_prefix_exports_locked")
        while self._prefix_exports:
            item = self._prefix_exports.popleft()
            item["error"] = err
            item["done"].set()

    def _serve_prefix_exports(self) -> None:
        """Scheduler-thread service for parked `export_prefix` items:
        only this thread may read the pools between dispatches (a
        donated dispatch invalidates the old buffers), so the
        device_get of the chain's pages happens here; the lease grant
        pins the chain nodes for the drain, and the waiting RPC thread
        gets the framed header."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.serving import kv_transfer

        while True:
            with self._cond:
                if not self._prefix_exports:
                    return
                item = self._prefix_exports.popleft()
                nodes = [] if self._prefix_cache is None else \
                    self._prefix_cache.match(item["prompt"],
                                             tenant=item["tenant"])
                depth = len(nodes)
                have = item["have"]
                if depth <= have:
                    item["error"] = kv_transfer.KVTransferError(
                        f"prefix chain no longer resident here beyond "
                        f"{have} pages (holds {depth}); the directory "
                        "entry was stale — fall back to cold prefill")
                    item["done"].set()
                    continue
                self._prefix_cache.acquire(nodes)
                pages = [n.page_id for n in nodes]
            try:
                jidx = jnp.asarray(np.asarray(pages[have:], np.int32))
                names = ("k", "v", "ks", "vs") if self._kv_quant \
                    else ("k", "v")
                blocks = []
                for c in self._caches:
                    blocks.append(
                        {name: np.asarray(jax.device_get(arr[jidx]))
                         for name, arr in zip(names, c)})
                handoff_id = kv_transfer.LeaseTable.new_id()
                payload = kv_transfer.build_payload(
                    handoff_id=handoff_id, kind="prefix",
                    weight_version=self._weight_version,
                    kv_quant=self._kv_quant, page_size=self.page_size,
                    n_blocks=len(self._caches),
                    prompt=item["prompt"][:depth * self.page_size],
                    n_tokens=0, temperature=0.0, seed=0, resumed_at=0,
                    tokens=[], blocks=blocks,
                    pages_shipped=depth - have, pages_omitted=have,
                    tenant=item["tenant"], source=self._holder_id)
                header = kv_transfer.payload_header(
                    payload,
                    frame_pages=item["frame_pages"]
                    or self._prefix_fetch_frame_pages)
            # graftlint: disable=typed-error  the export dies typed on
            # the WAITER (a wire edge), never in the scheduler loop;
            # the pins release like an aborted lease
            except BaseException as e:
                with self._cond:
                    self._prefix_cache.release(nodes)
                    self._cond.notify_all()
                item["error"] = e if isinstance(e, ServingError) else \
                    kv_transfer.KVTransferError(
                        f"prefix export failed: {type(e).__name__}: {e}")
                item["done"].set()
                continue
            nbytes = kv_transfer.payload_nbytes(payload)
            with self._cond:
                # n_shared == len(pages): lease resolution releases the
                # pins and returns NOTHING to the free list — the cache
                # owns these pages; the lease only pins them while the
                # receiver drains frames
                self._leases.grant(payload, pages=pages,
                                   n_shared=len(pages), nodes=nodes)
                self.prefix_exports_served += 1
                self._cond.notify_all()
            item["result"] = header
            item["done"].set()
            self.recorder.event(
                "prefix-export", holder=self._holder_id,
                handoff_id=handoff_id, pages=depth - have,
                skipped=have, bytes=nbytes)

    def _sweep_leases(self) -> None:
        """Orphan reclamation: a receiver that died (or never
        committed) lets its lease expire; the pages come home here, so
        a dead receiver can never leak sender pages."""
        now = time.monotonic()
        with self._cond:
            if not self._leases.expired_pending(now):
                return
            for lease in self._leases.sweep(now):
                self._release_lease_locked(lease)
                self.handoffs_expired += 1
                self.recorder.event("lease-expired",
                                    handoff_id=lease.handoff_id)
            self._cond.notify_all()

    def _release_lease_locked(self, lease) -> None:
        """Return a resolved lease's page ownership to the pool —
        mirror of `_free_request_pages_locked`, once per lease."""
        assert_owned(self._cond, "DecodeEngine._release_lease_locked")
        if lease.nodes:
            self._prefix_cache.release(lease.nodes)
            lease.nodes = None
        if lease.pages:
            self._free_pages.extend(lease.pages[lease.n_shared:])
        lease.pages = None

    # graftlint: hot-loop
    def _bind_prefix_import(self, req: _GenRequest) -> None:
        """Bind a verified cluster-prefix fetch into this request's
        pages: scatter the shipped chain pages into the pool (eager
        `.at[].set`, like `_import_into`), insert the now-resident
        chain into the local prefix cache (publishing to the directory
        exactly as a locally promoted prefix would), and extend the
        request's hit span so suffix prefill starts at the fetched
        depth. A failed scatter drops the bundle and keeps the local
        hit — the request still serves, just colder."""
        import jax.numpy as jnp

        pim, req.prefix_import = req.prefix_import, None
        payload = pim["payload"]
        page = self.page_size
        have = req.n_shared          # local chain pages already bound
        depth = int(pim["depth"])
        omitted = int(payload.get("pages_omitted", 0))
        shipped = int(payload["pages_shipped"])
        off = have - omitted         # leading shipped pages held here
        n_new = depth - have
        if off < 0 or off + n_new > shipped or n_new <= 0:
            self.recorder.event("prefix-fetch", decision="dropped",
                                have=have, depth=depth, skipped=omitted)
            return
        try:
            jidx = jnp.asarray(
                np.asarray(req.pages[have:depth], np.int32))
            names = ("k", "v", "ks", "vs") if self._kv_quant \
                else ("k", "v")
            new_caches = []
            for blk, c in zip(payload["blocks"], self._caches):
                new_c = []
                for name, arr in zip(names, c):
                    src = np.asarray(blk[name])[off:off + n_new]
                    out = arr.at[jidx].set(jnp.asarray(src))
                    if self._tp is not None:
                        out = self._tp.shard_pool(out)
                    new_c.append(out)
                new_caches.append(tuple(new_c))
            self._caches = new_caches
        # graftlint: disable=typed-error  never-slower contract: a
        # failed scatter falls back to prefilling from the local hit;
        # the pools stay valid (eager updates are not donated
        # dispatches)
        except BaseException as e:
            with self._cond:
                self.prefix_fetch_fallbacks += 1
            self.recorder.event("prefix-fetch", decision="bind-failed",
                                error=type(e).__name__)
            logger.warning("cluster prefix bind failed (%s: %s); "
                           "prefilling from the local hit",
                           type(e).__name__, e)
            return
        with self._cond:
            pnodes, freed = self._prefix_cache.insert(
                req.prompt[:depth * page], req.pages[:depth],
                req.nodes or [], tenant=req.tenant)
            self._free_pages.extend(freed)
            gained = (len(pnodes) - have) * page
            req.nodes = pnodes
            req.n_shared = len(pnodes)
            req.hit_len = len(pnodes) * page
            if have == 0:
                # the local lookup missed but the CLUSTER hit: fold
                # the request back into the hit column
                self.prefix_hits += 1
                self.prefix_misses -= 1
            self.prefix_hit_tokens += gained
            self.cluster_prefix_hit_tokens += gained
            self._cond.notify_all()
        req.trace.event("prefix-fetch-bind",
                        pages=len(pnodes) - have,
                        hit_tokens=req.hit_len, source=pim["source"])
        self.recorder.event("prefix-fetch", decision="bound",
                            holder=pim["source"],
                            pages=len(pnodes) - have,
                            hit_tokens=req.hit_len)

    # graftlint: hot-loop
    def _import_into(self, slot: int, req: _GenRequest) -> None:
        """Re-bind a validated warm handoff into a free slot: scatter
        the shipped pages into every block's pools (+ scale sidecars),
        restore the position/last-token/temperature registers and the
        live PRNG key, promote the prompt-covered pages into the prefix
        cache (weight versions already proven equal by validation), and
        activate — the next `_step_active` continues the sequence
        argmax-exact."""
        import jax.numpy as jnp

        payload = req.import_state
        shipped = int(payload["pages_shipped"])
        omitted = int(payload.get("pages_omitted", 0))
        # delta handoff: the first `omitted` pages are the locally
        # resident prefix chain (pinned at resume_submit, already in
        # req.pages as shared pages) — shipped pages land after them
        jidx = jnp.asarray(np.asarray(
            req.pages[omitted:omitted + shipped], np.int32))
        names = ("k", "v", "ks", "vs") if self._kv_quant else ("k", "v")
        new_caches = []
        for blk, c in zip(payload["blocks"], self._caches):
            new_c = []
            for name, arr in zip(names, c):
                out = arr.at[jidx].set(
                    jnp.asarray(np.asarray(blk[name])))
                if self._tp is not None:
                    out = self._tp.shard_pool(out)
                new_c.append(out)
            new_caches.append(tuple(new_c))
        self._caches = new_caches
        pos = int(payload["pos"])
        self._pos = self._pos.at[slot].set(pos)
        self._tok = self._tok.at[slot].set(int(payload["tok"]))
        self._keys = self._keys.at[slot].set(
            jnp.asarray(np.asarray(payload["key"], np.uint32)))
        self._temps = self._temps.at[slot].set(float(payload["temp"]))
        with self._cond:
            req.slot = slot
            req.import_state = None
            self._slots[slot] = req
            self._active[slot] = True
            self.migrations_in += 1
            self._promote_prefix_locked(req)
            held = self.pool_pages - len(self._free_pages)
            self.pages_in_use_peak = max(self.pages_in_use_peak, held)
            self._cond.notify_all()
        if self._spec is not None:
            # cold draft mirror: proposals start from draft-side
            # garbage and greedy verify rejects them — still
            # target-exact, just zero speedup until the draft re-warms
            self._spec.seed_slot(slot, req.seed)
        req.trace.event("migrate-in", slot=slot, pages_shipped=shipped,
                        pos=pos)
        self.recorder.event("migrate-in", slot=slot,
                            handoff_id=payload["handoff_id"],
                            pages_shipped=shipped, pos=pos)

    def _import_failure(self, slot: int, req: _GenRequest,
                        e: BaseException) -> None:
        """A failed import touches only this request: the eager pool
        updates are not donated dispatches, so other slots' KV is
        intact. The breaker token returns as success — a transfer
        failure is wire trouble, not model sickness."""
        from deeplearning4j_tpu.serving.kv_transfer import KVTransferError

        if self.breaker is not None:
            self.breaker.record_success(req.probe)
        with self._cond:
            self.failures += 1
            self._slots[slot] = None
            self._active[slot] = False
            self._free_request_pages_locked(req)
            self._cond.notify_all()
        err = e if isinstance(e, ServingError) else KVTransferError(
            f"KV import failed: {type(e).__name__}: {e}")
        logger.warning("decode engine: KV import failure (%s)", err)
        self._finish_obs(req, err, phase="import")

    # graftlint: hot-loop
    def _expire_in_flight(self) -> None:
        """An expired in-flight request (decoding OR mid-prefill) frees
        its slot and pages immediately — the next queued request takes
        them on the following iteration. Expired QUEUED requests are
        also swept here (not only at admission), so a doomed request
        behind long-running slots fails promptly."""
        now = time.monotonic()
        expired_queued = []
        with self._cond:
            keep = collections.deque()
            while self._queue:
                req = self._queue.popleft()
                if req.expired(now):
                    expired_queued.append(req)
                    self._pages_demand_queued -= req.n_pages
                    self._free_request_pages_locked(req)
                else:
                    keep.append(req)
            self._queue = keep
            self.shed_deadline += len(expired_queued)
        for req in expired_queued:
            req.trace.add_timed("queue-wait", req.enqueued_at, now,
                                decision="expired")
            self._finish_obs(req, DeadlineExceededError(
                "deadline expired while queued; request shed before "
                "prefill"))
        for s in range(self.n_slots):
            req = self._slots[s]
            if req is not None and req.expired(now):
                with self._cond:
                    self._slots[s] = None
                    self._active[s] = False
                    self._free_request_pages_locked(req)
                    self.shed_deadline += 1
                    self._cond.notify_all()
                if self.breaker is not None:
                    # the device work done so far was healthy; expiry is
                    # a deadline event, not a model failure
                    self.breaker.record_success(req.probe)
                self._finish_obs(req, DeadlineExceededError(
                    f"deadline expired after {len(req.tokens)} of "
                    f"{req.n_tokens} tokens; slot freed"))

    def _chunk_eligible(self, live, now: float) -> bool:
        """A chunked decode dispatch is allowed only when no scheduling
        event can land inside it: every live request needs at least a
        full chunk more tokens, no deadline could expire before the
        chunk returns, no prompt is mid-prefill (its chunks must
        interleave with decode, not wait behind a fused run), and —
        when EOS can retire a slot mid-chunk — no queued request is
        waiting to take a freed slot (without an eos_token, the
        remaining-tokens bound already proves nothing retires
        mid-chunk). Admission waits at most one chunk — `_admit` runs
        before every dispatch."""
        if self.decode_chunk <= 1:
            return False
        with self._cond:
            if any(r is not None and r.prefill_pos is not None
                   for r in self._slots):
                return False
            if self.eos_token is not None and self._queue:
                return False  # a mid-chunk EOS would strand the slot
        margin = 2.0 * self.decode_chunk * max(self._step_ewma, 1e-4)
        for _, r in live:
            if r.n_tokens - len(r.tokens) < self.decode_chunk:
                return False
            if r.deadline is not None and r.deadline - now < margin:
                return False
        return True

    def _decode_failure(self, live, e: BaseException) -> None:
        """Shared decode-step give-up: fail every live request typed,
        free slots + pages, and — on a failed DISPATCH under donation —
        fail mid-prefill slots too and rebuild the device state (the
        donated pools back all of them)."""
        err = e if isinstance(e, ServingError) else \
            InferenceFailedError(
                f"decode step failed: {type(e).__name__}: {e}")
        logger.warning("decode engine: decode failure (%s)", err)
        with self._cond:
            self.failures += len(live)
        for s, req in live:
            if self.breaker is not None:
                self.breaker.record_failure(req.probe)
            with self._cond:
                self._slots[s] = None
                self._active[s] = False
                self._free_request_pages_locked(req)
                self._cond.notify_all()
            self._finish_obs(req, err, phase="decode")
        if getattr(e, "_dispatch_failure", False):
            # only a failed DISPATCH can have invalidated the donated
            # pool buffers; hook failures leave them valid. Mid-prefill
            # slots are backed by the same pools — they go down with
            # them before the rebuild
            self._fail_occupied_slots(InferenceFailedError(
                "paged KV pool lost to a failed decode dispatch "
                "(donated buffers)"))
            self._reset_device_state()

    # graftlint: hot-loop
    def _retire_or_poison(self, s: int, req: _GenRequest, toks, oks,
                          n_steps: int, lps=None) -> None:
        """Consume one slot's emitted tokens from a decode/verify
        dispatch: append until done (count or EOS — overshoot dropped
        with the slot) or until a poisoned step fails the request typed
        while healthy neighbors keep decoding. `lps` is the slot's
        per-step (chosen, top_values, top_ids) logprob batch when the
        engine computes logprobs."""
        done = False
        poisoned = False
        for t in range(n_steps):
            if not bool(oks[t]):
                poisoned = True
                break
            tok = int(toks[t])
            req.tokens.append(tok)
            self._emit_token(req, lps, t)
            with self._cond:
                self.tokens_generated += 1
            if len(req.tokens) >= req.n_tokens \
                    or tok == self.eos_token:
                done = True
                break
        if poisoned:
            nf_err = InferenceFailedError(
                "model produced non-finite logits during decode "
                "(poisoned parameters or a numerically broken graph)")
            logger.warning("decode engine: %s", nf_err)
            with self._cond:
                self.failures += 1
                self._slots[s] = None
                self._active[s] = False
                self._free_request_pages_locked(req)
                self._cond.notify_all()
            if self.breaker is not None:
                self.breaker.record_failure(req.probe)
            self._finish_obs(req, nf_err, phase="decode")
        elif done:
            self._retire(s, req)

    # graftlint: hot-loop
    def _step_active_spec(self, live) -> bool:
        """One speculative iteration: draft proposes k tokens per slot,
        the target verifies them in one batched chunk — up to k+1
        tokens per slot in two dispatches. Returns False (caller falls
        back to the vanilla step) when no live slot has the write
        budget to speculate."""
        import jax
        import jax.numpy as jnp

        spec = self._spec
        k = spec.k
        # a slot can commit m speculative tokens only while its writes
        # stay within the reserved span: pos + m <= t0 + n_tokens - 2
        # (the last token is never written back). With pos = t0 + len - 1
        # that cap is rem - 1 (rem = tokens still to emit), so a slot
        # with rem >= 2 can still accept; when EVERY slot is down to its
        # final token the plain step is strictly cheaper
        if all(r.n_tokens - len(r.tokens) < 2 for _, r in live):
            return False
        wl = np.zeros((self.n_slots,), np.int32)
        for s, r in live:
            # resumed_at keeps the write limit at the ORIGINAL logical
            # span: a preempted request's prompt absorbed its emitted
            # tokens, which its n_tokens budget already spans
            wl[s] = r.prompt.shape[0] - r.resumed_at + r.n_tokens - 2
        info = {"active": len(live), "step": self.decode_steps,
                "spec": True, "k": k}
        t0c = time.monotonic()
        try:
            self._hook("pre_decode", info)

            def run():
                wlimit = jnp.asarray(wl)
                active = jnp.asarray(self._active)
                (spec._caches, spec._keys, props, qd) = spec._propose(
                    spec._draft_params(), spec._caches, self._page_table,
                    self._tok, self._pos, spec._keys, self._temps,
                    active, wlimit)
                (self._caches, self._tok, self._pos, self._keys, out,
                 n_emit, oks) = spec._verify(
                    self._dparams, self._caches, self._page_table,
                    self._tok, self._pos, self._keys, self._temps,
                    active, wlimit, props, qd)
                return jax.device_get((out, n_emit, oks))

            out, n_emit, oks = _dispatched(run, span=self._tp_span)
            self._hook("post_decode", info)
            t1c = time.monotonic()
        # graftlint: disable=typed-error  converts to a typed failure:
        # _decode_failure wraps the cause in InferenceFailedError for the
        # affected slots and recovers the pool
        except BaseException as e:
            self._decode_failure(live, e)
            return True
        emitted = int(sum(max(1, int(n_emit[s])) for s, _ in live))
        with self._cond:
            self._step_ewma = (0.8 * self._step_ewma
                               + 0.2 * (t1c - t0c)
                               * len(live) / max(1, emitted))
            self.decode_steps += 1
            self.active_slot_steps += len(live)
            self.spec_steps += 1
            for s, r in live:
                # proposals that could actually be consumed: the device
                # cap is m_cap = wlimit - pos = rem - 1, so accepted
                # (= n_emit - 1 <= m_cap) never exceeds this count and
                # the accept RATE stays a true <=100% ratio
                self.spec_proposed += min(
                    k, max(0, r.n_tokens - len(r.tokens) - 1))
                self.spec_accepted += max(0, int(n_emit[s]) - 1)
        delivered = 0
        for s, req in live:
            n = max(1, int(n_emit[s]))
            req.trace.add_timed("spec-verify", t0c, t1c, k=k,
                                emitted=n, active=len(live))
            before = len(req.tokens)
            self._retire_or_poison(s, req, out[s, :n],
                                   np.repeat(oks[s], n), n)
            delivered += len(req.tokens) - before
        with self._cond:
            # spec_tokens_per_step is a DELIVERED-throughput number:
            # tokens appended to requests, not device emissions — a
            # mid-verify EOS's dropped overshoot must not inflate it
            self.spec_emitted += delivered
        return True

    # graftlint: hot-loop
    def _step_active(self) -> None:
        import jax.numpy as jnp

        live = [(s, r) for s, r in enumerate(self._slots)
                if r is not None and r.prefill_pos is None]
        if not live:
            return
        if self._spec is not None and self._step_active_spec(live):
            return
        now = time.monotonic()
        chunked = self._spec is None and self._chunk_eligible(live, now)
        info = {"active": len(live), "step": self.decode_steps,
                "chunk": self.decode_chunk if chunked else 1}
        t0 = time.monotonic()
        try:
            import jax

            self._hook("pre_decode", info)

            def run():
                if chunked:
                    if self._logprobs_k:
                        (self._caches, self._tok, self._pos, self._keys,
                         toks_d, oks_d, lps_d) = self._decode_chunked(
                            self._dparams, self._caches,
                            self._page_table, self._tok, self._pos,
                            self._keys, self._temps,
                            jnp.asarray(self._active))
                    else:
                        (self._caches, self._tok, self._pos, self._keys,
                         toks_d, oks_d) = self._decode_chunked(
                            self._dparams, self._caches,
                            self._page_table, self._tok, self._pos,
                            self._keys, self._temps,
                            jnp.asarray(self._active))
                        lps_d = None
                    # (chunk, S) tokens + per-step flags, ONE host sync
                    return jax.device_get((toks_d, oks_d, lps_d))
                if self._logprobs_k:
                    (self._caches, self._tok, self._pos, self._keys,
                     ok_d, lp_d) = self._decode_step(
                        self._dparams, self._caches, self._page_table,
                        self._tok, self._pos, self._keys, self._temps,
                        jnp.asarray(self._active))
                else:
                    (self._caches, self._tok, self._pos, self._keys,
                     ok_d) = self._decode_step(
                        self._dparams, self._caches, self._page_table,
                        self._tok, self._pos, self._keys, self._temps,
                        jnp.asarray(self._active))
                    lp_d = None
                # THE per-iteration host sync — the price of
                # iteration-level scheduling; chunking amortizes it
                t, o, lp = jax.device_get((self._tok, ok_d, lp_d))
                return t[None], o[None], (None if lp is None else
                                          tuple(a[None] for a in lp))

            toks, oks, lps = _dispatched(run, span=self._tp_span)
            self._hook("post_decode", info)
        # graftlint: disable=typed-error  converts to a typed failure:
        # _decode_failure wraps the cause in InferenceFailedError for the
        # affected slots and recovers the pool
        except BaseException as e:
            self._decode_failure(live, e)
            return
        t1 = time.monotonic()
        n_steps = toks.shape[0]
        with self._cond:
            self._step_ewma = (0.8 * self._step_ewma
                               + 0.2 * (t1 - t0) / n_steps)
            self.decode_steps += n_steps
            self.active_slot_steps += len(live) * n_steps
        for s, req in live:
            req.trace.add_timed("decode", t0, t1, steps=n_steps,
                                active=len(live))
            # per-step, per-slot non-finite screen (predict's breaker
            # discipline): a poisoned step fails THIS request typed —
            # unless it already completed via EOS at an earlier step of
            # the chunk — and healthy neighbors keep decoding (their
            # pages are untouched)
            lp_s = None if lps is None else \
                (lps[0][:, s], lps[1][:, s], lps[2][:, s])
            self._retire_or_poison(s, req, toks[:, s], oks[:, s],
                                   n_steps, lps=lp_s)

    # graftlint: hot-loop
    def _maybe_swap(self) -> None:
        if not self._draining:
            return
        with self._cond:
            if any(r is not None for r in self._slots):
                return  # still draining: in-flight finish on old weights
            net = self._swap_net
            if net is None:  # drain abandoned (timeout in drain_and_swap)
                self._draining = False
                return
            # claimed: from here the swap WILL complete (or fail) and
            # set _swap_done — a timing-out drain_and_swap caller sees
            # this flag and waits it out instead of mis-reporting
            # "old weights still serving"
            self._swap_in_progress = True
        try:
            if net is self._net:
                # swap target IS the net the pools/prefix pages were
                # built under (ModelServer.restore_model hands back the
                # same object on rollback): skip the rebuild, keeping
                # warm page pools and every prefix-cache entry — a
                # failed canary rolls back FREE instead of serving the
                # next burst cold (ROADMAP item 5)
                with self._cond:
                    self.swaps += 1
                self.recorder.event("swap", decision="preserved-pools")
                return
            self._build(net)
            misfit = []
            with self._cond:
                self.swaps += 1
                # queued requests were validated against the OLD
                # max_len/page geometry; the rebuilt engine may be
                # tighter. A request that no longer fits would decode
                # silently-wrong tail tokens past the new cache length —
                # fail it typed instead. Survivors' page demand is
                # recomputed against the NEW geometry, re-applying both
                # admission bounds (per-request pool fit + wait-room cap)
                keep: collections.deque = collections.deque()
                reserved = 0
                while self._queue:
                    r = self._queue.popleft()
                    # delta-import pins reference the PRE-swap cache
                    # object (replaced by the rebuild, its pages
                    # reclaimed wholesale): null them, never release
                    # against the fresh cache
                    r.nodes = None
                    r.n_shared = 0
                    if r.import_state is not None:
                        # queued warm handoff: its KV was computed under
                        # the PRE-swap weights — binding it now would
                        # decode silently-wrong tokens. Fail it typed;
                        # the caller's fallback ladder re-prefills
                        misfit.append(r)
                        continue
                    if r.prompt.shape[0] - r.resumed_at + r.n_tokens \
                            > self.max_len:
                        misfit.append(r)
                        continue
                    r.n_pages = self._pages_for(
                        r.prompt.shape[0],
                        max(1, r.n_tokens - r.resumed_at))
                    if r.n_pages > self.pool_pages or \
                            reserved + r.n_pages > self.max_queued_pages:
                        misfit.append(r)  # incl. pool shrunk below the
                        continue          # surviving queue's demand
                    reserved += r.n_pages
                    keep.append(r)
                self._queue = keep
                self._pages_demand_queued = reserved
            from deeplearning4j_tpu.serving.kv_transfer import (
                KVTransferError,
            )

            for r in misfit:
                if r.import_state is not None:
                    self._finish_obs(r, KVTransferError(
                        "queued KV handoff refused: the engine's "
                        "weights swapped while it waited — stale KV "
                        "must not bind; fall back to re-prefill"))
                    continue
                self._finish_obs(r, ServingError(
                    f"request (prompt {r.prompt.shape[0]} + n_tokens "
                    f"{r.n_tokens}) no longer fits the swapped engine's "
                    f"max_len {self.max_len} / {self.pool_pages}-page "
                    "pool"))
            self.recorder.event("swap", decision="complete",
                                misfit=len(misfit))
        # graftlint: disable=typed-error  deliberate absorb: a rejected
        # swap keeps the OLD weights serving; the error is stored for
        # drain_and_swap's caller to re-raise
        except BaseException as e:
            with self._cond:
                self._swap_error = e
            self.recorder.event("swap", decision="rejected",
                                error=type(e).__name__)
            logger.warning("decode engine: weight swap rejected (%s); "
                           "old weights still serving", e)
        finally:
            with self._cond:
                self._swap_net = None
                self._draining = False
                self._swap_in_progress = False
                self._cond.notify_all()
            self._swap_done.set()
