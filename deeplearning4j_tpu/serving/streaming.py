"""Resumable token streaming: per-request emitted-token rings.

The streaming tier's contract (ROADMAP 5(a)): a token, once emitted by
a decode slot, is delivered to the consumer EXACTLY once and in order —
across torn connections, slow consumers, replica failovers, and live
KV migrations — or the consumer gets a typed error telling it how to
recover. The pieces:

- **`TokenStream`** — one bounded ring per in-flight generation. The
  decode engine's emission hook `publish()`es every token under a
  monotonic **cursor** (= tokens emitted so far, 1-based); `publish` is
  O(1), never blocks, and never raises into the scheduler loop. The
  ring retains the most recent `capacity` tokens so a reconnecting
  consumer can replay from its last cursor; a consumer that fell out
  of the window gets a typed `StreamBackpressureError` and falls back
  to the exactly-once parked outcome (`claim`).
- **Cursor dedup IS the exactly-once delivery mechanism**: a publish at
  a cursor ≤ the stream's high-water mark is dropped and counted
  (`duplicate_tokens_dropped`). A replica-pool failover re-runs the
  seeded generation from scratch and re-publishes cursors 1..k into
  the SAME stream; a warm KV migration resumes at k+1 on the peer.
  Either way the consumer-visible sequence is append-only — zero
  duplicates, zero gaps, concatenation identical to the unary result.
- **`StreamRegistry`** — the gateway's keyed map of live + recently
  finished streams. `resume_stream(request_id, cursor)` attaches here;
  finished streams linger for `ttl` seconds so a terminal frame lost
  on the wire can still be replayed, then a lazy sweep (no background
  thread) retires them to the dedup door's parked-outcome path.

Slow consumers are shed, never accommodated: the scheduler-side
`publish` drops the OLDEST ring entries on overflow (the slot keeps
decoding at full speed), and it is the *pump* — the gateway handler
thread feeding one socket — that discovers the lag and sheds the
consumer with a typed error. A stalled reader can therefore never pin
a decode slot or stall other slots' emission.
"""
from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.serving.model_server import ServingError


class StreamBackpressureError(ServingError):
    """The consumer's cursor fell out of the bounded emitted-token ring
    (it stalled while the slot kept decoding) — the stream cannot be
    resumed losslessly from the ring. The generation itself is NOT
    lost: the outcome parks behind the exactly-once door and
    `claim(request_id)` recovers the full sequence. `retry_after`
    hints when the parked outcome should be ready."""

    def __init__(self, msg: str, retry_after: float = 0.5):
        super().__init__(msg)
        self.retry_after = retry_after


class TokenStream:
    """One request's bounded emitted-token ring.

    `publish(cursor, token)` is called from the decode engine's
    scheduler loop: O(1), lock held for a few appends, never blocks on
    a consumer, never raises. `read(cursor)` is called from a gateway
    handler thread pumping one socket: blocks (bounded) for new tokens
    and replays retained history for resumes. `finish(body)` parks the
    terminal wire body (result or typed error + trace) on the stream;
    it is idempotent — the first body wins, so the handler-side worker
    (which holds the trace-enriched body) and the bare execution path
    can both call it safely."""

    def __init__(self, request_id: str, capacity: int = 1024):
        if capacity < 1:
            raise ValueError("stream ring capacity must be >= 1")
        self.request_id = request_id
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # set by finish(); lets a coalescing read() linger without
        # taking a wakeup per published token (publish never sets it)
        self._finished = threading.Event()
        # ring of (token, logprob-entry-or-None); guarded by: _lock
        self._ring: collections.deque = collections.deque()
        self._base = 0  # cursor of the oldest retained token, minus 1; guarded by: _lock
        self._cursor = 0  # tokens published so far (high-water mark); guarded by: _lock
        self._body: Optional[dict] = None  # terminal wire body; guarded by: _lock
        self.duplicate_tokens_dropped = 0  # guarded by: _cond
        self.gap_tokens_dropped = 0  # guarded by: _cond
        self.finished_at: Optional[float] = None  # guarded by: _cond

    # -- producer side (decode engine emission hook) -----------------------
    def publish(self, cursor: int, token: int,
                logprob: Optional[dict] = None) -> bool:
        """Record one emitted token under its absolute cursor.

        Returns True when the token advanced the stream; False when it
        was dropped as a duplicate (cursor ≤ high-water mark — a
        failover re-run or migration replay re-emitting history) or as
        an out-of-order gap (counted loudly; must never happen from a
        single slot's ordered emission)."""
        with self._cond:
            if cursor <= self._cursor:
                self.duplicate_tokens_dropped += 1
                return False
            if cursor != self._cursor + 1:
                # a gap would desync every downstream cursor — refuse
                # the token rather than deliver out of order
                self.gap_tokens_dropped += 1
                return False
            self._ring.append((int(token), logprob))
            self._cursor = cursor
            while len(self._ring) > self.capacity:
                self._ring.popleft()
                self._base += 1
            self._cond.notify_all()
            return True

    def finish(self, body: dict) -> bool:
        """Park the terminal wire body. Idempotent: the first call
        wins; returns True exactly once."""
        with self._cond:
            first = self._body is None
            if first:
                self._body = dict(body)
                self.finished_at = time.monotonic()
            self._cond.notify_all()
            self._finished.set()
            return first

    # -- consumer side (gateway pump) --------------------------------------
    @property
    def cursor(self) -> int:
        with self._lock:
            return self._cursor

    @property
    def done(self) -> bool:
        with self._lock:
            return self._body is not None

    def read(self, cursor: int, timeout: Optional[float] = None,
             linger: float = 0.0
             ) -> Tuple[List[int], Optional[list], int, Optional[dict]]:
        """Everything published past `cursor`, blocking up to `timeout`
        for the first new token. Returns `(tokens, logprobs, new_cursor,
        terminal_body)` — `logprobs` is None unless any returned token
        carries a logprob entry; `terminal_body` is None until the
        stream finished. An empty `tokens` with a body means the
        consumer is fully drained and the body is the terminal frame.

        `linger` > 0 keeps waiting that long AFTER the first new token
        so follow-ups batch into one frame — per-token frame writes are
        the streaming goodput tax. The linger sleeps on the `finished`
        event (publish never touches it), so it costs ZERO wakeups per
        token and aborts the instant the stream finishes: the terminal
        body is never delayed by coalescing.

        Raises `StreamBackpressureError` when `cursor` fell out of the
        ring — the consumer must fall back to the parked outcome."""
        toks, lps, new_cursor, body = self._read_locked(cursor, timeout)
        if linger > 0 and toks and body is None:
            self._finished.wait(linger)
            return self._read_locked(cursor, timeout=0.0)
        return toks, lps, new_cursor, body

    def _read_locked(self, cursor: int, timeout: Optional[float]
                     ) -> Tuple[List[int], Optional[list], int,
                                Optional[dict]]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                if cursor < self._base:
                    raise StreamBackpressureError(
                        f"stream {self.request_id!r}: cursor {cursor} fell "
                        f"out of the {self.capacity}-token ring (oldest "
                        f"retained cursor is {self._base + 1}) — the "
                        "consumer stalled past the replay window; claim "
                        "the parked outcome instead")
                if cursor < self._cursor:
                    start = cursor - self._base
                    entries = list(itertools.islice(
                        self._ring, start, len(self._ring)))
                    toks = [t for t, _ in entries]
                    lps = [lp for _, lp in entries]
                    if not any(lp is not None for lp in lps):
                        lps = None
                    return toks, lps, self._cursor, self._body
                if self._body is not None:
                    return [], None, cursor, self._body
                if deadline is None:
                    self._cond.wait()
                else:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return [], None, cursor, None
                    self._cond.wait(left)


class StreamRegistry:
    """The gateway's keyed map of token streams.

    `open()` is called once per `generate_stream` execution (re-opening
    a LIVE stream attaches to it — that is how a replica-pool failover
    re-run keeps publishing into the same ring); `attach()` serves
    `resume_stream`; a lazy TTL sweep (piggybacked on open/attach, no
    background thread) retires finished streams and folds their dedup
    counters into the registry totals. `stats()` matches
    `observability.STREAMING_STATS_KEYS` and is registered into the
    serving tier's MetricsRegistry for Prometheus exposition."""

    def __init__(self, ring: int = 1024, ttl: float = 120.0):
        if ttl <= 0:
            raise ValueError("stream ttl must be > 0")
        self.ring = int(ring)
        self.ttl = float(ttl)
        self._lock = threading.Lock()
        self._streams: Dict[str, TokenStream] = {}  # guarded by: _lock
        self._opened = 0  # guarded by: _lock
        self._finished = 0  # guarded by: _lock
        self._resumes = 0  # guarded by: _lock
        self._sheds = 0  # guarded by: _lock
        self._dups_retired = 0  # dropped dups of swept streams; guarded by: _lock

    def _sweep_locked(self) -> None:
        now = time.monotonic()
        dead = [rid for rid, s in self._streams.items()
                if s.finished_at is not None
                and now - s.finished_at > self.ttl]
        for rid in dead:
            self._dups_retired += \
                self._streams.pop(rid).duplicate_tokens_dropped

    def open(self, request_id: str) -> TokenStream:
        """Get-or-create the stream for one execution. A live stream is
        returned as-is (failover re-runs keep the ring and its cursor
        high-water mark — dedup depends on it); a finished one is
        replaced, since a re-execution past the door is a genuinely new
        attempt."""
        rid = str(request_id)
        with self._lock:
            self._sweep_locked()
            stream = self._streams.get(rid)
            if stream is not None and stream.finished_at is None:
                return stream
            if stream is not None:
                self._dups_retired += stream.duplicate_tokens_dropped
            stream = TokenStream(rid, capacity=self.ring)
            self._streams[rid] = stream
            self._opened += 1
            return stream

    def get(self, request_id: str) -> Optional[TokenStream]:
        with self._lock:
            self._sweep_locked()
            return self._streams.get(str(request_id))

    def attach(self, request_id: str) -> Optional[TokenStream]:
        """A resuming consumer re-joins its stream; None when the
        stream aged out (the caller falls back to the parked
        outcome)."""
        with self._lock:
            self._sweep_locked()
            stream = self._streams.get(str(request_id))
            if stream is not None:
                self._resumes += 1
            return stream

    def finish(self, stream: TokenStream, body: dict) -> bool:
        """Park `body` as `stream`'s terminal frame (idempotent) and
        count the finish exactly once."""
        first = stream.finish(body)
        if first:
            with self._lock:
                self._finished += 1
        return first

    def shed(self, stream: TokenStream) -> None:
        """Count one slow-consumer shed (the pump detached; the
        generation keeps running and its outcome parks)."""
        with self._lock:
            self._sheds += 1

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            live = [s for s in self._streams.values()
                    if s.finished_at is None]
            dups = self._dups_retired + sum(
                s.duplicate_tokens_dropped
                for s in self._streams.values())
            return {
                "streams_active": len(live),
                "streams_opened": self._opened,
                "streams_finished": self._finished,
                "stream_resumes": self._resumes,
                "stream_backpressure_sheds": self._sheds,
                "duplicate_tokens_dropped": dups,
                "ring_capacity": self.ring,
                "ttl_s": self.ttl,
            }
