"""Remote stats routing (reference
`deeplearning4j-core/.../api/storage/impl/RemoteUIStatsStorageRouter.java`:
HTTP POSTs stats records to a remote UI's receiver module
`ui/module/remote/RemoteReceiverModule.java`)."""
from __future__ import annotations

import json
import queue
import threading
import urllib.request
from typing import Optional

from deeplearning4j_tpu.ui.storage import StatsRecord, StatsStorageRouter


class RemoteUIStatsStorageRouter(StatsStorageRouter):
    """Asynchronously POSTs records to `<url>/remote/receive` (background
    thread + bounded queue, mirroring the reference's async posting with
    retry backoff)."""

    def __init__(self, url: str, queue_size: int = 1000,
                 retries: int = 3, timeout: float = 5.0):
        self.url = url.rstrip("/") + "/remote/receive"
        self.retries = retries
        self.timeout = timeout
        self._q: "queue.Queue[Optional[StatsRecord]]" = queue.Queue(queue_size)
        self._dropped = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def put_record(self, record: StatsRecord) -> None:
        try:
            self._q.put_nowait(record)
        except queue.Full:
            self._dropped += 1

    def shutdown(self, timeout: float = 10.0) -> None:
        self._q.put(None)
        self._thread.join(timeout)

    def _run(self) -> None:
        while True:
            rec = self._q.get()
            if rec is None:
                return
            body = rec.to_json().encode()
            for attempt in range(self.retries):
                try:
                    req = urllib.request.Request(
                        self.url, data=body,
                        headers={"Content-Type": "application/json"})
                    with urllib.request.urlopen(req, timeout=self.timeout) as r:
                        r.read()
                    break
                except Exception:
                    if attempt == self.retries - 1:
                        self._dropped += 1
