"""Remote stats routing (reference
`deeplearning4j-core/.../api/storage/impl/RemoteUIStatsStorageRouter.java`:
HTTP POSTs stats records to a remote UI's receiver module
`ui/module/remote/RemoteReceiverModule.java`)."""
from __future__ import annotations

import json
import logging
import queue
import threading
import time
import urllib.request
from typing import Optional

from deeplearning4j_tpu.ui.storage import StatsRecord, StatsStorageRouter

logger = logging.getLogger("deeplearning4j_tpu")


class RemoteUIStatsStorageRouter(StatsStorageRouter):
    """Asynchronously POSTs records to `<url>/remote/receive` (background
    thread + bounded queue, mirroring the reference's async posting with
    retry backoff — `RemoteUIStatsStorageRouter.java` retries with
    exponential delay and counts what it sheds).

    Stats delivery is best-effort by design — a slow/unreachable UI must
    never stall training — but loss is OBSERVABLE, never silent:
    `dropped_count` exposes how many records were discarded (full queue,
    or POST retries exhausted), and a rate-limited warning (at most one
    per `warn_every` seconds, with the running total) lands in the log
    the moment shedding starts. Transient POST failures retry `retries`
    times with bounded exponential backoff (`backoff ×2^attempt`)."""

    def __init__(self, url: str, queue_size: int = 1000,
                 retries: int = 3, timeout: float = 5.0,
                 backoff: float = 0.1, warn_every: float = 30.0):
        self.url = url.rstrip("/") + "/remote/receive"
        self.retries = retries
        self.timeout = timeout
        self.backoff = backoff
        self.warn_every = warn_every
        self._q: "queue.Queue[Optional[StatsRecord]]" = queue.Queue(queue_size)
        self._dropped = 0
        self._drop_lock = threading.Lock()
        # -inf, not 0.0: monotonic's origin is arbitrary (host uptime),
        # and the FIRST drop must always warn
        self._last_warn = -float("inf")
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    @property
    def dropped_count(self) -> int:
        """Records discarded so far (queue overflow + exhausted POSTs)."""
        return self._dropped

    def _record_drop(self, why: str) -> None:
        with self._drop_lock:
            self._dropped += 1
            total = self._dropped
            now = time.monotonic()
            warn = now - self._last_warn >= self.warn_every
            if warn:
                self._last_warn = now
        if warn:
            logger.warning(
                "remote UI router: dropping stats records (%s); %d "
                "dropped so far — the UI at %s is slow or unreachable",
                why, total, self.url)

    def put_record(self, record: StatsRecord) -> None:
        try:
            self._q.put_nowait(record)
        except queue.Full:
            self._record_drop("queue full")

    def shutdown(self, timeout: float = 10.0) -> None:
        self._q.put(None)
        self._thread.join(timeout)

    def _post_once(self, body: bytes) -> None:
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            r.read()

    def _run(self) -> None:
        while True:
            rec = self._q.get()
            if rec is None:
                return
            body = rec.to_json().encode()
            for attempt in range(self.retries):
                try:
                    self._post_once(body)
                    break
                except Exception as e:
                    if attempt == self.retries - 1:
                        self._record_drop(
                            f"POST failed {self.retries}x, last: "
                            f"{type(e).__name__}")
                    else:
                        # bounded exponential backoff between attempts;
                        # the bounded queue absorbs the stall (overflow
                        # sheds with its own counter, never blocks)
                        time.sleep(self.backoff * (2 ** attempt))
