"""UI component DSL: JSON-serializable charts/tables/text.

Reference: `deeplearning4j-ui-components` (SURVEY §2.7, 2,163 LoC —
`ui/components/chart/Chart*.java`, `table/ComponentTable.java`,
`text/ComponentText.java`) — declarative components a listener or report
builder assembles, serialized as JSON, rendered by the front end. Here the
renderer is `render_html`: a self-contained page with inline SVG (zero
external assets — mirrors `EvaluationTools`' standalone HTML export).
"""
from __future__ import annotations

import html
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

_REGISTRY: Dict[str, type] = {}


def _register(cls):
    _REGISTRY[cls.TYPE] = cls
    return cls


@dataclass
class Component:
    TYPE = "component"

    def to_dict(self) -> dict:
        d = {k: v for k, v in self.__dict__.items()}
        d["type"] = self.TYPE
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @staticmethod
    def from_dict(d: dict) -> "Component":
        d = dict(d)
        t = d.pop("type")
        cls = _REGISTRY[t]
        obj = cls(**d)
        return obj

    @staticmethod
    def from_json(s: str) -> "Component":
        return Component.from_dict(json.loads(s))

    def _svg(self) -> str:
        raise NotImplementedError


_COLORS = ["#1f77b4", "#d62728", "#2ca02c", "#9467bd", "#ff7f0e", "#17becf"]
_W, _H, _PAD = 720, 300, 40


def _scale(vals, lo, hi, out_lo, out_hi):
    span = max(hi - lo, 1e-12)
    return [out_lo + (v - lo) / span * (out_hi - out_lo) for v in vals]


def _axes(title: str, xlo, xhi, ylo, yhi) -> str:
    fmt = lambda v: f"{v:.4g}"
    return (
        f'<text x="{_W / 2}" y="16" text-anchor="middle" '
        f'font-size="13">{html.escape(title)}</text>'
        f'<line x1="{_PAD}" y1="{_H - _PAD}" x2="{_W - _PAD}" '
        f'y2="{_H - _PAD}" stroke="#333"/>'
        f'<line x1="{_PAD}" y1="{_PAD}" x2="{_PAD}" y2="{_H - _PAD}" '
        f'stroke="#333"/>'
        f'<text x="{_PAD}" y="{_H - _PAD + 14}" font-size="10">{fmt(xlo)}</text>'
        f'<text x="{_W - _PAD}" y="{_H - _PAD + 14}" text-anchor="end" '
        f'font-size="10">{fmt(xhi)}</text>'
        f'<text x="{_PAD - 4}" y="{_H - _PAD}" text-anchor="end" '
        f'font-size="10">{fmt(ylo)}</text>'
        f'<text x="{_PAD - 4}" y="{_PAD + 4}" text-anchor="end" '
        f'font-size="10">{fmt(yhi)}</text>')


@_register
@dataclass
class ChartLine(Component):
    """Multi-series line chart (reference `ChartLine.java`)."""

    TYPE = "chart_line"
    title: str = ""
    series_names: List[str] = field(default_factory=list)
    x: List[List[float]] = field(default_factory=list)
    y: List[List[float]] = field(default_factory=list)

    def add_series(self, name: str, xs: Sequence[float],
                   ys: Sequence[float]) -> "ChartLine":
        self.series_names.append(name)
        self.x.append([float(v) for v in xs])
        self.y.append([float(v) for v in ys])
        return self

    def _svg(self) -> str:
        allx = [v for s in self.x for v in s] or [0.0, 1.0]
        ally = [v for s in self.y for v in s] or [0.0, 1.0]
        xlo, xhi, ylo, yhi = min(allx), max(allx), min(ally), max(ally)
        parts = [_axes(self.title, xlo, xhi, ylo, yhi)]
        for i, (xs, ys) in enumerate(zip(self.x, self.y)):
            if len(xs) < 2:
                continue
            px = _scale(xs, xlo, xhi, _PAD, _W - _PAD)
            py = _scale(ys, ylo, yhi, _H - _PAD, _PAD)
            pts = " ".join(f"{a:.1f},{b:.1f}" for a, b in zip(px, py))
            color = _COLORS[i % len(_COLORS)]
            parts.append(f'<polyline points="{pts}" fill="none" '
                         f'stroke="{color}" stroke-width="1.5"/>')
            parts.append(f'<text x="{_W - _PAD + 4}" y="{_PAD + 14 * i + 10}" '
                         f'font-size="10" fill="{color}">'
                         f'{html.escape(self.series_names[i])}</text>')
        return (f'<svg width="{_W}" height="{_H}" '
                f'xmlns="http://www.w3.org/2000/svg">' + "".join(parts)
                + "</svg>")


@_register
@dataclass
class ChartScatter(Component):
    """Scatter chart (reference `ChartScatter.java`)."""

    TYPE = "chart_scatter"
    title: str = ""
    series_names: List[str] = field(default_factory=list)
    x: List[List[float]] = field(default_factory=list)
    y: List[List[float]] = field(default_factory=list)
    point_labels: List[Optional[List[str]]] = field(default_factory=list)

    def add_series(self, name, xs, ys, labels=None) -> "ChartScatter":
        self.series_names.append(name)
        self.x.append([float(v) for v in xs])
        self.y.append([float(v) for v in ys])
        self.point_labels.append(None if labels is None
                                 else [str(l) for l in labels])
        return self

    def _svg(self) -> str:
        allx = [v for s in self.x for v in s] or [0.0, 1.0]
        ally = [v for s in self.y for v in s] or [0.0, 1.0]
        xlo, xhi, ylo, yhi = min(allx), max(allx), min(ally), max(ally)
        parts = [_axes(self.title, xlo, xhi, ylo, yhi)]
        for i, (xs, ys) in enumerate(zip(self.x, self.y)):
            px = _scale(xs, xlo, xhi, _PAD, _W - _PAD)
            py = _scale(ys, ylo, yhi, _H - _PAD, _PAD)
            color = _COLORS[i % len(_COLORS)]
            parts.extend(f'<circle cx="{a:.1f}" cy="{b:.1f}" r="2.5" '
                         f'fill="{color}" fill-opacity="0.7"/>'
                         for a, b in zip(px, py))
            labels = (self.point_labels[i]
                      if i < len(self.point_labels) else None)
            if labels:
                parts.extend(
                    f'<text x="{a + 4:.1f}" y="{b - 3:.1f}" font-size="9" '
                    f'fill="#444">{html.escape(l)}</text>'
                    for a, b, l in zip(px, py, labels) if l)
        return (f'<svg width="{_W}" height="{_H}" '
                f'xmlns="http://www.w3.org/2000/svg">' + "".join(parts)
                + "</svg>")


@_register
@dataclass
class ChartHistogram(Component):
    """Histogram chart (reference `ChartHistogram.java`): explicit bin
    edges + counts."""

    TYPE = "chart_histogram"
    title: str = ""
    lower: List[float] = field(default_factory=list)
    upper: List[float] = field(default_factory=list)
    counts: List[float] = field(default_factory=list)

    def add_bin(self, lower: float, upper: float, count: float) -> "ChartHistogram":
        self.lower.append(float(lower))
        self.upper.append(float(upper))
        self.counts.append(float(count))
        return self

    def _svg(self) -> str:
        if not self.counts:
            return f'<svg width="{_W}" height="{_H}"></svg>'
        xlo, xhi = min(self.lower), max(self.upper)
        yhi = max(self.counts)
        parts = [_axes(self.title, xlo, xhi, 0.0, yhi)]
        for lo, up, c in zip(self.lower, self.upper, self.counts):
            x0 = _scale([lo], xlo, xhi, _PAD, _W - _PAD)[0]
            x1 = _scale([up], xlo, xhi, _PAD, _W - _PAD)[0]
            y = _scale([c], 0.0, yhi, _H - _PAD, _PAD)[0]
            parts.append(f'<rect x="{x0:.1f}" y="{y:.1f}" '
                         f'width="{max(x1 - x0 - 1, 1):.1f}" '
                         f'height="{_H - _PAD - y:.1f}" fill="#1f77b4" '
                         f'fill-opacity="0.8"/>')
        return (f'<svg width="{_W}" height="{_H}" '
                f'xmlns="http://www.w3.org/2000/svg">' + "".join(parts)
                + "</svg>")


@_register
@dataclass
class ComponentTable(Component):
    """Table (reference `table/ComponentTable.java`)."""

    TYPE = "table"
    header: List[str] = field(default_factory=list)
    rows: List[List[str]] = field(default_factory=list)

    def _svg(self) -> str:  # tables render as HTML, not SVG
        head = "".join(f"<th>{html.escape(str(h))}</th>" for h in self.header)
        body = "".join(
            "<tr>" + "".join(f"<td>{html.escape(str(c))}</td>" for c in row)
            + "</tr>" for row in self.rows)
        return (f'<table border="1" cellpadding="4" cellspacing="0">'
                f"<tr>{head}</tr>{body}</table>")


@_register
@dataclass
class ComponentText(Component):
    """Text block (reference `text/ComponentText.java`)."""

    TYPE = "text"
    text: str = ""

    def _svg(self) -> str:
        return f"<p>{html.escape(self.text)}</p>"


@_register
@dataclass
class ComponentDiv(Component):
    """Container of components (reference `ComponentDiv.java`)."""

    TYPE = "div"
    components: List = field(default_factory=list)

    def add(self, c: Component) -> "ComponentDiv":
        # store the OBJECT: mutations after add() (the builder API invites
        # them) must be visible in the rendered/serialized output
        self.components.append(c)
        return self

    def _children(self) -> List[Component]:
        return [c if isinstance(c, Component) else Component.from_dict(c)
                for c in self.components]

    def to_dict(self) -> dict:
        return {"type": self.TYPE,
                "components": [c.to_dict() for c in self._children()]}

    def _svg(self) -> str:
        return "".join(c._svg() for c in self._children())


def render_html(component: Component, title: str = "deeplearning4j_tpu report",
                refresh_seconds: int = 0) -> str:
    """Standalone HTML document for a component tree (the
    `EvaluationTools.exportevaluation`-style artifact). `refresh_seconds`
    > 0 adds a meta-refresh so server-rendered dashboard pages update
    during a running fit (the Play UI's pages poll; meta-refresh is the
    zero-asset equivalent)."""
    refresh = int(refresh_seconds)  # gate on the NORMALIZED value: 0.5
    # would pass a raw >0 check but render content="0" (instant reload)
    meta = (f'<meta http-equiv="refresh" content="{refresh}">'
            if refresh > 0 else "")
    return (f"<!DOCTYPE html><html><head>{meta}"
            f"<title>{html.escape(title)}</title>"
            f"<style>body{{font-family:sans-serif;margin:2em}}"
            f"table{{border-collapse:collapse}}</style></head>"
            f"<body>{component._svg()}</body></html>")
