"""StatsListener: training telemetry capture (reference
`deeplearning4j-ui-model/.../ui/stats/BaseStatsListener.java:273`
`iterationDone` — score, timings, memory, param/gradient/update histograms
and mean magnitudes, learning rates — encoded there via Agrona SBE
flyweights; here as plain JSON records into a StatsStorageRouter).

Device note: histogram/magnitude summaries pull parameters to host, so they
run every `report_frequency` iterations only (score/timing is free — it is
already host-side after the jitted step)."""
from __future__ import annotations

import time
import uuid
from typing import Any, Dict, Optional

import numpy as np

from deeplearning4j_tpu.ui.storage import StatsRecord, StatsStorageRouter


def _array_stats(arr, n_bins: int = 20) -> Dict[str, Any]:
    a = np.asarray(arr).ravel()
    if a.size == 0:
        return {}
    hist, edges = np.histogram(a, bins=n_bins)
    return {
        "mean_magnitude": float(np.mean(np.abs(a))),
        "mean": float(np.mean(a)),
        "stdev": float(np.std(a)),
        "min": float(np.min(a)),
        "max": float(np.max(a)),
        "histogram_counts": hist.tolist(),
        "histogram_min": float(edges[0]),
        "histogram_max": float(edges[-1]),
    }


def _system_stats() -> Dict[str, Any]:
    """Host + device memory snapshot (the reference's system page feeds:
    JVM memory + GC via JMX, `BaseStatsListener.java:356-370`; here process
    RSS + TPU HBM usage via the PJRT memory stats when exposed)."""
    out: Dict[str, Any] = {}
    try:
        # current RSS, not ru_maxrss: the peak can only grow, which would
        # hide exactly the leak-vs-plateau signal this page exists to show
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    out["host_rss_mb"] = float(line.split()[1]) / 1024.0
                    break
    except OSError:
        try:
            import resource
            import sys

            peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            # ru_maxrss is KiB on Linux but BYTES on macOS
            scale = 1024.0 * 1024.0 if sys.platform == "darwin" else 1024.0
            out["host_rss_peak_mb"] = peak / scale
        except Exception:
            pass
    try:
        import jax

        ms = jax.local_devices()[0].memory_stats()
        if ms:
            out["device_bytes_in_use"] = int(ms.get("bytes_in_use", 0))
            out["device_bytes_limit"] = int(ms.get("bytes_limit", 0))
    except Exception:
        pass  # CPU backend / no memory_stats: host stats only
    try:
        import gc

        # collector activity per report window (the reference records GC
        # count/time deltas via JMX, `BaseStatsListener.java:356-370`; the
        # CPython analogue is cycle-collector runs per generation — a
        # rising gen-2 rate during fit() flags host-side churn)
        stats = gc.get_stats()  # ONE snapshot: both series must agree
        out["gc_collections"] = [s["collections"] for s in stats]
        out["gc_collected"] = [s["collected"] for s in stats]
    except Exception:
        pass
    return out


class StatsListener:
    """Attach with `net.set_listeners(StatsListener(storage))`."""

    def __init__(self, router: StatsStorageRouter,
                 report_frequency: int = 1,
                 session_id: Optional[str] = None,
                 worker_id: str = "worker-0",
                 collect_histograms: bool = True):
        self.router = router
        self.report_frequency = max(1, report_frequency)
        self.session_id = session_id or f"session-{uuid.uuid4().hex[:12]}"
        self.worker_id = worker_id
        self.collect_histograms = collect_histograms
        self._last_time: Optional[float] = None
        self._examples = 0
        self._static_sent = False

    # listener SPI ----------------------------------------------------------
    def record_batch(self, n_examples: int) -> None:
        self._examples += n_examples

    def iteration_done(self, model, iteration: int) -> None:
        if not self._static_sent:
            self._send_static(model)
        if iteration % self.report_frequency != 0:
            return
        now = time.time()
        dt_ms = ((now - self._last_time) * 1000.0 / self.report_frequency
                 if self._last_time is not None else None)
        self._last_time = now
        data: Dict[str, Any] = {
            "iteration": iteration,
            "score": model.score_value,
            "iteration_ms": dt_ms,
            "examples_seen": self._examples,
        }
        if self.collect_histograms and getattr(model, "_params", None) is not None:
            params: Dict[str, Any] = {}
            for i, p in enumerate(self._named_params(model)):
                name, arr = p
                params[name] = _array_stats(arr)
            data["parameters"] = params
        data["system"] = _system_stats()
        self.router.put_record(StatsRecord(
            session_id=self.session_id, type_id="stats",
            worker_id=self.worker_id, timestamp=now, data=data))

    def on_epoch_start(self, model) -> None:
        pass

    def on_epoch_end(self, model) -> None:
        pass

    # helpers ---------------------------------------------------------------
    def _named_params(self, model):
        ps = model._params
        if isinstance(ps, dict):  # ComputationGraph: name → {param: arr}
            for vname, d in ps.items():
                for pname, arr in d.items():
                    yield f"{vname}_{pname}", arr
        else:                     # MultiLayerNetwork: list of dicts
            for i, d in enumerate(ps):
                for pname, arr in d.items():
                    yield f"{i}_{pname}", arr

    def _send_static(self, model) -> None:
        """Session metadata (reference sends model config/class/param count
        as the init report)."""
        self._static_sent = True
        try:
            n_params = int(model.num_params())
        except Exception:
            n_params = -1
        self.router.put_record(StatsRecord(
            session_id=self.session_id, type_id="static_info",
            worker_id=self.worker_id, timestamp=time.time(),
            data={"model_class": type(model).__name__,
                  "n_params": n_params,
                  "n_layers": len(getattr(model, "layers", []) or [])}))
