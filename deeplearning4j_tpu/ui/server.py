"""UI web server (reference `deeplearning4j-play/.../PlayUIServer.java:51`:
`UIServer.getInstance().attach(statsStorage)`, default port 9000, train
module pages `module/train/TrainModule.java:53` overview/model/system +
remote receiver `RemoteReceiverModule`).

Implemented on the stdlib ThreadingHTTPServer: JSON endpoints + a
self-contained HTML dashboard (inline SVG chart, no external assets — the
container has zero egress)."""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from deeplearning4j_tpu.ui.storage import StatsRecord, StatsStorage

# shared chart + poll scaffolding, interpolated into every live page so
# a fix lands once (the doubled-brace bug had to be fixed twice before)
_CHART_JS = """
function poly(svg, xs, ys, color) {
  if (xs.length < 2) return;
  const W = svg.clientWidth || 800, H = svg.clientHeight || 300, pad = 30;
  const xmin = Math.min(...xs), xmax = Math.max(...xs);
  const ymin = Math.min(...ys), ymax = Math.max(...ys);
  const sx = x => pad + (x - xmin) / Math.max(xmax - xmin, 1e-9) * (W - 2 * pad);
  const sy = y => H - pad - (y - ymin) / Math.max(ymax - ymin, 1e-9) * (H - 2 * pad);
  const pts = xs.map((x, i) => sx(x) + ',' + sy(ys[i])).join(' ');
  const p = document.createElementNS('http://www.w3.org/2000/svg', 'polyline');
  p.setAttribute('points', pts);
  p.setAttribute('fill', 'none');
  p.setAttribute('stroke', color);
  svg.appendChild(p);
}
const COLORS = ['#d62728', '#2ca02c', '#9467bd', '#ff7f0e', '#17becf',
                '#1f77b4', '#8c564b', '#e377c2'];
"""

_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu training UI</title>
<style>
 body { font-family: sans-serif; margin: 2em; }
 .chart { border: 1px solid #ccc; margin-bottom: 1.5em; }
 h2 { margin-bottom: 0.2em; }
</style></head>
<body>
<h1>Training overview</h1>
<div id="meta"></div>
<h2>Score vs iteration</h2>
<svg id="score" class="chart" width="800" height="300"></svg>
<h2>Parameter mean magnitudes</h2>
<svg id="params" class="chart" width="800" height="300"></svg>
<script>""" + _CHART_JS + """
async function refresh() {
  const r = await fetch('/train/overview/data');
  const d = await r.json();
  document.getElementById('meta').textContent =
    'session: ' + d.session_id + '  iterations: ' + d.iterations.length;
  const svg = document.getElementById('score');
  svg.innerHTML = '';
  poly(svg, d.iterations, d.scores, '#1f77b4');
  const ps = document.getElementById('params');
  ps.innerHTML = '';
  let ci = 0;
  for (const [name, series] of Object.entries(d.param_mean_magnitudes)) {
    poly(ps, d.iterations.slice(-series.length), series, COLORS[ci++ % COLORS.length]);
  }
}
refresh(); setInterval(refresh, 5000);
</script>
</body></html>
"""


_MODEL_PAGE = """<!DOCTYPE html>
<html><head><title>deeplearning4j_tpu model</title>
<style>
 body { font-family: sans-serif; margin: 2em; }
 .chart { border: 1px solid #ccc; margin-bottom: 1.5em; }
 table { border-collapse: collapse; margin-bottom: 1.5em; }
 td, th { border: 1px solid #ccc; padding: 4px 10px; text-align: right; }
 th { background: #f4f4f4; }
 td:first-child, th:first-child { text-align: left; }
</style></head>
<body>
<h1>Model</h1>
<div id="meta"></div>
<h2>Parameter table (latest iteration)</h2>
<table id="ptable"><thead><tr><th>parameter</th><th>mean</th>
<th>stdev</th><th>mean |w|</th></tr></thead><tbody></tbody></table>
<h2>Mean |w| vs iteration (per parameter)</h2>
<svg id="pchart" class="chart" width="800" height="300"></svg>
<div id="legend"></div>
<script>""" + _CHART_JS + """
function cell(row, text) {
  const td = document.createElement('td');
  td.textContent = text;     // names come from untrusted remote stats
  row.appendChild(td);       // records: textContent, never innerHTML
}
async function refresh() {
  const r = await fetch('/train/model/data');
  const d = await r.json();
  document.getElementById('meta').textContent =
    'session: ' + d.session_id + '  model: ' + (d.static.model_class || '?')
    + '  params: ' + (d.static.n_params || '?')
    + '  iteration: ' + d.latest_iteration;
  const tb = document.querySelector('#ptable tbody');
  tb.innerHTML = '';
  const svg = document.getElementById('pchart');
  svg.innerHTML = '';
  const legend = document.getElementById('legend');
  legend.innerHTML = '';
  let ci = 0;
  for (const [name, s] of Object.entries(d.params)) {
    const last = i => (s[i] && s[i].length ? s[i][s[i].length - 1] : NaN);
    const row = document.createElement('tr');
    cell(row, name);
    cell(row, Number(last('mean')).toPrecision(4));
    cell(row, Number(last('stdev')).toPrecision(4));
    cell(row, Number(last('mean_magnitude')).toPrecision(4));
    tb.appendChild(row);
    const color = COLORS[ci++ % COLORS.length];
    poly(svg, d.iterations.slice(-s.mean_magnitude.length),
         s.mean_magnitude, color);
    const span = document.createElement('span');
    span.style.color = color;
    span.textContent = '\u25A0 ' + name + '  ';
    legend.appendChild(span);
  }
}
refresh(); setInterval(refresh, 3000);
</script>
</body></html>
"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4jtpu-ui/1.0"

    def log_message(self, fmt, *args):  # quiet
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _html(self, text: str):
        body = text.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- GET ----------------------------------------------------------------
    def do_GET(self):
        ui: "UIServer" = self.server.ui  # type: ignore[attr-defined]
        if self.path in ("/", "/train", "/train/overview"):
            return self._html(_PAGE)
        if self.path == "/train/overview/data":
            return self._json(ui._overview_data())
        if self.path == "/train/sessions":
            return self._json({"sessions": ui._session_ids()})
        if self.path == "/train/model":
            return self._json(ui._model_data())
        if self.path == "/train/model/page":
            return self._html(_MODEL_PAGE)
        if self.path == "/train/model/data":
            return self._json(ui._model_series())
        if self.path == "/train/system":
            return self._json(ui._system_data())
        if self.path == "/train/histograms":
            return self._json(ui._histogram_data())
        if self.path == "/train/histograms/page":
            return self._html(ui._histogram_page())
        if self.path == "/tsne":
            return self._html(ui._tsne_page())
        if self.path == "/tsne/data":
            return self._json(ui._tsne)
        if self.path == "/train/flow":
            return self._html(ui._flow_page())
        if self.path == "/train/activations":
            return self._html(ui._activations_page())
        return self._json({"error": f"unknown path {self.path}"}, 404)

    # -- POST (remote stats receiver + tsne upload) -------------------------
    def do_POST(self):
        ui: "UIServer" = self.server.ui  # type: ignore[attr-defined]
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        if self.path == "/remote/receive":
            try:
                rec = StatsRecord.from_json(body.decode())
            except Exception as e:  # malformed post
                return self._json({"error": str(e)}, 400)
            if ui._storages:
                ui._storages[0].put_record(rec)
                return self._json({"ok": True})
            return self._json({"error": "no storage attached"}, 503)
        if self.path == "/tsne/upload":
            # {"coords": [[x, y], ...], "labels": ["word", ...]}
            try:
                payload = json.loads(body)
                coords = [[float(c[0]), float(c[1])] for c in payload["coords"]]
                labels = payload.get("labels") or [""] * len(coords)
                if len(labels) != len(coords):
                    raise ValueError("labels/coords length mismatch")
                labels = [str(l) for l in labels]
            except Exception as e:
                return self._json({"error": f"bad upload: {e}"}, 400)
            ui._tsne = {"coords": coords, "labels": labels}
            return self._json({"ok": True, "points": len(coords)})
        return self._json({"error": f"unknown path {self.path}"}, 404)


class UIServer:
    """`UIServer().attach(storage)` then browse http://localhost:<port>/
    (reference `PlayUIServer.attach:247`; default port 9000 as at `:58`)."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000):
        self._storages: List[StatsStorage] = []
        self._tsne: dict = {"coords": [], "labels": []}
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.ui = self  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @classmethod
    def get_instance(cls, port: int = 9000) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer(port)
        return cls._instance

    def attach(self, storage: StatsStorage) -> None:
        if storage not in self._storages:
            self._storages.append(storage)

    def detach(self, storage: StatsStorage) -> None:
        if storage in self._storages:
            self._storages.remove(storage)

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if UIServer._instance is self:
            UIServer._instance = None

    # -- data assembly ------------------------------------------------------
    def _session_ids(self) -> List[str]:
        out: List[str] = []
        for s in self._storages:
            out.extend(s.list_session_ids())
        return sorted(set(out))

    def _latest_session(self):
        for s in self._storages:
            ids = s.list_session_ids()
            if ids:
                return s, ids[-1]
        return None, None

    def _overview_data(self):
        storage, sid = self._latest_session()
        if storage is None:
            return {"session_id": None, "iterations": [], "scores": [],
                    "param_mean_magnitudes": {}}
        recs = storage.get_records(sid, type_id="stats")
        iterations = [r.data.get("iteration") for r in recs]
        scores = [r.data.get("score") for r in recs]
        pmm: dict = {}
        for r in recs:
            for name, st in (r.data.get("parameters") or {}).items():
                pmm.setdefault(name, []).append(st.get("mean_magnitude"))
        return {"session_id": sid, "iterations": iterations, "scores": scores,
                "param_mean_magnitudes": pmm}

    def _model_data(self):
        storage, sid = self._latest_session()
        if storage is None:
            return {"session_id": None}
        static = storage.get_records(sid, type_id="static_info")
        latest = storage.get_latest_record(sid, type_id="stats")
        return {"session_id": sid,
                "static": static[-1].data if static else {},
                "latest": latest.data if latest else {}}

    def _model_series(self):
        """Model-page feed: static info + full per-parameter stat series
        (the reference TrainModule model tab's per-layer charts)."""
        storage, sid = self._latest_session()
        if storage is None:
            return {"session_id": None, "static": {}, "iterations": [],
                    "params": {}, "latest_iteration": None}
        static = storage.get_records(sid, type_id="static_info")
        recs = storage.get_records(sid, type_id="stats")
        iterations = [r.data.get("iteration") for r in recs]
        params: dict = {}
        for r in recs:
            for name, st in (r.data.get("parameters") or {}).items():
                slot = params.setdefault(
                    name, {"mean": [], "stdev": [], "mean_magnitude": []})
                for k in slot:
                    slot[k].append(st.get(k))
        return {"session_id": sid,
                "static": static[-1].data if static else {},
                "iterations": iterations, "params": params,
                "latest_iteration": iterations[-1] if iterations else None}

    def _system_data(self):
        """System page feed (reference TrainModule system tab: JVM/GC; here
        host RSS + device HBM per iteration)."""
        storage, sid = self._latest_session()
        if storage is None:
            return {"session_id": None, "iterations": [], "host_rss_mb": [],
                    "device_bytes_in_use": [], "gc_gen2_collections": []}
        recs = storage.get_records(sid, type_id="stats")
        out = {"session_id": sid, "iterations": [], "host_rss_mb": [],
               "device_bytes_in_use": [], "gc_gen2_collections": []}
        for r in recs:
            sysd = r.data.get("system") or {}
            out["iterations"].append(r.data.get("iteration"))
            # non-procfs platforms record peak RSS instead of current
            out["host_rss_mb"].append(sysd.get("host_rss_mb",
                                               sysd.get("host_rss_peak_mb")))
            out["device_bytes_in_use"].append(sysd.get("device_bytes_in_use"))
            gens = sysd.get("gc_collections")
            # gen-2 cumulative count — the reference system tab's GC trace
            out["gc_gen2_collections"].append(gens[-1] if gens else None)
        return out

    def _histogram_data(self):
        """Latest per-parameter histograms (reference HistogramModule)."""
        storage, sid = self._latest_session()
        latest = (storage.get_latest_record(sid, type_id="stats")
                  if storage else None)
        if latest is None:
            return {"session_id": None, "parameters": {}}
        params = {
            name: {k: st.get(k) for k in
                   ("histogram_counts", "histogram_min", "histogram_max",
                    "mean", "stdev")}
            for name, st in (latest.data.get("parameters") or {}).items()}
        return {"session_id": sid, "iteration": latest.data.get("iteration"),
                "parameters": params}

    def _histogram_page(self) -> str:
        from deeplearning4j_tpu.ui.components import ChartHistogram, ComponentDiv, render_html

        d = self._histogram_data()
        div = ComponentDiv()
        for name, st in d["parameters"].items():
            counts = st.get("histogram_counts") or []
            if not counts:
                continue
            lo, hi = st.get("histogram_min", 0.0), st.get("histogram_max", 1.0)
            width = (hi - lo) / max(len(counts), 1)
            ch = ChartHistogram(title=f"{name} (iter {d.get('iteration')})")
            for i, c in enumerate(counts):
                ch.add_bin(lo + i * width, lo + (i + 1) * width, c)
            div.add(ch)
        return render_html(div, title="parameter histograms",
                           refresh_seconds=5)

    def _latest_of_type(self, type_id: str):
        """Most recent record of a type across all sessions/storages (flow
        and activation listeners run under their own session ids)."""
        best = None
        for storage in self._storages:
            for sid in storage.list_session_ids():
                rec = storage.get_latest_record(sid, type_id=type_id)
                if rec is not None and (best is None
                                        or rec.timestamp > best.timestamp):
                    best = rec
        return best

    def _flow_page(self) -> str:
        from deeplearning4j_tpu.ui.flow import render_flow_svg

        rec = self._latest_of_type("flow")
        nodes = rec.data.get("nodes", []) if rec else []
        body = render_flow_svg(nodes) if nodes else "<p>no flow captured</p>"
        return ("<!DOCTYPE html><html><head><title>network flow</title>"
                "</head><body><h1>Network flow</h1>" + body + "</body></html>")

    def _activations_page(self) -> str:
        from deeplearning4j_tpu.ui.flow import render_activation_svg

        rec = self._latest_of_type("activations")
        if rec is None:
            body = "<p>no activations captured</p>"
        else:
            body = (f"<p>iteration {rec.data.get('iteration')}</p>"
                    + render_activation_svg(rec.data.get("channels", [])))
        return ("<!DOCTYPE html><html><head><title>activations</title>"
                "</head><body><h1>Conv activations</h1>" + body
                + "</body></html>")

    def _tsne_page(self) -> str:
        from deeplearning4j_tpu.ui.components import ChartScatter, render_html

        coords = self._tsne.get("coords") or []
        chart = ChartScatter(title=f"t-SNE ({len(coords)} points)")
        if coords:
            chart.add_series("points", [c[0] for c in coords],
                             [c[1] for c in coords],
                             labels=self._tsne.get("labels"))
        return render_html(chart, title="t-SNE", refresh_seconds=10)
