"""StatsStorage: pub/sub persistence for training stats (reference
`deeplearning4j-core/.../api/storage/StatsStorage.java`,
`StatsStorageRouter.java`, `Persistable.java`; backends
`deeplearning4j-ui-model/.../ui/storage/InMemoryStatsStorage.java` and
`FileStatsStorage.java` (MapDB) — the file backend here is append-only
JSONL, which serves the same durability role without a MapDB dependency)."""
from __future__ import annotations

import json
import threading
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Union


@dataclass
class StatsRecord:
    """One persisted stats update (reference `Persistable` +
    `StatsReport`): arbitrary JSON-serializable `data`."""

    session_id: str
    type_id: str          # e.g. 'stats', 'static_info'
    worker_id: str
    timestamp: float
    data: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @staticmethod
    def from_json(s: str) -> "StatsRecord":
        return StatsRecord(**json.loads(s))


class StatsStorageRouter:
    """Write-side interface (reference `StatsStorageRouter.java`)."""

    def put_record(self, record: StatsRecord) -> None:
        raise NotImplementedError


class StatsStorage(StatsStorageRouter):
    """Read+write+listen interface (reference `StatsStorage.java`)."""

    def __init__(self) -> None:
        self._listeners: List[Callable[[StatsRecord], None]] = []
        self._lock = threading.Lock()

    # -- write --------------------------------------------------------------
    def put_record(self, record: StatsRecord) -> None:
        with self._lock:
            self._store(record)
        for cb in list(self._listeners):
            cb(record)

    def _store(self, record: StatsRecord) -> None:
        raise NotImplementedError

    # -- read ---------------------------------------------------------------
    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def list_workers(self, session_id: str) -> List[str]:
        return sorted({r.worker_id for r in self.get_records(session_id)})

    def get_records(self, session_id: str,
                    type_id: Optional[str] = None,
                    worker_id: Optional[str] = None) -> List[StatsRecord]:
        raise NotImplementedError

    def get_latest_record(self, session_id: str,
                          type_id: Optional[str] = None) -> Optional[StatsRecord]:
        recs = self.get_records(session_id, type_id)
        return recs[-1] if recs else None

    # -- listen -------------------------------------------------------------
    def register_stats_listener(self, cb: Callable[[StatsRecord], None]) -> None:
        self._listeners.append(cb)

    def deregister_stats_listener(self, cb: Callable[[StatsRecord], None]) -> None:
        if cb in self._listeners:
            self._listeners.remove(cb)


class InMemoryStatsStorage(StatsStorage):
    """Reference `ui/storage/InMemoryStatsStorage.java`."""

    def __init__(self) -> None:
        super().__init__()
        self._records: List[StatsRecord] = []

    def _store(self, record: StatsRecord) -> None:
        self._records.append(record)

    def list_session_ids(self) -> List[str]:
        return sorted({r.session_id for r in self._records})

    def get_records(self, session_id: str, type_id: Optional[str] = None,
                    worker_id: Optional[str] = None) -> List[StatsRecord]:
        return [r for r in self._records
                if r.session_id == session_id
                and (type_id is None or r.type_id == type_id)
                and (worker_id is None or r.worker_id == worker_id)]


class FileStatsStorage(StatsStorage):
    """Durable append-only JSONL storage (role of
    `ui/storage/FileStatsStorage.java`); readable cross-process."""

    def __init__(self, path: Union[str, Path]):
        super().__init__()
        self._path = Path(path)
        self._path.parent.mkdir(parents=True, exist_ok=True)
        if not self._path.exists():
            self._path.touch()

    def _store(self, record: StatsRecord) -> None:
        with open(self._path, "a", encoding="utf-8") as f:
            f.write(record.to_json() + "\n")

    def _load(self) -> List[StatsRecord]:
        out = []
        for line in self._path.read_text(encoding="utf-8").splitlines():
            if line.strip():
                out.append(StatsRecord.from_json(line))
        return out

    def list_session_ids(self) -> List[str]:
        return sorted({r.session_id for r in self._load()})

    def get_records(self, session_id: str, type_id: Optional[str] = None,
                    worker_id: Optional[str] = None) -> List[StatsRecord]:
        return [r for r in self._load()
                if r.session_id == session_id
                and (type_id is None or r.type_id == type_id)
                and (worker_id is None or r.worker_id == worker_id)]
