"""StatsStorage: pub/sub persistence for training stats (reference
`deeplearning4j-core/.../api/storage/StatsStorage.java`,
`StatsStorageRouter.java`, `Persistable.java`; backends
`deeplearning4j-ui-model/.../ui/storage/InMemoryStatsStorage.java` and
`FileStatsStorage.java` (MapDB) — the file backend here is append-only
JSONL, which serves the same durability role without a MapDB dependency)."""
from __future__ import annotations

import json
import threading
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Union


@dataclass
class StatsRecord:
    """One persisted stats update (reference `Persistable` +
    `StatsReport`): arbitrary JSON-serializable `data`."""

    session_id: str
    type_id: str          # e.g. 'stats', 'static_info'
    worker_id: str
    timestamp: float
    data: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @staticmethod
    def from_json(s: str) -> "StatsRecord":
        return StatsRecord(**json.loads(s))


class StatsStorageRouter:
    """Write-side interface (reference `StatsStorageRouter.java`)."""

    def put_record(self, record: StatsRecord) -> None:
        raise NotImplementedError


class StatsStorage(StatsStorageRouter):
    """Read+write+listen interface (reference `StatsStorage.java`)."""

    def __init__(self) -> None:
        self._listeners: List[Callable[[StatsRecord], None]] = []
        self._lock = threading.Lock()

    # -- write --------------------------------------------------------------
    def put_record(self, record: StatsRecord) -> None:
        with self._lock:
            self._store(record)
        for cb in list(self._listeners):
            cb(record)

    def _store(self, record: StatsRecord) -> None:
        raise NotImplementedError

    # -- read ---------------------------------------------------------------
    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def list_workers(self, session_id: str) -> List[str]:
        return sorted({r.worker_id for r in self.get_records(session_id)})

    def get_records(self, session_id: str,
                    type_id: Optional[str] = None,
                    worker_id: Optional[str] = None) -> List[StatsRecord]:
        raise NotImplementedError

    def get_latest_record(self, session_id: str,
                          type_id: Optional[str] = None) -> Optional[StatsRecord]:
        recs = self.get_records(session_id, type_id)
        return recs[-1] if recs else None

    # -- listen -------------------------------------------------------------
    def register_stats_listener(self, cb: Callable[[StatsRecord], None]) -> None:
        self._listeners.append(cb)

    def deregister_stats_listener(self, cb: Callable[[StatsRecord], None]) -> None:
        if cb in self._listeners:
            self._listeners.remove(cb)


class InMemoryStatsStorage(StatsStorage):
    """Reference `ui/storage/InMemoryStatsStorage.java`."""

    def __init__(self) -> None:
        super().__init__()
        self._records: List[StatsRecord] = []

    def _store(self, record: StatsRecord) -> None:
        self._records.append(record)

    def list_session_ids(self) -> List[str]:
        return sorted({r.session_id for r in self._records})

    def get_records(self, session_id: str, type_id: Optional[str] = None,
                    worker_id: Optional[str] = None) -> List[StatsRecord]:
        return [r for r in self._records
                if r.session_id == session_id
                and (type_id is None or r.type_id == type_id)
                and (worker_id is None or r.worker_id == worker_id)]


class FileStatsStorage(StatsStorage):
    """Durable append-only JSONL storage (role of
    `ui/storage/FileStatsStorage.java`, which persists via MapDB);
    readable cross-process.

    Queries are served from an in-memory per-session index; only the
    bytes APPENDED since the last read are parsed on refresh (r1 re-read
    and re-parsed the whole file on every dashboard query, which falls
    over on long runs). External truncation/rotation triggers a rebuild."""

    def __init__(self, path: Union[str, Path]):
        super().__init__()
        self._path = Path(path)
        self._path.parent.mkdir(parents=True, exist_ok=True)
        if not self._path.exists():
            self._path.touch()
        self._offset = 0                      # bytes fully parsed so far
        self._by_session: dict = {}           # session_id -> [records]

    def _store(self, record: StatsRecord) -> None:
        with open(self._path, "ab") as f:
            f.write((record.to_json() + "\n").encode("utf-8"))

    def _refresh(self) -> None:
        # the UI serves queries from ThreadingHTTPServer handler threads:
        # index mutation must hold the same lock as writes, or concurrent
        # refreshes double-append and push _offset past EOF
        with self._lock:
            size = self._path.stat().st_size
            if size < self._offset:
                # truncated or rotated externally: rebuild from scratch
                self._offset = 0
                self._by_session = {}
            if size == self._offset:
                return
            with open(self._path, "rb") as f:
                f.seek(self._offset)
                chunk = f.read()
            # consume only COMPLETE lines — a writer may be mid-line
            end = chunk.rfind(b"\n") + 1
            parsed = []
            for line in chunk[:end].decode("utf-8").splitlines():
                if not line.strip():
                    continue
                try:
                    parsed.append(StatsRecord.from_json(line))
                except Exception:
                    # a corrupt line (crashed writer) is skipped, not
                    # retried forever: parse the whole chunk BEFORE
                    # mutating the index, then advance past it
                    import logging

                    logging.getLogger("deeplearning4j_tpu").warning(
                        "FileStatsStorage: skipping malformed record in %s",
                        self._path)
            for r in parsed:
                self._by_session.setdefault(r.session_id, []).append(r)
            self._offset += end

    def list_session_ids(self) -> List[str]:
        self._refresh()
        return sorted(self._by_session)

    def get_records(self, session_id: str, type_id: Optional[str] = None,
                    worker_id: Optional[str] = None) -> List[StatsRecord]:
        self._refresh()
        return [r for r in self._by_session.get(session_id, [])
                if (type_id is None or r.type_id == type_id)
                and (worker_id is None or r.worker_id == worker_id)]
