"""Flow + convolutional activation listeners.

Reference (SURVEY §2.7): `ui/flow/FlowIterationListener.java` (legacy
Dropwizard UI — network-structure flow chart with per-layer info) and
`ConvolutionalListenerModule` (activation images for conv layers). Both
capture into the same StatsStorage stream the train modules use; the
server renders them at /train/flow and /train/activations as standalone
SVG (no image codecs in this environment — activations render as SVG
heatmap cells).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.ui.storage import StatsRecord, StatsStorageRouter


class FlowListener:
    """Captures the network structure once per session (reference
    `FlowIterationListener.java`): layer index/name/type/shape chain."""

    def __init__(self, router: StatsStorageRouter,
                 session_id: str = "flow-session"):
        self.router = router
        self.session_id = session_id
        self._sent = False

    def iteration_done(self, model, iteration: int) -> None:
        if self._sent:
            return
        self._sent = True
        nodes: List[Dict[str, Any]] = []
        layers = getattr(model, "layers", None)
        if layers:  # MultiLayerNetwork: a chain
            for i, layer in enumerate(layers):
                nodes.append({
                    "name": f"layer_{i}",
                    "type": type(layer).__name__,
                    "n_in": int(getattr(layer, "n_in", 0) or 0),
                    "n_out": int(getattr(layer, "n_out", 0) or 0),
                    "inputs": [f"layer_{i - 1}"] if i > 0 else [],
                })
        else:  # ComputationGraph: the DAG
            conf = getattr(model, "conf", None)
            for name in getattr(conf, "topological_order", []):
                node = conf.nodes[name]
                nodes.append({
                    "name": name,
                    "type": (type(node.layer).__name__ if node.is_layer
                             else type(node).__name__),
                    "n_in": 0, "n_out": 0,
                    "inputs": list(getattr(node, "inputs", [])),
                })
        self.router.put_record(StatsRecord(
            session_id=self.session_id, type_id="flow", worker_id="w0",
            timestamp=time.time(), data={"nodes": nodes}))


class ConvolutionalIterationListener:
    """Captures downsampled per-channel activation grids of the first
    convolution-shaped activation every `frequency` iterations (reference
    `ConvolutionalListenerModule` activation images)."""

    def __init__(self, router: StatsStorageRouter, frequency: int = 10,
                 session_id: str = "conv-session", max_channels: int = 8,
                 cell: int = 12):
        self.router = router
        self.frequency = max(1, frequency)
        self.session_id = session_id
        self.max_channels = max_channels
        self.cell = cell
        self._probe: Optional[np.ndarray] = None

    def record_batch(self, n: int) -> None:
        pass

    def set_probe(self, features: np.ndarray) -> None:
        """Sample inputs to visualize (first example is used)."""
        self._probe = np.asarray(features)[:1]

    def iteration_done(self, model, iteration: int) -> None:
        if self._probe is None or iteration % self.frequency != 0:
            return
        acts = model.feed_forward(self._probe)
        grids = None
        for a in acts:
            if a.ndim == 4:  # (1, H, W, C) — first conv activation
                grids = a[0]
                break
        if grids is None:
            return
        H, W, C = grids.shape
        ds = max(1, H // self.cell, W // self.cell)
        small = grids[::ds, ::ds, :self.max_channels]
        lo, hi = float(small.min()), float(small.max())
        norm = (small - lo) / max(hi - lo, 1e-9)
        self.router.put_record(StatsRecord(
            session_id=self.session_id, type_id="activations",
            worker_id="w0", timestamp=time.time(),
            data={"iteration": iteration,
                  "channels": [norm[:, :, c].tolist()
                               for c in range(norm.shape[-1])]}))


def render_flow_svg(nodes: List[Dict[str, Any]]) -> str:
    """Layer boxes + arrows (the flow chart)."""
    import html as _html

    BW, BH, GAP = 180, 46, 28
    pos = {n["name"]: i for i, n in enumerate(nodes)}
    parts = []
    for n in nodes:
        i = pos[n["name"]]
        y = 10 + i * (BH + GAP)
        label = f'{n["name"]}: {n["type"]}'
        dims = (f'{n["n_in"]}→{n["n_out"]}'
                if n.get("n_in") or n.get("n_out") else "")
        parts.append(
            f'<rect x="20" y="{y}" width="{BW}" height="{BH}" rx="6" '
            f'fill="#eef" stroke="#336"/>'
            f'<text x="{20 + BW / 2}" y="{y + 19}" text-anchor="middle" '
            f'font-size="11">{_html.escape(label)}</text>'
            f'<text x="{20 + BW / 2}" y="{y + 35}" text-anchor="middle" '
            f'font-size="10" fill="#555">{_html.escape(dims)}</text>')
        for src in n.get("inputs", []):
            if src in pos:
                sy = 10 + pos[src] * (BH + GAP) + BH
                parts.append(
                    f'<line x1="{20 + BW / 2}" y1="{sy}" x2="{20 + BW / 2}" '
                    f'y2="{y}" stroke="#336" marker-end="url(#arr)"/>')
    height = 20 + len(nodes) * (BH + GAP)
    return (f'<svg width="400" height="{height}" '
            f'xmlns="http://www.w3.org/2000/svg">'
            f'<defs><marker id="arr" markerWidth="8" markerHeight="8" '
            f'refX="6" refY="3" orient="auto"><path d="M0,0 L6,3 L0,6 z" '
            f'fill="#336"/></marker></defs>' + "".join(parts) + "</svg>")


def render_activation_svg(channels: List[List[List[float]]],
                          cell_px: int = 10) -> str:
    """Per-channel heatmap grids as SVG cells."""
    parts = []
    x0 = 0
    for grid in channels:
        h = len(grid)
        w = len(grid[0]) if h else 0
        for r in range(h):
            for c in range(w):
                v = grid[r][c]
                shade = int(255 * (1.0 - v))
                parts.append(
                    f'<rect x="{x0 + c * cell_px}" y="{r * cell_px}" '
                    f'width="{cell_px}" height="{cell_px}" '
                    f'fill="rgb({shade},{shade},255)"/>')
        x0 += (w + 1) * cell_px
    height = max((len(g) for g in channels), default=0) * cell_px
    return (f'<svg width="{x0}" height="{height}" '
            f'xmlns="http://www.w3.org/2000/svg">' + "".join(parts)
            + "</svg>")
