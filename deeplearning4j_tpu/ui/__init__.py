"""Observability / UI (reference `deeplearning4j-ui-parent/`, §2.7 of
SURVEY.md): StatsListener capture → StatsStorage (in-memory / file) →
web UI server + remote HTTP routing."""
from deeplearning4j_tpu.ui.storage import (  # noqa: F401
    FileStatsStorage,
    InMemoryStatsStorage,
    StatsRecord,
    StatsStorage,
)
from deeplearning4j_tpu.ui.stats_listener import StatsListener  # noqa: F401
from deeplearning4j_tpu.ui.server import UIServer  # noqa: F401
from deeplearning4j_tpu.ui.remote import RemoteUIStatsStorageRouter  # noqa: F401
