"""Profiling / tracing hooks.

Reference (SURVEY §5 "Tracing / profiling"): the reference has only coarse
per-phase timing (`PerformanceListener` samples/sec, Spark per-phase stats,
`BaseStatsListener` fwd/bwd wall-clock). The prescribed TPU equivalent is
"per-step timing + XLA profiler hooks; keep the listener SPI" — so:

- `ProfilerListener`: an `IterationListener` capturing per-iteration
  wall-clock (with an optional sync so timings mean device time, not
  dispatch time) and summarizing percentiles.
- `XlaTraceListener`: starts/stops a `jax.profiler` trace around a chosen
  iteration window; the dump is viewable in TensorBoard/Perfetto and shows
  the real XLA op timeline on the TPU.
- `trace_annotation`: names host-side phases so they show up in the trace.
"""
from __future__ import annotations

import logging
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import IterationListener

logger = logging.getLogger("deeplearning4j_tpu")


class ProfilerListener(IterationListener):
    """Per-iteration wall-clock capture.

    `sync=True` blocks on the model's score each iteration so an interval
    covers the device step it timed (one sync per iteration — use for
    profiling runs, not production training: it defeats step pipelining)."""

    def __init__(self, sync: bool = False, log_every: int = 0):
        self.sync = sync
        self.log_every = log_every
        self.durations_ms: List[float] = []
        self._last: Optional[float] = None

    def iteration_done(self, model, iteration: int) -> None:
        if self.sync:
            _ = model.score_value  # forces device sync (lazy score read)
        now = time.perf_counter()
        if self._last is not None:
            ms = (now - self._last) * 1000.0
            self.durations_ms.append(ms)
            if self.log_every and len(self.durations_ms) % self.log_every == 0:
                logger.info("iteration %d: %.2f ms/step (mean over last %d)",
                            iteration,
                            float(np.mean(self.durations_ms[-self.log_every:])),
                            self.log_every)
        self._last = now

    def summary(self) -> Dict[str, float]:
        if not self.durations_ms:
            return {}
        d = np.asarray(self.durations_ms)
        return {
            "iterations": int(d.size),
            "mean_ms": float(d.mean()),
            "p50_ms": float(np.percentile(d, 50)),
            "p90_ms": float(np.percentile(d, 90)),
            "p99_ms": float(np.percentile(d, 99)),
            "max_ms": float(d.max()),
        }

    def reset(self) -> None:
        self.durations_ms = []
        self._last = None


class XlaTraceListener(IterationListener):
    """Captures a `jax.profiler` trace for iterations
    [start_iteration, start_iteration + num_iterations) — the XLA-level
    view (op timeline, HBM traffic) of the compiled step."""

    def __init__(self, log_dir: str, start_iteration: int = 5,
                 num_iterations: int = 5):
        self.log_dir = log_dir
        self.start_iteration = start_iteration
        self.num_iterations = num_iterations
        self._active = False
        self.completed = False

    def iteration_done(self, model, iteration: int) -> None:
        import jax

        if (not self._active and not self.completed
                and iteration >= self.start_iteration):
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            self._until = iteration + self.num_iterations
        elif self._active and iteration >= self._until:
            # sync first so the trace includes the steps' device work
            _ = model.score_value
            jax.profiler.stop_trace()
            self._active = False
            self.completed = True
            logger.info("XLA trace written to %s (view in TensorBoard)",
                        self.log_dir)

    def stop(self) -> None:
        """Force-stop an in-flight trace (e.g. training ended early)."""
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            self.completed = True


@contextmanager
def trace_annotation(name: str):
    """Names a host-side phase in the profiler timeline (reference analogue:
    the per-phase wall-clock keys of `SparkTrainingStats`)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


@contextmanager
def trace_capture(log_dir: str):
    """Capture a `jax.profiler` trace over the with-block (the
    block-scoped sibling of `XlaTraceListener`'s iteration window —
    `bench.py --trace` wraps one timed benchmark rep in this). The
    trace always stops, even when the block raises, so an aborted
    bench never leaves the profiler armed for the next one."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("XLA trace written to %s (view in TensorBoard)",
                    log_dir)
