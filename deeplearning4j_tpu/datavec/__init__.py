"""DataVec-equivalent ETL: record readers + DataSet iterator adapters.

The reference consumes DataVec (external Java ETL library) through adapter
iterators in `deeplearning4j-core/.../datasets/datavec/` (SURVEY §2.2 / §2.9
"DataVec" row: "host-side input pipeline feeding device infeed"). This
package is the TPU build's host-side input pipeline: readers parse records
on the host (optionally via the C++ native parser), the iterator adapters
assemble padded/masked numpy batches, and `AsyncDataSetIterator` overlaps
that with device dispatch.
"""
from deeplearning4j_tpu.datavec.records import (
    CollectionRecordReader,
    CollectionSequenceRecordReader,
    CSVRecordReader,
    CSVSequenceRecordReader,
    ImageRecordReader,
    LineRecordReader,
    RecordReader,
    SequenceRecordReader,
)
from deeplearning4j_tpu.datavec.iterators import (
    AlignmentMode,
    RecordReaderDataSetIterator,
    RecordReaderMultiDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)

__all__ = [
    "AlignmentMode",
    "CollectionRecordReader",
    "CollectionSequenceRecordReader",
    "CSVRecordReader",
    "CSVSequenceRecordReader",
    "ImageRecordReader",
    "LineRecordReader",
    "RecordReader",
    "RecordReaderDataSetIterator",
    "RecordReaderMultiDataSetIterator",
    "SequenceRecordReader",
    "SequenceRecordReaderDataSetIterator",
]
