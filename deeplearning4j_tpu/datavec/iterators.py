"""RecordReader → DataSet/MultiDataSet iterator adapters.

Reference: `deeplearning4j-core/.../datasets/datavec/
RecordReaderDataSetIterator.java` (classification one-hot / regression column
ranges), `SequenceRecordReaderDataSetIterator.java` (two-reader label
alignment modes with masking), `RecordReaderMultiDataSetIterator.java`
(named readers + per-column-range subsets) — SURVEY §2.2.

Batches are assembled as numpy on the host; sequence batches are padded to
the longest sequence in the batch with (B, T) masks — the mask-based padding
strategy that keeps downstream XLA shapes static per batch.
"""
from __future__ import annotations

import enum
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator
from deeplearning4j_tpu.datavec.records import (
    Record,
    RecordReader,
    SequenceRecordReader,
)


class _GeneratorIterator(DataSetIterator):
    """Bridges a generator (`_generate`) to the stateful
    has_next/next/reset contract that AsyncDataSetIterator's producer thread
    drives; reset() restarts from the underlying reader."""

    _gen = None
    _peeked = None

    def _generate(self):
        raise NotImplementedError

    def reset(self) -> None:
        self._gen = self._generate()
        self._peeked = None

    def has_next(self) -> bool:
        if self._gen is None:
            self.reset()
        if self._peeked is None:
            self._peeked = next(self._gen, None)
        return self._peeked is not None

    def next(self):
        if not self.has_next():
            raise StopIteration
        v, self._peeked = self._peeked, None
        return v

    def batch(self) -> int:
        return self.batch_size


def _one_hot(idx: float, n: int) -> np.ndarray:
    i = int(idx)
    if not 0 <= i < n:
        raise ValueError(f"label index {i} out of range [0, {n})")
    v = np.zeros(n, np.float32)
    v[i] = 1.0
    return v


def _num(rec: Record, lo: int, hi: int) -> List[float]:
    out = []
    for v in rec[lo:hi]:
        if isinstance(v, str):
            raise ValueError(
                f"non-numeric value {v!r} in feature columns [{lo}, {hi}) — "
                "string columns must be label columns or excluded")
        out.append(float(v))
    return out


class RecordReaderDataSetIterator(_GeneratorIterator):
    """Classification: `label_index` column one-hot to `num_classes`;
    regression: columns [label_index, label_index_to] are the targets
    (reference `RecordReaderDataSetIterator.java` constructors)."""

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index_to: Optional[int] = None):
        if label_index is not None and not regression and num_classes is None:
            raise ValueError("classification requires num_classes")
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.label_index_to = label_index_to if label_index_to is not None else label_index
        # string class labels are mapped to indices in first-seen order
        # (stable across epochs: readers restart deterministically)
        self._label_map: Dict[str, int] = {}

    def _class_index(self, v) -> float:
        if isinstance(v, str):
            idx = self._label_map.setdefault(v, len(self._label_map))
            if idx >= self.num_classes:
                raise ValueError(
                    f"found {len(self._label_map)} distinct string labels "
                    f"({sorted(self._label_map)}) but num_classes="
                    f"{self.num_classes}")
            return float(idx)
        return float(v)

    def _convert(self, rec: Record) -> Tuple[List[float], Optional[np.ndarray]]:
        li = self.label_index
        if li is None:
            return _num(rec, 0, len(rec)), None
        if li < 0:
            li = len(rec) + li
        hi = self.label_index_to if self.label_index_to is not None else li
        if hi < 0:
            hi = len(rec) + hi
        feats = _num(rec, 0, li) + _num(rec, hi + 1, len(rec))
        if self.regression:
            label = np.asarray([float(v) for v in rec[li:hi + 1]], np.float32)
        else:
            label = _one_hot(self._class_index(rec[li]), self.num_classes)
        return feats, label

    def _generate(self):
        batch_f: List[List[float]] = []
        batch_l: List[np.ndarray] = []
        for rec in self.reader:
            f, l = self._convert(rec)
            batch_f.append(f)
            if l is not None:
                batch_l.append(l)
            if len(batch_f) == self.batch_size:
                yield self._emit(batch_f, batch_l)
                batch_f, batch_l = [], []
        if batch_f:
            yield self._emit(batch_f, batch_l)

    def _emit(self, fs, ls) -> DataSet:
        return DataSet(np.asarray(fs, np.float32),
                       np.stack(ls) if ls else None)


class AlignmentMode(str, enum.Enum):
    """Two-reader sequence label alignment (reference
    `SequenceRecordReaderDataSetIterator.AlignmentMode`)."""

    EQUAL_LENGTH = "equal_length"
    ALIGN_START = "align_start"
    ALIGN_END = "align_end"


class SequenceRecordReaderDataSetIterator(_GeneratorIterator):
    """Sequence features (+ optionally separate sequence labels) → padded
    (B, T, F) batches with (B, T) masks.

    Single-reader mode: `label_index` column of each timestep record is the
    per-step label. Two-reader mode: `label_reader` supplies label sequences;
    when lengths differ, `alignment` places the shorter sequence at the
    start/end and masks the rest (reference `SequenceRecordReaderDataSetIterator.java`)."""

    def __init__(self, reader: SequenceRecordReader, batch_size: int,
                 num_classes: Optional[int] = None,
                 label_index: Optional[int] = None,
                 regression: bool = False,
                 label_reader: Optional[SequenceRecordReader] = None,
                 alignment: AlignmentMode = AlignmentMode.EQUAL_LENGTH):
        if label_reader is None and label_index is None:
            raise ValueError("need label_index (single-reader) or label_reader")
        if not regression and num_classes is None:
            raise ValueError("classification requires num_classes")
        self.reader = reader
        self.label_reader = label_reader
        self.batch_size = batch_size
        self.num_classes = num_classes
        self.label_index = label_index
        self.regression = regression
        self.alignment = alignment

    # each item: (feat_seq (Tf, F), label_seq (Tl, L))
    def _items(self):
        if self.label_reader is None:
            for seq in self.reader:
                f_rows, l_rows = [], []
                for rec in seq:
                    li = self.label_index if self.label_index >= 0 else len(rec) + self.label_index
                    f_rows.append(_num(rec, 0, li) + _num(rec, li + 1, len(rec)))
                    l_rows.append(np.asarray([float(rec[li])], np.float32)
                                  if self.regression
                                  else _one_hot(float(rec[li]), self.num_classes))
                yield np.asarray(f_rows, np.float32), np.stack(l_rows)
        else:
            import itertools

            _END = object()
            for seq, lseq in itertools.zip_longest(
                    self.reader, self.label_reader, fillvalue=_END):
                if seq is _END or lseq is _END:
                    which = "label" if seq is _END else "feature"
                    raise ValueError(
                        f"{which} reader ran out of sequences before the "
                        "other — the two readers must yield the same number "
                        "of sequences")
                f = np.asarray([_num(r, 0, len(r)) for r in seq], np.float32)
                if self.regression:
                    l = np.asarray([[float(v) for v in r] for r in lseq], np.float32)
                else:
                    l = np.stack([_one_hot(float(r[0]), self.num_classes)
                                  for r in lseq])
                if self.alignment == AlignmentMode.EQUAL_LENGTH \
                        and f.shape[0] != l.shape[0]:
                    raise ValueError(
                        f"EQUAL_LENGTH alignment but feature seq has "
                        f"{f.shape[0]} steps and label seq {l.shape[0]} "
                        "(use ALIGN_START/ALIGN_END)")
                yield f, l

    def _generate(self):
        buf: List[Tuple[np.ndarray, np.ndarray]] = []
        for item in self._items():
            buf.append(item)
            if len(buf) == self.batch_size:
                yield self._emit(buf)
                buf = []
        if buf:
            yield self._emit(buf)

    def _emit(self, items) -> DataSet:
        B = len(items)
        T = max(max(f.shape[0], l.shape[0]) for f, l in items)
        F = items[0][0].shape[1]
        L = items[0][1].shape[1]
        feats = np.zeros((B, T, F), np.float32)
        labs = np.zeros((B, T, L), np.float32)
        fmask = np.zeros((B, T), np.float32)
        lmask = np.zeros((B, T), np.float32)
        at_end = self.alignment == AlignmentMode.ALIGN_END
        for b, (f, l) in enumerate(items):
            tf, tl = f.shape[0], l.shape[0]
            fo = T - tf if at_end else 0
            lo = T - tl if at_end else 0
            feats[b, fo:fo + tf] = f
            fmask[b, fo:fo + tf] = 1.0
            labs[b, lo:lo + tl] = l
            lmask[b, lo:lo + tl] = 1.0
        same = np.array_equal(fmask, lmask)
        full = bool(fmask.all())
        return DataSet(feats, labs,
                       None if full else fmask,
                       None if full and same else lmask)


class RecordReaderMultiDataSetIterator(_GeneratorIterator):
    """Named readers + per-column-range input/output subsets →
    `MultiDataSet` (reference `RecordReaderMultiDataSetIterator.java`
    builder: `addReader/addInput/addOutput/addOutputOneHot`).

    Build with the `add_*` methods, then iterate:

        it = (RecordReaderMultiDataSetIterator(batch_size=32)
              .add_reader("csv", reader)
              .add_input("csv", 0, 3)
              .add_output_one_hot("csv", 4, 10))
    """

    def __init__(self, batch_size: int):
        self.batch_size = batch_size
        self.readers: Dict[str, RecordReader] = {}
        self._inputs: List[Tuple[str, Optional[int], Optional[int]]] = []
        self._outputs: List[Tuple[str, Optional[int], Optional[int], Optional[int]]] = []

    def add_reader(self, name: str, reader: RecordReader):
        self.readers[name] = reader
        return self

    def add_input(self, name: str, col_from: Optional[int] = None,
                  col_to: Optional[int] = None):
        self._check(name)
        self._inputs.append((name, col_from, col_to))
        return self

    def add_output(self, name: str, col_from: Optional[int] = None,
                   col_to: Optional[int] = None):
        self._check(name)
        self._outputs.append((name, col_from, col_to, None))
        return self

    def add_output_one_hot(self, name: str, col: int, num_classes: int):
        self._check(name)
        self._outputs.append((name, col, col, num_classes))
        return self

    def _check(self, name: str):
        if name not in self.readers:
            raise ValueError(f"unknown reader {name!r}; add_reader first")

    def _cols(self, rec: Record, lo: Optional[int], hi: Optional[int],
              one_hot: Optional[int]) -> np.ndarray:
        lo = 0 if lo is None else lo
        hi = len(rec) - 1 if hi is None else hi
        if one_hot is not None:
            return _one_hot(float(rec[lo]), one_hot)
        return np.asarray(_num(rec, lo, hi + 1), np.float32)

    def _generate(self):
        if not self._inputs or not self._outputs:
            raise ValueError("need at least one input and one output subset")
        iters = {n: iter(r) for n, r in self.readers.items()}
        while True:
            rows_in: List[List[np.ndarray]] = [[] for _ in self._inputs]
            rows_out: List[List[np.ndarray]] = [[] for _ in self._outputs]
            n = 0
            try:
                for _ in range(self.batch_size):
                    recs = {name: next(it) for name, it in iters.items()}
                    for i, (name, lo, hi) in enumerate(self._inputs):
                        rows_in[i].append(self._cols(recs[name], lo, hi, None))
                    for i, (name, lo, hi, oh) in enumerate(self._outputs):
                        rows_out[i].append(self._cols(recs[name], lo, hi, oh))
                    n += 1
            except StopIteration:
                pass
            if n == 0:
                return
            yield MultiDataSet(features=[np.stack(r) for r in rows_in],
                               labels=[np.stack(r) for r in rows_out])
            if n < self.batch_size:
                return
