"""Record readers: sources of per-example value lists.

Reference surface: DataVec `RecordReader`/`SequenceRecordReader` as consumed
by `deeplearning4j-core/.../datasets/datavec/RecordReaderDataSetIterator.java`
(SURVEY §2.2). A record is a list of values (numbers or strings — DataVec's
`Writable`s); a sequence record is a list of records (one per timestep).

Readers are plain host-side iterators — no device work happens here. The
CSV hot path optionally goes through the C++ native parser
(`deeplearning4j_tpu.native`) when the shared library is available,
mirroring how the reference's ETL is native-backed (DataVec on libnd4j
buffers); the pure-Python fallback is always present.
"""
from __future__ import annotations

import os
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Union

Value = Union[float, int, str]
Record = List[Value]

_IMG_EXTS = (".ppm", ".pgm", ".npy")


def _coerce(token: str) -> Value:
    """CSV token → float where possible, else the raw string (the adapter
    layer decides how to use string columns, e.g. as labels)."""
    try:
        return float(token)
    except ValueError:
        return token


class RecordReader:
    """One record per example. Iterate, `reset()`, then iterate again."""

    def __iter__(self):
        self.reset()
        return self._iterate()

    def _iterate(self):
        raise NotImplementedError

    def reset(self) -> None:  # stateless readers need nothing
        pass


class SequenceRecordReader(RecordReader):
    """One sequence (list of per-timestep records) per example."""


class CollectionRecordReader(RecordReader):
    """Wraps an in-memory collection of records (reference
    `CollectionRecordReader` — used heavily in DataVec adapter tests)."""

    def __init__(self, records: Sequence[Record]):
        self.records = [list(r) for r in records]

    def _iterate(self):
        return iter([list(r) for r in self.records])


class CollectionSequenceRecordReader(SequenceRecordReader):
    """Wraps an in-memory collection of sequences."""

    def __init__(self, sequences: Sequence[Sequence[Record]]):
        self.sequences = [[list(r) for r in seq] for seq in sequences]

    def _iterate(self):
        return iter([[list(r) for r in seq] for seq in self.sequences])


class LineRecordReader(RecordReader):
    """Each line of each file is one single-value record (reference DataVec
    `LineRecordReader`)."""

    def __init__(self, paths: Union[str, Path, Sequence[Union[str, Path]]]):
        self.paths = _as_paths(paths)

    def _iterate(self):
        for p in self.paths:
            with open(p, "r") as f:
                for line in f:
                    yield [line.rstrip("\n")]


class CSVRecordReader(RecordReader):
    """CSV → records (reference DataVec `CSVRecordReader`): one record per
    line, numeric columns parsed to floats, others kept as strings.

    `skip_lines` drops header rows; `delimiter` defaults to ','. Parsing of
    all-numeric files goes through the C++ native parser when available."""

    def __init__(self, paths: Union[str, Path, Sequence[Union[str, Path]]] = (),
                 skip_lines: int = 0, delimiter: str = ","):
        self.paths = _as_paths(paths)
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def _iterate(self):
        from deeplearning4j_tpu.native import csv_parse_numeric

        for p in self.paths:
            rows = csv_parse_numeric(p, self.skip_lines, self.delimiter)
            if rows is not None:  # native fast path (numeric-only file)
                # tolist() unboxes the whole matrix to plain floats in C —
                # iterating rows of np.float64 scalars would hand the boxing
                # cost right back to the per-record consumers
                yield from rows.tolist()
                continue
            with open(p, "r") as f:
                for i, line in enumerate(f):
                    if i < self.skip_lines:
                        continue
                    line = line.rstrip("\n")
                    if not line:
                        continue
                    yield [_coerce(t) for t in line.split(self.delimiter)]


class CSVSequenceRecordReader(SequenceRecordReader):
    """One CSV file per sequence (reference DataVec
    `CSVSequenceRecordReader`): each line is one timestep."""

    def __init__(self, paths: Union[str, Path, Sequence[Union[str, Path]]] = (),
                 skip_lines: int = 0, delimiter: str = ","):
        self.paths = _as_paths(paths)
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def _iterate(self):
        for p in self.paths:
            inner = CSVRecordReader([p], self.skip_lines, self.delimiter)
            yield list(inner)


class ImageRecordReader(RecordReader):
    """Images → flat pixel records, label appended from the parent directory
    name (reference DataVec `ImageRecordReader` with `ParentPathLabelGenerator`).

    Zero-dependency formats only: `.npy` arrays and binary `.ppm`/`.pgm`
    (the environment has no image codec libraries; datasets cached by the
    fetchers use these formats)."""

    def __init__(self, height: int, width: int, channels: int = 1,
                 paths: Union[str, Path, Sequence[Union[str, Path]]] = (),
                 labels: Optional[List[str]] = None):
        self.height, self.width, self.channels = height, width, channels
        self.paths = _as_paths(paths, exts=_IMG_EXTS)
        # label vocabulary: provided, or inferred (sorted parent dir names)
        self.labels = (list(labels) if labels is not None
                       else sorted({p.parent.name for p in self.paths}))

    def _iterate(self):
        import numpy as np

        for p in self.paths:
            if p.suffix == ".npy":
                img = np.load(p)
            else:
                img = _read_pnm(p)
            img = np.asarray(img, np.float32).reshape(-1)
            expect = self.height * self.width * self.channels
            if img.shape[0] != expect:
                raise ValueError(
                    f"{p}: image has {img.shape[0]} values, expected "
                    f"{self.height}x{self.width}x{self.channels}={expect}")
            # tolist() unboxes to plain Python floats in one C call (list()
            # would create one np.float32 object per pixel)
            rec: Record = img.tolist()
            rec.append(float(self.labels.index(p.parent.name)))
            yield rec

    def num_labels(self) -> int:
        return len(self.labels)


def _as_paths(paths, exts: Optional[tuple] = None) -> List[Path]:
    """str/Path/dir/sequence → flat sorted file list (reference FileSplit)."""
    if isinstance(paths, (str, Path)):
        paths = [paths]
    out: List[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files = sorted(f for f in p.rglob("*") if f.is_file())
            if exts:
                files = [f for f in files if f.suffix in exts]
            out.extend(files)
        else:
            out.append(p)
    return out


def _read_pnm(path: Path):
    """Binary PPM (P6) / PGM (P5) parser — pure stdlib."""
    import numpy as np

    with open(path, "rb") as f:
        data = f.read()
    # header: magic, width, height, maxval — whitespace/comment separated
    tokens: List[bytes] = []
    i = 0
    while len(tokens) < 4:
        while i < len(data) and data[i:i + 1].isspace():
            i += 1
        if data[i:i + 1] == b"#":
            while i < len(data) and data[i] != 0x0A:
                i += 1
            continue
        j = i
        while j < len(data) and not data[j:j + 1].isspace():
            j += 1
        tokens.append(data[i:j])
        i = j
    magic, w, h, maxval = tokens[0], int(tokens[1]), int(tokens[2]), int(tokens[3])
    if magic not in (b"P5", b"P6"):
        raise ValueError(f"{path}: unsupported PNM magic {magic!r}")
    ch = 1 if magic == b"P5" else 3
    i += 1  # single whitespace after maxval
    dtype = np.uint8 if maxval < 256 else ">u2"
    arr = np.frombuffer(data, dtype=dtype, count=w * h * ch, offset=i)
    return arr.reshape(h, w, ch).astype(np.float32)
