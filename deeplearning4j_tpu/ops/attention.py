"""Scaled-dot-product attention primitives.

The reference (DL4J 0.7.3 era) has no attention — its sequence toolbox is
LSTM+tBPTT (`LSTMHelpers.java:58`, `MultiLayerNetwork.doTruncatedBPTT:1140`)
and its only long-sequence mechanism is window slicing. This build treats
long-context as first-class: the core primitive here is **blockwise
(flash-style) attention** — an online-softmax accumulation over KV chunks via
`lax.scan` — which gives O(T) memory on one chip and is the per-device inner
loop of ring attention (`parallel/sequence.py`) when the sequence axis is
sharded across chips.

Layout: (B, T, H, D) for q/k/v — batch, time, heads, head_dim. The matmuls
are einsums over (T, D)×(D, T') per head: large, batched, MXU-friendly.
"""
from __future__ import annotations

from contextlib import contextmanager
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp()/where() NaN-free


def mask_bias(key_mask: jnp.ndarray) -> jnp.ndarray:
    """(B, Tk) 1=valid key mask → additive (B, 1, 1, Tk) attention bias."""
    return jnp.where(key_mask[:, None, None, :] > 0, 0.0, NEG_INF)


def full_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   bias: Optional[jnp.ndarray] = None,
                   causal: bool = False) -> jnp.ndarray:
    """Plain softmax(QKᵀ/√d + bias)·V. q/k/v: (B, T, H, D); bias broadcastable
    to (B, H, Tq, Tk). Reference semantics for the blockwise/ring variants'
    parity tests (the cuDNN-vs-builtin parity pattern,
    `deeplearning4j-cuda/src/test/.../TestConvolution.java`)."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.asarray(d, q.dtype))
    if bias is not None:
        s = s + bias
    if causal:
        Tq, Tk = s.shape[-2], s.shape[-1]
        iq = jnp.arange(Tq)[:, None]
        ik = jnp.arange(Tk)[None, :]
        s = jnp.where(ik <= iq + (Tk - Tq), s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked rows: softmax over all-NEG_INF is uniform garbage — zero
    # masked positions so such rows produce output 0, matching
    # blockwise_attention's l == 0 finalisation (the two dispatch paths must
    # agree for any mask)
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def full_attention_grouped(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                           bias: Optional[jnp.ndarray] = None,
                           causal: bool = False) -> jnp.ndarray:
    """`full_attention` for grouped-query attention WITHOUT materializing
    the repeated K/V: q (B, T, H, D) against k/v carrying only Hkv
    grouped heads (H a multiple of Hkv; query head j reads KV head
    j // (H/Hkv)). The queries fold into (B, T, Hkv, G, D) and the
    score/weighted-sum einsums batch over Hkv with G as a free query
    axis — each K/V element is touched once and BROADCAST across its
    G query heads, instead of being copied G× through HBM by
    `jnp.repeat` (the training path's old cost). Per-head numerics are
    the exact dots `full_attention` computes on the repeated operands,
    so the two paths agree bitwise (pinned in tests/test_ops.py).
    `bias` broadcastable to (B, H, Tq, Tk) — a full H-headed bias is
    regrouped, a broadcasting (B, 1, 1, Tk) mask bias passes through."""
    B, Tq, H, D = q.shape
    Tk, Hkv = k.shape[1], k.shape[2]
    G = H // Hkv
    qg = q.reshape(B, Tq, Hkv, G, D)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg,
                   k) / jnp.sqrt(jnp.asarray(D, q.dtype))
    if bias is not None:
        if bias.ndim == 4 and bias.shape[1] == H:
            bias = bias.reshape(B, Hkv, G, *bias.shape[2:])
        else:  # broadcasting head axis (e.g. mask_bias): keep it 1-wide
            bias = bias[:, :, None]
        s = s + bias
    if causal:
        iq = jnp.arange(Tq)[:, None]
        ik = jnp.arange(Tk)[None, :]
        s = jnp.where(ik <= iq + (Tk - Tq), s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    att = jnp.einsum("bhgqk,bkhd->bqhgd", p, v)
    return att.reshape(B, Tq, H, D)


def attention_block_accum(carry: Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray],
                          q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          bias: Optional[jnp.ndarray]):
    """One online-softmax accumulation step against a KV block.

    carry = (o, l, m): running un-normalised output (B, Tq, H, D), running
    softmax denominator (B, H, Tq) and running row max (B, H, Tq). The final
    attention output is o / l. This is the flash-attention recurrence; it is
    exact (not an approximation) for any KV block order.
    """
    o, l, m = carry
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(jnp.asarray(d, q.dtype))
    if bias is not None:
        s = s + bias
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    corr = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    # masked scores sit near NEG_INF; exp(s - m_new) does NOT underflow to 0
    # when the whole row is masked (m_new is then ~NEG_INF too), so zero them
    # explicitly — this keeps l == 0 for fully-masked rows, which
    # attention_finalize maps to output 0 instead of softmax-over-garbage
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = (o * jnp.transpose(corr, (0, 2, 1))[..., None]
             + jnp.einsum("bhqk,bkhd->bqhd", p, v))
    return o_new, l_new, m_new


def _accum_init(q: jnp.ndarray):
    B, Tq, H, D = q.shape
    o = jnp.zeros((B, Tq, H, D), q.dtype)
    l = jnp.zeros((B, H, Tq), q.dtype)
    m = jnp.full((B, H, Tq), NEG_INF, q.dtype)
    return o, l, m


def attention_finalize(o: jnp.ndarray, l: jnp.ndarray) -> jnp.ndarray:
    """o / l with fully-masked rows (l == 0) mapped to 0, not NaN."""
    l_t = jnp.transpose(l, (0, 2, 1))[..., None]
    return jnp.where(l_t > 0, o / jnp.where(l_t > 0, l_t, 1.0), 0.0)


def blockwise_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        *, causal: bool = False,
                        key_mask: Optional[jnp.ndarray] = None,
                        block_size: int = 512) -> jnp.ndarray:
    """Memory-efficient exact attention: scan over KV blocks with the
    online-softmax recurrence. Peak memory is O(Tq·block) for scores instead
    of O(Tq·Tk). q/k/v: (B, T, H, D); key_mask: (B, Tk) with 1=valid.

    Under jit the scan compiles to a single XLA while-loop — static shapes,
    no data-dependent Python control flow.
    """
    B, Tk, H, D = k.shape
    Tq = q.shape[1]
    Tk_orig = Tk
    blk = min(block_size, Tk)
    if Tk % blk != 0:  # pad keys to a block multiple; padded keys masked off
        pad = blk - Tk % blk
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        km = key_mask if key_mask is not None else jnp.ones((B, Tk), q.dtype)
        key_mask = jnp.pad(km, ((0, 0), (0, pad)))
        Tk = Tk + pad
    n_blocks = Tk // blk
    # (n_blocks, B, blk, H, D) for scan
    ks = jnp.moveaxis(k.reshape(B, n_blocks, blk, H, D), 1, 0)
    vs = jnp.moveaxis(v.reshape(B, n_blocks, blk, H, D), 1, 0)
    if key_mask is not None:
        ms = jnp.moveaxis(key_mask.reshape(B, n_blocks, blk), 1, 0)
    else:
        ms = jnp.ones((n_blocks, B, blk), q.dtype)
    iq = jnp.arange(Tq)
    # Tq != Tk: align queries to the END of the keys (decode-style), matching
    # full_attention's `ik <= iq + (Tk - Tq)` — offset uses the UNPADDED Tk
    causal_off = Tk_orig - Tq

    def body(carry, xs):
        k_blk, v_blk, m_blk, blk_idx = xs
        bias = mask_bias(m_blk)
        if causal:
            ik = blk_idx * blk + jnp.arange(blk)
            cb = jnp.where(ik[None, :] <= iq[:, None] + causal_off, 0.0, NEG_INF)
            bias = bias + cb[None, None, :, :]
        carry = attention_block_accum(carry, q, k_blk, v_blk, bias)
        return carry, None

    init = _accum_init(q)
    (o, l, _), _ = lax.scan(body, init,
                            (ks, vs, ms, jnp.arange(n_blocks)))
    return attention_finalize(o, l)


def cached_attention_step(q: jnp.ndarray, k_cache: jnp.ndarray,
                          v_cache: jnp.ndarray, pos) -> jnp.ndarray:
    """One autoregressive decode step against decode-layout KV caches.

    `q`: (B, H, D) — this step's query heads for every sequence (or slot).
    `k_cache`: (B, Hkv, D, L) and `v_cache`: (B, Hkv, L, D) — the TPU
    decode layouts (r4): the score einsum contracts D with L on the minor
    (lane) axis and the weighted sum contracts L with D minor, so each
    step streams the cache without a strided transpose. `pos`: position of
    the token being consumed — a scalar (whole-batch decode: every row at
    the same position) or a (B,) vector (slotted decode: every slot at its
    own position); cache entries past a row's `pos` are masked off, which
    is what makes one compiled step correct for slots holding sequences of
    different lengths (inactive/garbage tail entries are never attended).

    GQA: `H` may be a multiple of `Hkv`; query heads are grouped by the
    KV head they share and the einsums batch over Hkv against the
    UN-repeated caches — each cache byte (the decode bandwidth bound) is
    read once and serves H/Hkv query heads.

    Returns (B, H*D), ready for the output projection.
    """
    B, Hkv, D, L = k_cache.shape
    H = q.shape[1]
    G = H // Hkv
    qg = q.reshape(B, Hkv, G, D)
    s = jnp.einsum("bkgd,bkdl->bkgl", qg,
                   k_cache) / jnp.sqrt(jnp.asarray(D, q.dtype))
    pos = jnp.asarray(pos)
    limit = pos[:, None, None, None] if pos.ndim else pos
    s = jnp.where(jnp.arange(L)[None, None, None, :] <= limit, s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1)
    att = jnp.einsum("bkgl,bkld->bkgd", w, v_cache)
    return att.reshape(B, H * D)


def paged_gather(k_pool: jnp.ndarray, v_pool: jnp.ndarray,
                 page_table: jnp.ndarray):
    """Materialize per-slot dense decode-layout caches from a paged pool.

    `k_pool`: (P, Hkv, D, page) and `v_pool`: (P, Hkv, page, D) — the
    decode layouts of `cached_attention_step` with the length axis cut
    into fixed-size pages; page 0 is the reserved trash page (never
    allocated, absorbs masked writes). `page_table`: (S, n_pages) int32
    mapping each slot's logical page index to a pool page id
    (unallocated entries point at page 0). Returns (k, v) in the dense
    layouts (S, Hkv, D, n_pages*page) / (S, Hkv, n_pages*page, D): the
    gather is ordered by logical page index, so logical position
    `p` lands at index `p` exactly as in the contiguous cache — downstream
    attention numerics are the DENSE step's numerics, which is what
    keeps paged decode argmax-identical to `generate`. Garbage in
    unwritten/trash regions is masked by position downstream (and is
    always finite — pages only ever hold zeros or real KV — so masked
    `0 * garbage` terms stay exact zeros)."""
    P, Hkv, D, page = k_pool.shape
    S, n_pages = page_table.shape
    k = jnp.take(k_pool, page_table, axis=0)     # (S, n_pages, Hkv, D, page)
    k = jnp.transpose(k, (0, 2, 3, 1, 4)).reshape(S, Hkv, D, n_pages * page)
    v = jnp.take(v_pool, page_table, axis=0)     # (S, n_pages, Hkv, page, D)
    v = jnp.transpose(v, (0, 2, 1, 3, 4)).reshape(S, Hkv, n_pages * page, D)
    return k, v


def paged_gather_quant(k_pool: jnp.ndarray, v_pool: jnp.ndarray,
                       k_scale: jnp.ndarray, v_scale: jnp.ndarray,
                       page_table: jnp.ndarray, dtype=jnp.float32):
    """`paged_gather` over INT8 pools: gather the int8 pages and their
    per-(head, position) f32 scale pages (`k_scale`/`v_scale`:
    (P, Hkv, page), riding the same page table), dequantize, and return
    dense `dtype` caches in the decode layouts. This is the int8 tier's
    CPU/tier-1/kill-switch numerics ORACLE: the Pallas int8 kernel's
    page-loop dequant is parity-pinned against exactly this path
    (tests/test_pallas_paged_attention.py and the dispatch probe), the
    same role `paged_gather` plays for the full-precision kernel.
    Trash-page semantics hold for free: int8 zeros dequantize to exact
    0.0 under any scale, so unwritten regions stay finite and are
    masked by position downstream."""
    P, Hkv, D, page = k_pool.shape
    S, n_pages = page_table.shape
    L = n_pages * page
    k = jnp.take(k_pool, page_table, axis=0)   # (S, n_pages, Hkv, D, page)
    ks = jnp.take(k_scale, page_table, axis=0)  # (S, n_pages, Hkv, page)
    k = k.astype(jnp.float32) * ks[:, :, :, None, :]
    k = jnp.transpose(k, (0, 2, 3, 1, 4)).reshape(S, Hkv, D, L)
    v = jnp.take(v_pool, page_table, axis=0)   # (S, n_pages, Hkv, page, D)
    vs = jnp.take(v_scale, page_table, axis=0)
    v = v.astype(jnp.float32) * vs[..., None]
    v = jnp.transpose(v, (0, 2, 1, 3, 4)).reshape(S, Hkv, L, D)
    return k.astype(dtype), v.astype(dtype)


def paged_attention_step(q: jnp.ndarray, k_pool: jnp.ndarray,
                         v_pool: jnp.ndarray, page_table: jnp.ndarray,
                         pos) -> jnp.ndarray:
    """One decode step against a PAGED KV pool: gather each slot's pages
    into the dense decode layout, then run `cached_attention_step`
    unchanged — paged storage, dense numerics. The persistent allocation
    is the pool (pages actually held per request), not
    slots × max-length; the gathered dense view is a transient of the
    step. This XLA form is the portable reference semantics AND the
    dispatch fallback: `paged_attention_step_auto` runs the fused Pallas
    kernel that walks the page table in-place (vLLM's PagedAttention,
    `ops/pallas_paged_attention.py`) when the platform supports it.

    Head-count contract: Hkv here is whatever the POOLS carry — under
    tensor-parallel serving (`serving.tp_engine`) this runs per shard
    inside `shard_map` with the LOCAL head count Hkv/tp (pools are
    sharded on the head axis), and neither this step nor the kernel can
    tell: heads never mix in attention, so the per-shard computation is
    the single-device one at a smaller Hkv."""
    k, v = paged_gather(k_pool, v_pool, page_table)
    return cached_attention_step(q, k, v, pos)


def paged_attention_step_auto(q: jnp.ndarray, k_pool: jnp.ndarray,
                              v_pool: jnp.ndarray,
                              page_table: jnp.ndarray, pos,
                              active=None, k_scale=None,
                              v_scale=None) -> jnp.ndarray:
    """`paged_attention_step` behind the kernel-dispatch contract: on
    TPU the Pallas paged-attention kernel walks the page table in place
    (`ops/pallas_paged_attention.py` — no dense transient, each cache
    byte read once); everywhere else (CPU tier-1, kill switch, failed
    probe) the `paged_gather` + `cached_attention_step` reference path
    runs unchanged. `q`: (S, H, D); `pos`: (S,) per-slot positions.
    Inactive lanes (optional `active` (S,) bool) are a compute skip on
    the kernel path (exact-zero rows) and plain masked-downstream
    garbage on the gather path — both discarded by the engine.
    int8 pools pass their f32 scale pools as `k_scale`/`v_scale`
    ((P+1, Hkv, page)): the kernel dequantizes inside the page loop,
    the fallback dequantizes via `paged_gather_quant` — same dispatch
    contract, halved DMA bytes. Returns (S, H*D)."""
    from deeplearning4j_tpu.ops.pallas_paged_attention import (
        paged_attention_or_none,
    )

    S, H, D = q.shape
    pos = jnp.asarray(pos)
    if pos.ndim == 0:
        pos = jnp.broadcast_to(pos, (S,))
    out = paged_attention_or_none(q[:, None], k_pool, v_pool, page_table,
                                  pos, active, k_scale=k_scale,
                                  v_scale=v_scale)
    if out is not None:
        return out.reshape(S, H * D)
    if k_scale is not None:
        kd, vd = paged_gather_quant(k_pool, v_pool, k_scale, v_scale,
                                    page_table, q.dtype)
        return cached_attention_step(q, kd, vd, pos)
    return paged_attention_step(q, k_pool, v_pool, page_table, pos)


def paged_attention_chunk_auto(q: jnp.ndarray, k_pool: jnp.ndarray,
                               v_pool: jnp.ndarray,
                               page_table: jnp.ndarray, pos0,
                               active=None, k_scale=None,
                               v_scale=None) -> jnp.ndarray:
    """Chunk-width paged attention behind the same dispatch contract —
    the speculative (k+1)-verify and chunked-prefill-suffix shapes.
    `q`: (S, C, H, D) — C CONTIGUOUS query tokens per slot starting at
    absolute position `pos0[s]` (row c attends to cache entries
    `<= pos0[s] + c`, the `cached_attention_chunk` mask). Kernel path:
    one fused page-walk dispatch; fallback: `paged_gather` + slot-vmapped
    `cached_attention_chunk` (exactly `_verify_block_attention`, and for
    S=1 exactly `_prefill_chunk_block_attention`). int8 pools pass
    `k_scale`/`v_scale` exactly as in `paged_attention_step_auto`.
    Returns (S, C, H*D)."""
    from deeplearning4j_tpu.ops.pallas_paged_attention import (
        paged_attention_or_none,
    )

    S, C, H, D = q.shape
    pos0 = jnp.asarray(pos0)
    if pos0.ndim == 0:
        pos0 = jnp.broadcast_to(pos0, (S,))
    out = paged_attention_or_none(q, k_pool, v_pool, page_table, pos0,
                                  active, k_scale=k_scale,
                                  v_scale=v_scale)
    if out is not None:
        return out.reshape(S, C, H * D)
    if k_scale is not None:
        kd, vd = paged_gather_quant(k_pool, v_pool, k_scale, v_scale,
                                    page_table, q.dtype)
    else:
        kd, vd = paged_gather(k_pool, v_pool, page_table)
    qpos = pos0[:, None] + jnp.arange(C)[None, :]
    return jax.vmap(cached_attention_chunk)(q, kd, vd, qpos)


def cached_attention_chunk(q: jnp.ndarray, k_cache: jnp.ndarray,
                           v_cache: jnp.ndarray, q_pos) -> jnp.ndarray:
    """Chunked-prefill attention for ONE slot: a block of C queries
    against that slot's dense-layout cache.

    `q`: (C, H, D) — the prompt chunk's query heads, at absolute
    positions `q_pos` (C,). `k_cache`: (Hkv, D, L), `v_cache`:
    (Hkv, L, D) — the slot's cache (typically `paged_gather` output for
    one slot) which already contains this chunk's own K/V, so masking
    each query to cache entries `<= q_pos` yields exactly causal
    attention over [prompt-so-far ‖ this chunk]. GQA contracts against
    the un-repeated Hkv caches, like `cached_attention_step`.

    Returns (C, H*D), ready for the output projection."""
    Hkv, D, L = k_cache.shape
    C, H = q.shape[0], q.shape[1]
    G = H // Hkv
    qg = jnp.transpose(q.reshape(C, Hkv, G, D), (1, 2, 0, 3))  # (Hkv,G,C,D)
    s = jnp.einsum("kgcd,kdl->kgcl", qg,
                   k_cache) / jnp.sqrt(jnp.asarray(D, q.dtype))
    limit = jnp.asarray(q_pos)[None, None, :, None]
    s = jnp.where(jnp.arange(L)[None, None, None, :] <= limit, s, -jnp.inf)
    w = jax.nn.softmax(s, axis=-1)
    att = jnp.einsum("kgcl,kld->kgcd", w, v_cache)    # (Hkv, G, C, D)
    return jnp.transpose(att, (2, 0, 1, 3)).reshape(C, H * D)


_SEQ_PARALLEL: list = []  # (mesh, seq_axis, batch_axis) stack


@contextmanager
def sequence_parallel_scope(mesh, axis_name: str = "seq",
                            batch_axis: Optional[str] = None):
    """Within this scope, `multi_head_attention` (and therefore every
    attention layer traced under it) computes via ring attention with the
    time axis sharded over `axis_name` — how a SelfAttention/Transformer
    model trains with sequences longer than one chip holds. Trace-time
    static: enter the scope around the jit/trace of the step."""
    _SEQ_PARALLEL.append((mesh, axis_name, batch_axis))
    try:
        yield
    finally:
        _SEQ_PARALLEL.pop()


def multi_head_attention(q, k, v, *, causal=False, key_mask=None,
                         block_size: Optional[int] = None):
    """Dispatch (the cuDNN-helper pattern: same contract, fastest available
    path picked): ring attention when a sequence-parallel scope is active,
    pallas flash kernel for long unmasked sequences, XLA blockwise beyond
    `block_size`, full attention otherwise.

    GQA: `k`/`v` may carry fewer heads than `q` (Hkv dividing H). The
    full-attention path computes the grouping as a broadcast einsum
    (`full_attention_grouped` — no materialized repeat); the kernel
    paths (ring/flash/blockwise) require equal head counts and widen
    via `jnp.repeat`, exactly the layers' historical behavior."""
    H, Hkv = q.shape[2], k.shape[2]

    def widened():
        if Hkv == H:
            return k, v
        g = H // Hkv
        return jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2)

    if _SEQ_PARALLEL:
        from deeplearning4j_tpu.parallel.sequence import ring_attention

        kf, vf = widened()
        mesh, axis_name, batch_axis = _SEQ_PARALLEL[-1]
        return ring_attention(q, kf, vf, mesh, axis_name=axis_name,
                              causal=causal, key_mask=key_mask,
                              batch_axis=batch_axis)
    long_seq = block_size is not None and k.shape[1] > block_size
    if long_seq and key_mask is None:
        from deeplearning4j_tpu.ops.pallas_attention import flash_attention_or_none

        kf, vf = widened()
        out = flash_attention_or_none(q, kf, vf, causal=causal)
        if out is not None:
            return out
    if long_seq:
        kf, vf = widened()
        return blockwise_attention(q, kf, vf, causal=causal,
                                   key_mask=key_mask,
                                   block_size=block_size)
    bias = None if key_mask is None else mask_bias(key_mask)
    if Hkv != H:
        return full_attention_grouped(q, k, v, bias=bias, causal=causal)
    return full_attention(q, k, v, bias=bias, causal=causal)
