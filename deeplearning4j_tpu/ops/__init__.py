"""Core math ops: activations, loss functions, learning-rate schedules.

TPU-equivalent of the ND4J op surface the reference consumes
(`org.nd4j.linalg.api.ops.*`, `Transforms`, `LossFunctions`, `IActivation`) —
implemented as pure jax.numpy functions so XLA fuses them into the
surrounding matmul/conv HLO instead of dispatching one JNI op at a time.
"""

from deeplearning4j_tpu.ops.activations import Activation, activation_fn  # noqa: F401
from deeplearning4j_tpu.ops.losses import LossFunction, loss_fn  # noqa: F401
