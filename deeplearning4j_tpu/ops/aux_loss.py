"""Auxiliary-loss plumbing for mid-network losses.

Layers sometimes contribute loss terms that are not a function of the
network output — the Switch MoE load-balancing loss is the canonical case.
The reference has no such mechanism (its losses live only in output
layers); here a trace-time collector lets any layer `add_aux_loss(term)`
during the forward pass, and the network's `_loss_pure` drains the
collected terms into the total. Purely trace-time state (like
`sequence_parallel_scope`), so it is jit-safe: the terms become part of
the traced computation.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

# thread-LOCAL: concurrent jit traces (e.g. parameter-server worker threads
# each tracing their replica's step) must not cross-contaminate scopes
_tls = threading.local()


def _stack() -> list:
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


@contextmanager
def aux_loss_scope():
    """Collects aux-loss terms added during the enclosed trace; yields the
    list (sum it after the forward)."""
    terms: list = []
    stack = _stack()
    stack.append(terms)
    try:
        yield terms
    finally:
        stack.pop()


def add_aux_loss(term) -> None:
    """Called by layers during forward; no-op when no scope is active
    (e.g. plain inference through `output()`)."""
    stack = _stack()
    if stack:
        stack[-1].append(term)
