"""Batch-row-indexed PRNG scope: partition-invariant dropout masks.

Dropout draws its mask from per-ROW keys — `fold_in(layer_rng, global_row)`
— instead of one bulk draw over the whole batch. The realization for a
given (seed, iteration, layer, row) is then identical no matter how the
batch is partitioned: single device, dp shards under the global-view jit,
or GPipe microbatches inside a manual `shard_map` (where each microbatch
sees only a SLICE of the batch and a bulk draw could not reproduce the
single-device mask). This is what lets pipeline stages run dropout with
exact same-seed parity vs single-device training
(`parallel/pipeline_wrapper.py`) — the reference has no analogous problem
because its only strategy is whole-model replicas (`ParallelWrapper.java`,
each worker holds the full net and draws locally).

The scope communicates the first global row index of the slice currently
being processed; it is trace-time state (set while tracing the pipeline
step), never runtime state. Outside any scope the offset is None and
dropout specializes to ONE bulk draw (r6): the single-device and
global-view-jit cases need no per-row stream — a single trace of the
whole batch is partition-invariant by construction — and the per-row
fold_in+vmap costs B extra threefry derivations per dropout site
(measured each round as bench gpt_med's `dropout_rng_overhead_pct`).
Enter `row_offset_scope(0)` around a single-device trace to opt into
the partition-invariant per-row stream — how the pipeline parity tests
(`tests/test_pipeline_wrapper.py`) and the dryrun 3-D tier pin
same-seed mask equality between one device and a pipelined mesh.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

# thread-local like the sibling aux_loss scope: training masters and the
# distributed wrappers trace on ThreadPoolExecutor threads, and a traced
# offset leaking across threads would poison an unrelated trace
_STATE = threading.local()


@contextmanager
def row_offset_scope(offset):
    """While tracing: batch rows seen by dropout are global rows
    [offset, offset + local_rows)."""
    prev = getattr(_STATE, "offset", None)
    _STATE.offset = offset
    try:
        yield
    finally:
        _STATE.offset = prev


def current_row_offset():
    """The active slice's first global row index, or None (== row 0)."""
    return getattr(_STATE, "offset", None)
