"""Rotary position embeddings (RoPE, Su et al. 2021 — rotate-half form).

No counterpart in the reference (no transformer tier); included because
relative-position attention is how modern long-context decoders encode
order: each head's feature pairs (x_a, x_b) rotate by angle pos * base
^(-2a/hd), so the q·k inner product depends only on the RELATIVE
distance between query and key — attention generalizes past the trained
context window, and there is no learned positional table to bound
`max_length`. TPU-friendly: pure elementwise mul/add on (B, T, H, hd)
slabs, fused by XLA into the surrounding projections; the precomputed
cos/sin tables are (T, hd/2) and broadcast over batch and heads.

Decode contract (models/transformer.py): keys are rotated at their own
absolute position BEFORE entering the KV cache — a cached key never
needs re-rotation — and each step's query rotates at the current
position.
"""
from __future__ import annotations

import jax.numpy as jnp


def rope_angles(positions, head_dim: int, base: float = 10000.0):
    """cos/sin tables for `positions` (any shape P...): ((P..., hd/2) x 2).
    `head_dim` must be even (pairs rotate together)."""
    if head_dim % 2:
        raise ValueError(f"RoPE needs an even head_dim, got {head_dim}")
    half = head_dim // 2
    inv = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = jnp.asarray(positions, jnp.float32)[..., None] * inv
    return jnp.cos(ang), jnp.sin(ang)


def rope_rotate(x, cos, sin):
    """Rotate (..., T, H, hd) by per-position tables (..., T, hd/2) — or
    a single position's (hd/2,) tables for one decode step. Computed in
    f32 (angles are precision-sensitive at long range) and cast back."""
    half = x.shape[-1] // 2
    dt = x.dtype
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    if cos.ndim == 1:            # single position: broadcast over heads
        c, s = cos, sin
    else:                        # (..., T, half) -> (..., T, 1, half):
        # an axis inserted before `half` broadcasts over heads; leading
        # dims (e.g. the slotted decode's per-slot position batch) align
        # with x's leading dims
        c, s = cos[..., None, :], sin[..., None, :]
    return jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c],
                           axis=-1).astype(dt)
