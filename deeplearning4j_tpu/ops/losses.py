"""Loss functions.

Reference surface: ND4J `LossFunctions.LossFunction` enum + ILossFunction
impls, consumed by DL4J output layers (`nn/conf/layers/OutputLayer` via
`LossFunction` builder arg). Implemented as pure functions of
(labels, pre-activation output) so the softmax+cross-entropy pair fuses into
the numerically-stable log-softmax form under XLA — the reference gets the
same stability via ILossFunction#computeGradient special-casing.

Conventions (match the reference):
- per-example score = sum over output dims of elementwise loss;
- network score     = mean over (unmasked) examples;
- masks broadcast over the feature dim (per-timestep masking for RNNs).
"""
from __future__ import annotations

import enum
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.activations import Activation, activation_fn

_EPS = 1e-8


class LossFunction(str, enum.Enum):
    MSE = "mse"
    L1 = "l1"
    L2 = "l2"
    XENT = "xent"  # binary cross-entropy
    MCXENT = "mcxent"  # multi-class cross-entropy
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"
    COSINE_PROXIMITY = "cosine_proximity"
    HINGE = "hinge"
    SQUARED_HINGE = "squared_hinge"
    KL_DIVERGENCE = "kl_divergence"
    MEAN_ABSOLUTE_ERROR = "mean_absolute_error"
    MEAN_ABSOLUTE_PERCENTAGE_ERROR = "mean_absolute_percentage_error"
    MEAN_SQUARED_LOGARITHMIC_ERROR = "mean_squared_logarithmic_error"
    POISSON = "poisson"


def _elementwise_loss(loss: LossFunction, labels: jnp.ndarray, out: jnp.ndarray) -> jnp.ndarray:
    """Per-element loss on post-activation outputs (non-fused generic path)."""
    if loss in (LossFunction.MSE, LossFunction.L2):
        # DL4J: L2 = sum squared error; MSE = L2 / nOut. Score-level scaling
        # is applied in loss_score below.
        return (out - labels) ** 2
    if loss in (LossFunction.L1, LossFunction.MEAN_ABSOLUTE_ERROR):
        return jnp.abs(out - labels)
    if loss == LossFunction.XENT:
        o = jnp.clip(out, _EPS, 1.0 - _EPS)
        return -(labels * jnp.log(o) + (1.0 - labels) * jnp.log(1.0 - o))
    if loss in (LossFunction.MCXENT, LossFunction.NEGATIVELOGLIKELIHOOD):
        return -labels * jnp.log(jnp.clip(out, _EPS, None))
    if loss == LossFunction.COSINE_PROXIMITY:
        # handled at the row level in loss_score
        raise ValueError("cosine proximity is row-level")
    if loss == LossFunction.HINGE:
        return jnp.maximum(0.0, 1.0 - labels * out)
    if loss == LossFunction.SQUARED_HINGE:
        return jnp.maximum(0.0, 1.0 - labels * out) ** 2
    if loss == LossFunction.KL_DIVERGENCE:
        l = jnp.clip(labels, _EPS, None)
        o = jnp.clip(out, _EPS, None)
        return labels * (jnp.log(l) - jnp.log(o))
    if loss == LossFunction.MEAN_ABSOLUTE_PERCENTAGE_ERROR:
        return 100.0 * jnp.abs((labels - out) / jnp.clip(jnp.abs(labels), _EPS, None))
    if loss == LossFunction.MEAN_SQUARED_LOGARITHMIC_ERROR:
        return (jnp.log1p(jnp.clip(out, -1 + _EPS, None)) - jnp.log1p(jnp.clip(labels, -1 + _EPS, None))) ** 2
    if loss == LossFunction.POISSON:
        return out - labels * jnp.log(jnp.clip(out, _EPS, None))
    raise ValueError(f"unknown loss {loss}")


def loss_per_row(
    loss: LossFunction | str,
    activation: Activation | str,
    labels: jnp.ndarray,
    preout: jnp.ndarray,
) -> jnp.ndarray:
    """Per-ROW loss from PRE-activation outputs: shape preout.shape[:-1]
    (one score per example row, or per (b, t) position for time-distributed
    outputs). The reference's `ILossFunction.computeScoreArray` role —
    what `scoreExamples` aggregates and `loss_score` means over."""
    loss = LossFunction(loss) if not isinstance(loss, LossFunction) else loss
    activation = Activation(activation) if not isinstance(activation, Activation) else activation

    # SPARSE labels: integer class ids of shape preout.shape[:-1] instead of
    # one-hot rows. A (B, T) int array is vocab_size× fewer bytes over the
    # host link than its (B, T, V) one-hot — for LM training the label
    # transfer dominates the batch. (The reference supports only dense
    # one-hot labels; this is a TPU-native extension.)
    if (labels.ndim == preout.ndim - 1
            and jnp.issubdtype(labels.dtype, jnp.integer)):
        if loss in (LossFunction.MCXENT,
                    LossFunction.NEGATIVELOGLIKELIHOOD) \
                and activation == Activation.SOFTMAX:
            ls = jax.nn.log_softmax(preout, axis=-1)
            # clamp into range: sentinel ids on MASKED positions must stay
            # harmless (an OOB gather yields NaN, and NaN×0 mask is NaN)
            idx = jnp.clip(labels, 0, preout.shape[-1] - 1)
            return -jnp.take_along_axis(
                ls, idx[..., None].astype(jnp.int32), axis=-1)[..., 0]
        raise ValueError(
            "integer class-id labels require MCXENT/NEGATIVELOGLIKELIHOOD "
            f"with SOFTMAX output (got loss={loss.value}, "
            f"activation={activation.value}); pass one-hot labels instead")

    if loss in (LossFunction.MCXENT, LossFunction.NEGATIVELOGLIKELIHOOD) and activation == Activation.SOFTMAX:
        per_elem = -labels * jax.nn.log_softmax(preout, axis=-1)
    elif loss == LossFunction.XENT and activation == Activation.SIGMOID:
        # stable BCE-with-logits
        per_elem = jnp.maximum(preout, 0.0) - preout * labels + jnp.log1p(jnp.exp(-jnp.abs(preout)))
    elif loss == LossFunction.COSINE_PROXIMITY:
        out = activation_fn(activation)(preout)
        num = jnp.sum(labels * out, axis=-1)
        den = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(out, axis=-1)
        return -num / jnp.clip(den, _EPS, None)
    else:
        out = activation_fn(activation)(preout)
        per_elem = _elementwise_loss(loss, labels, out)

    if loss == LossFunction.MSE:
        return jnp.mean(per_elem, axis=-1)
    return jnp.sum(per_elem, axis=-1)


def loss_score(
    loss: LossFunction | str,
    activation: Activation | str,
    labels: jnp.ndarray,
    preout: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Mean-per-example loss from PRE-activation outputs.

    Fuses softmax+MCXENT / sigmoid+XENT into numerically-stable forms — the
    TPU/XLA analogue of the reference's ILossFunction computeGradient
    shortcuts for the softmax and sigmoid output-activation cases.
    Returns a scalar: sum over output dims, mean over (unmasked) rows.
    """
    return _masked_row_mean(loss_per_row(loss, activation, labels, preout),
                            mask)


def _masked_row_mean(per_row: jnp.ndarray, mask: Optional[jnp.ndarray]) -> jnp.ndarray:
    """Mean over rows; with a mask, masked rows contribute 0 and the divisor
    is the unmasked count (reference: per-example masking in
    `BaseOutputLayer.computeScore` / `GradientCheckTestsMasking`)."""
    if mask is None:
        return jnp.mean(per_row)
    mask = jnp.reshape(mask, per_row.shape)
    total = jnp.sum(per_row * mask)
    count = jnp.clip(jnp.sum(mask), 1.0, None)
    return total / count


def loss_fn(loss: LossFunction | str):
    """Convenience: (labels, postactivation_out, mask) -> scalar.

    Generic (non-fused) path used by evaluation code; training uses
    loss_score on pre-activations for stability.
    """
    loss = LossFunction(loss) if not isinstance(loss, LossFunction) else loss

    def f(labels, out, mask=None):
        if loss == LossFunction.COSINE_PROXIMITY:
            num = jnp.sum(labels * out, axis=-1)
            den = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(out, axis=-1)
            return _masked_row_mean(-num / jnp.clip(den, _EPS, None), mask)
        per_elem = _elementwise_loss(loss, labels, out)
        per_row = jnp.mean(per_elem, axis=-1) if loss == LossFunction.MSE else jnp.sum(per_elem, axis=-1)
        return _masked_row_mean(per_row, mask)

    return f


_range_skip_warned: set = set()


def warn_range_skip_once(key: str, message: str) -> None:
    """Warn once per `key` that a device-resident batch skipped its id/label
    range validation (shared by check_sparse_label_range and
    OneHotEncoder.check_ids so the dedup policy lives in one place)."""
    if key in _range_skip_warned:
        return
    _range_skip_warned.add(key)
    import warnings

    warnings.warn(message, stacklevel=3)


def check_sparse_label_range(labels, n_classes, mask=None,
                             where: str = "the output layer",
                             value_range=None) -> None:
    """Shared validation for sparse class-id labels (used by
    MultiLayerNetwork, ComputationGraph, and Evaluation): raise a clear
    error when an id falls outside [0, n_classes) — inside the traced
    gather an out-of-range id would clamp and silently train the wrong
    class. Positions where `mask` == 0 are exempt: pad-with-sentinel plus a
    labels mask is the standard variable-length convention, and masked
    positions contribute nothing to the (clamped) loss."""
    import jax.numpy as jnp
    import numpy as np

    if isinstance(labels, jnp.ndarray) and not isinstance(labels, np.ndarray):
        # device-resident batch: a value check would download it through
        # the host link every step. DeviceCacheDataSetIterator records the
        # (masked) integer range at staging time while the data is still
        # host-side — validate against that instead.
        if not jnp.issubdtype(labels.dtype, jnp.integer):
            return  # float labels (one-hot/regression): not sparse ids
        if value_range is not None and n_classes:
            mn, mx = value_range
            if mx >= n_classes or mn < 0:
                bad = mx if mx >= n_classes else mn
                raise ValueError(
                    f"sparse label id {bad} out of range [0, {n_classes}) "
                    f"for {where} (range recorded when the batch was "
                    "staged on device)")
        elif n_classes:
            # raw jnp labels with no staged range: the loud OOB failure the
            # docstrings promise cannot run — say so once instead of
            # silently reverting to clamp semantics (key includes n_classes
            # so distinct nets sharing the default `where` still each warn)
            warn_range_skip_once(
                f"{where}[{n_classes}]",
                f"sparse-label range check skipped for {where}: labels are "
                "device-resident with no staged value range (pass host "
                "arrays or use DeviceCacheDataSetIterator to keep the "
                "out-of-range check); out-of-range ids will clamp silently")
        return
    larr = np.asarray(labels)
    if (not np.issubdtype(larr.dtype, np.integer) or not larr.size
            or not n_classes):
        return
    if mask is not None:
        m = np.asarray(mask).astype(bool).reshape(larr.shape)
        larr = larr[m]
        if not larr.size:
            return
    mx, mn = int(larr.max()), int(larr.min())
    if mx >= n_classes or mn < 0:
        bad = mx if mx >= n_classes else mn
        raise ValueError(
            f"sparse label id {bad} out of range [0, {n_classes}) for "
            f"{where} (mask padded positions with a labels mask instead of "
            "unmasked sentinel ids)")
