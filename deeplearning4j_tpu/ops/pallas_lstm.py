"""Pallas TPU fused LSTM cell: the recurrent scan as ONE kernel (fwd + bwd).

The reference's hottest loop is the LSTM time loop
(`deeplearning4j-nn/.../recurrent/LSTMHelpers.java:157` forward,
`:311` BPTT backward), which it accelerates with cuDNN-class fused RNN
kernels. The XLA lowering here (`nn/layers/recurrent.py` `lax.scan`)
compiles the cell once, but on v5e each scan iteration still runs ~5
separate kernels (recurrent-GEMM fusion, gate elementwise, carry copies,
dynamic-update-slice output stacking) at ~14 us/step measured — mostly
per-iteration overhead around a 1.4 us matmul.

This module fuses the whole time loop into one Pallas kernel per
direction:

- grid = (B/block_b, T): batch blocks parallel, time sequential
  (`dimension_semantics=("parallel", "arbitrary")`); the (h, c) carries
  live in f32 VMEM scratch ACROSS grid steps, so HBM sees no carry
  traffic at all.
- Per step the kernel does exactly one MXU matmul (h @ RW) plus the gate
  elementwise chain, and streams in the pre-computed input projections
  xw[t] (the (B,T,nIn)@(nIn,4H) GEMM is batched over time OUTSIDE the
  kernel where the MXU runs it at full tilt).
- The TRAINING forward also stashes post-activation gates (i,f,o,g) and
  the cell states — the residuals the backward needs. The backward kernel
  walks the grid time-reversed computing only the truly-sequential part
  (dz per step + one (B,4H)@(4H,H) matmul for dh_prev); every batched
  gradient contraction (dW, dRW, db, d-peephole, dx) is a single big XLA
  GEMM/reduction over the stashed slabs outside the kernel.

Gate math (order [i, f, o, g], matching GravesLSTMParamInitializer):
  z  = xw[t] + h @ RW;  zi += pI*c;  zf += pF*c          (peepholes)
  i, f = sigmoid(zi), sigmoid(zf);  g = tanh(zg)
  c' = f*c + i*g;  o = sigmoid(zo + pO*c');  h' = o*tanh(c')

Dispatch follows the cuDNN-helper pattern (`ConvolutionLayer.java:69-79`,
as in `ops/pallas_attention.py`): an eager compile probe per shape class,
silent fall-through to the lax.scan path when the kernel can't serve
(non-sigmoid/tanh activations, non-MXU-friendly sizes, or a platform
where Mosaic won't compile). Masked (variable-length) sequences run a
dedicated kernel pair: a masked step passes (h, c) through and emits
zeros (`LSTMHelpers`/`GradientCheckTestsMasking` semantics, binary
masks), with the carries stashed separately from the outputs — under
masking they differ.
"""
from __future__ import annotations

import functools
import logging
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.kernel_dispatch import (
    vmem_limit_bytes as _vmem_limit,
    dot as _dot,
    mxu_dtype as _mxu_dtype,
    probe_verdict as _probe_verdict,
    stat_dtype as _stat_dtype,
    tpu_compiler_params as _compiler_params,
)

logger = logging.getLogger("deeplearning4j_tpu")


def _lstm_fwd_kernel(xw_ref, rw_ref, peep_ref, h0_ref, c0_ref,
                     h_out_ref, cT_ref, c_stash_ref, gates_ref,
                     h_scr, c_scr, *, n_out: int, with_stash: bool):
    from jax.experimental import pallas as pl

    t = pl.program_id(1)
    nt = pl.num_programs(1)
    dt = _mxu_dtype(xw_ref.dtype)
    sdt = _stat_dtype(xw_ref.dtype)
    H = n_out

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:].astype(sdt)
        c_scr[:] = c0_ref[:].astype(sdt)

    c = c_scr[:]
    z = xw_ref[0].astype(sdt) + _dot(h_scr[:].astype(dt), rw_ref[:],
                                     ((1,), (0,)), dt)
    pI = peep_ref[0:1].astype(sdt)
    pF = peep_ref[1:2].astype(sdt)
    pO = peep_ref[2:3].astype(sdt)
    i = jax.nn.sigmoid(z[:, :H] + pI * c)
    f = jax.nn.sigmoid(z[:, H:2 * H] + pF * c)
    g = jnp.tanh(z[:, 3 * H:])
    c_new = f * c + i * g
    o = jax.nn.sigmoid(z[:, 2 * H:3 * H] + pO * c_new)
    h_new = o * jnp.tanh(c_new)

    h_out_ref[0] = h_new.astype(h_out_ref.dtype)
    if with_stash:
        c_stash_ref[0] = c_new.astype(c_stash_ref.dtype)
        gates_ref[0] = jnp.concatenate([i, f, o, g], axis=1).astype(
            gates_ref.dtype)
    h_scr[:] = h_new
    c_scr[:] = c_new

    @pl.when(t == nt - 1)
    def _final_cell():
        cT_ref[:] = c_new.astype(cT_ref.dtype)


def _lstm_bwd_kernel(gates_ref, c_ref, c_prev_ref, dh_out_ref, dcT_ref,
                     rw_ref, peep_ref, c0_ref, dz_ref, dhc0_ref,
                     dh_scr, dc_scr, *, n_out: int):
    from jax.experimental import pallas as pl

    t = pl.program_id(1)
    nt = pl.num_programs(1)
    s_is_first = t == nt - 1  # reversed walk: last grid step is timestep 0
    dt = _mxu_dtype(dz_ref.dtype)
    sdt = _stat_dtype(dz_ref.dtype)
    H = n_out

    @pl.when(t == 0)
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dc_scr[:] = dcT_ref[:].astype(sdt)

    gates = gates_ref[0].astype(sdt)
    i, f, o, g = (gates[:, :H], gates[:, H:2 * H], gates[:, 2 * H:3 * H],
                  gates[:, 3 * H:])
    c_t = c_ref[0].astype(sdt)
    # c_{t-1}: the block index is clamped to 0 at the first timestep, where
    # the real previous state is c0
    c_prev = jnp.where(s_is_first, c0_ref[:].astype(sdt),
                       c_prev_ref[0].astype(sdt))
    pI = peep_ref[0:1].astype(sdt)
    pF = peep_ref[1:2].astype(sdt)
    pO = peep_ref[2:3].astype(sdt)

    tanh_c = jnp.tanh(c_t)
    dh = dh_out_ref[0].astype(sdt) + dh_scr[:]
    do = dh * tanh_c
    dzo = do * o * (1.0 - o)
    dct = dh * o * (1.0 - tanh_c * tanh_c) + dc_scr[:] + dzo * pO
    dzg = dct * i * (1.0 - g * g)
    dzi = dct * g * i * (1.0 - i)
    dzf = dct * c_prev * f * (1.0 - f)
    dc_prev = dct * f + dzi * pI + dzf * pF
    dz = jnp.concatenate([dzi, dzf, dzo, dzg], axis=1)
    dh_prev = _dot(dz.astype(dt), rw_ref[:], ((1,), (1,)), dt)

    dz_ref[0] = dz.astype(dz_ref.dtype)
    dh_scr[:] = dh_prev
    dc_scr[:] = dc_prev

    @pl.when(s_is_first)
    def _emit_carry_grads():
        dhc0_ref[0] = dh_prev.astype(dhc0_ref.dtype)
        dhc0_ref[1] = dc_prev.astype(dhc0_ref.dtype)


def _lstm_fwd_kernel_masked(xw_ref, rw_ref, peep_ref, h0_ref, c0_ref,
                            m_ref, h_out_ref, hT_ref, cT_ref, hsel_ref,
                            csel_ref, gates_ref, h_scr, c_scr, *,
                            n_out: int, with_stash: bool):
    """Masked forward (reference `LSTMHelpers` masking semantics): a
    masked timestep passes (h, c) through unchanged and emits zeros. The
    carry h_sel = m*h_new + (1-m)*h_prev DIFFERS from the emitted output
    m*h_new, so the training stash keeps both (the backward's h_prev /
    c_prev come from the carries)."""
    from jax.experimental import pallas as pl

    t = pl.program_id(1)
    nt = pl.num_programs(1)
    dt = _mxu_dtype(xw_ref.dtype)
    sdt = _stat_dtype(xw_ref.dtype)
    H = n_out

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:].astype(sdt)
        c_scr[:] = c0_ref[:].astype(sdt)

    c = c_scr[:]
    h_prev = h_scr[:]
    z = xw_ref[0].astype(sdt) + _dot(h_prev.astype(dt), rw_ref[:],
                                     ((1,), (0,)), dt)
    pI = peep_ref[0:1].astype(sdt)
    pF = peep_ref[1:2].astype(sdt)
    pO = peep_ref[2:3].astype(sdt)
    i = jax.nn.sigmoid(z[:, :H] + pI * c)
    f = jax.nn.sigmoid(z[:, H:2 * H] + pF * c)
    g = jnp.tanh(z[:, 3 * H:])
    c_new = f * c + i * g
    o = jax.nn.sigmoid(z[:, 2 * H:3 * H] + pO * c_new)
    h_new = o * jnp.tanh(c_new)
    m = m_ref[0].astype(sdt)
    # hard select on m > 0 (NOT a linear blend): matches the scan path's
    # where() for any mask values; the emitted output scales by m like
    # the reference (`out = h_new * m`). The mask is non-differentiable.
    mpos = m > 0
    h_sel = jnp.where(mpos, h_new, h_prev)
    c_sel = jnp.where(mpos, c_new, c)

    h_out_ref[0] = (h_sel * m).astype(h_out_ref.dtype)
    if with_stash:
        hsel_ref[0] = h_sel.astype(hsel_ref.dtype)
        csel_ref[0] = c_sel.astype(csel_ref.dtype)
        gates_ref[0] = jnp.concatenate([i, f, o, g], axis=1).astype(
            gates_ref.dtype)
    h_scr[:] = h_sel
    c_scr[:] = c_sel

    @pl.when(t == nt - 1)
    def _final_state():
        # the final CARRY differs from the last output under masking:
        # emit it explicitly (the unmasked kernel's h_out[-1] trick
        # would return m*h_new instead of the carried state)
        hT_ref[:] = h_sel.astype(hT_ref.dtype)
        cT_ref[:] = c_sel.astype(cT_ref.dtype)


def _lstm_bwd_kernel_masked(gates_ref, cprev_ref, dh_out_ref,
                            dhT_ref, dcT_ref, m_ref, rw_ref, peep_ref,
                            c0_ref, dz_ref, dhc0_ref, dh_scr, dc_scr,
                            *, n_out: int):
    from jax.experimental import pallas as pl

    t = pl.program_id(1)
    nt = pl.num_programs(1)
    s_is_first = t == nt - 1
    dt = _mxu_dtype(dz_ref.dtype)
    sdt = _stat_dtype(dz_ref.dtype)
    H = n_out

    @pl.when(t == 0)
    def _init():
        dh_scr[:] = dhT_ref[:].astype(sdt)
        dc_scr[:] = dcT_ref[:].astype(sdt)

    gates = gates_ref[0].astype(sdt)
    i, f, o, g = (gates[:, :H], gates[:, H:2 * H], gates[:, 2 * H:3 * H],
                  gates[:, 3 * H:])
    c_prev = jnp.where(s_is_first, c0_ref[:].astype(sdt),
                       cprev_ref[0].astype(sdt))
    m = m_ref[0].astype(sdt)
    # the stash keeps the SELECTED carry; the cell backward needs the
    # candidate cell state, reconstructed from the gates
    c_pre = f * c_prev + i * g
    pI = peep_ref[0:1].astype(sdt)
    pF = peep_ref[1:2].astype(sdt)
    pO = peep_ref[2:3].astype(sdt)

    dhc = dh_scr[:]
    dcc = dc_scr[:]
    # out = h_sel*m; carry h_sel = where(m>0, h_new, h_prev) — the
    # select's transpose routes the whole cotangent to ONE side
    mpos = m > 0
    d_hsel = m * dh_out_ref[0].astype(sdt) + dhc
    zero = jnp.zeros_like(d_hsel)
    dh_new = jnp.where(mpos, d_hsel, zero)
    dh_prev_bypass = jnp.where(mpos, zero, d_hsel)
    dc_new = jnp.where(mpos, dcc, zero)
    dc_prev_bypass = jnp.where(mpos, zero, dcc)

    tanh_c = jnp.tanh(c_pre)
    do = dh_new * tanh_c
    dzo = do * o * (1.0 - o)
    dct = dh_new * o * (1.0 - tanh_c * tanh_c) + dc_new + dzo * pO
    dzg = dct * i * (1.0 - g * g)
    dzi = dct * g * i * (1.0 - i)
    dzf = dct * c_prev * f * (1.0 - f)
    dz = jnp.concatenate([dzi, dzf, dzo, dzg], axis=1)
    dh_prev = _dot(dz.astype(dt), rw_ref[:], ((1,), (1,)), dt) \
        + dh_prev_bypass
    dc_prev = dct * f + dzi * pI + dzf * pF + dc_prev_bypass

    dz_ref[0] = dz.astype(dz_ref.dtype)
    dh_scr[:] = dh_prev
    dc_scr[:] = dc_prev

    @pl.when(s_is_first)
    def _emit_carry_grads():
        dhc0_ref[0] = dh_prev.astype(dhc0_ref.dtype)
        dhc0_ref[1] = dc_prev.astype(dhc0_ref.dtype)


# _vmem_limit() (generation-derived ceiling, kernel_dispatch): the default 16 MiB
# scoped-stack limit caps the batch block at 512 for H=256 (bb=1024
# needs 18.4 MiB of double-buffered xw/gates slabs) and rejects H=1024
# outright (100.1 MiB at bb=1024); the raised ceiling lets the probe
# ladder serve MXU-width hidden sizes, and the fall-through still lands
# on whatever the hardware accepts (bb=2048 at H=1024 wants 145 MiB >
# the physical 128 and falls to 1024)

_BLOCK_CANDIDATES = (2048, 1024, 512, 256, 128, 64, 32, 16, 8)


def _batch_block(B: int) -> Optional[int]:
    """Largest batch block dividing B (the starting candidate — the
    dispatch probes downward from here, see _probed_batch_block)."""
    for bb in _BLOCK_CANDIDATES:
        if B % bb == 0:
            return bb
    return None


def _fwd_call(xw, rw, peep, h0, c0, *, bb: int, with_stash: bool,
              interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, B, G = xw.shape
    H = G // 4
    sdt = _stat_dtype(xw.dtype)
    kernel = functools.partial(_lstm_fwd_kernel, n_out=H,
                               with_stash=with_stash)
    blk = lambda shape: pl.BlockSpec(shape, lambda b, t: (t, b, 0))
    const2 = lambda shape: pl.BlockSpec(shape, lambda b, t: (b, 0))
    small = pl.BlockSpec((1, 1, 1), lambda b, t: (0, 0, 0))
    h_out, cT, c_stash, gates = pl.pallas_call(
        kernel,
        grid=(B // bb, T),
        in_specs=[
            blk((1, bb, G)),                                   # xw[t]
            pl.BlockSpec((H, G), lambda b, t: (0, 0)),         # RW
            pl.BlockSpec((3, H), lambda b, t: (0, 0)),         # peepholes
            const2((bb, H)),                                   # h0
            const2((bb, H)),                                   # c0
        ],
        out_specs=[blk((1, bb, H)),
                   const2((bb, H)),
                   blk((1, bb, H)) if with_stash else small,
                   blk((1, bb, G)) if with_stash else small],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H), xw.dtype),
            jax.ShapeDtypeStruct((B, H), xw.dtype),
            jax.ShapeDtypeStruct((T, B, H) if with_stash else (1, 1, 1),
                                 xw.dtype),
            jax.ShapeDtypeStruct((T, B, G) if with_stash else (1, 1, 1),
                                 xw.dtype)],
        scratch_shapes=[pltpu.VMEM((bb, H), sdt),
                        pltpu.VMEM((bb, H), sdt)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=_vmem_limit()),
        interpret=interpret,
    )(xw, rw, peep, h0, c0)
    return h_out, cT, c_stash, gates


def _bwd_call(gates, c_stash, dh_out, dcT, rw, peep, c0, *, bb: int,
              interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, B, G = gates.shape
    H = G // 4
    sdt = _stat_dtype(gates.dtype)
    kernel = functools.partial(_lstm_bwd_kernel, n_out=H)
    rev = lambda shape: pl.BlockSpec(shape, lambda b, t: (T - 1 - t, b, 0))
    const2 = lambda shape: pl.BlockSpec(shape, lambda b, t: (b, 0))
    dz, dhc0 = pl.pallas_call(
        kernel,
        grid=(B // bb, T),
        in_specs=[
            rev((1, bb, G)),                                   # gates[s]
            rev((1, bb, H)),                                   # c[s]
            # c[s-1] (block index clamped at s == 0; kernel swaps in c0)
            pl.BlockSpec((1, bb, H),
                         lambda b, t: (jnp.maximum(T - 2 - t, 0), b, 0)),
            rev((1, bb, H)),                                   # dh_out[s]
            const2((bb, H)),                                   # dcT
            pl.BlockSpec((H, G), lambda b, t: (0, 0)),         # RW
            pl.BlockSpec((3, H), lambda b, t: (0, 0)),         # peepholes
            const2((bb, H)),                                   # c0
        ],
        out_specs=[rev((1, bb, G)),
                   pl.BlockSpec((2, bb, H), lambda b, t: (0, b, 0))],
        out_shape=[jax.ShapeDtypeStruct((T, B, G), gates.dtype),
                   jax.ShapeDtypeStruct((2, B, H), sdt)],
        scratch_shapes=[pltpu.VMEM((bb, H), sdt),
                        pltpu.VMEM((bb, H), sdt)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=_vmem_limit()),
        interpret=interpret,
    )(gates, c_stash, c_stash, dh_out, dcT, rw, peep, c0)
    return dz, dhc0


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _lstm_core(xw, rw, peep, h0, c0, interpret, bb):
    """(T,B,4H) projected inputs -> ((T,B,H) hidden states, cT (B,H))."""
    h_out, cT, _, _ = _fwd_call(xw, rw, peep, h0, c0, bb=bb,
                                with_stash=False, interpret=interpret)
    return h_out, cT


def _lstm_core_fwd(xw, rw, peep, h0, c0, interpret, bb):
    h_out, cT, c_stash, gates = _fwd_call(xw, rw, peep, h0, c0, bb=bb,
                                          with_stash=True,
                                          interpret=interpret)
    return (h_out, cT), (gates, c_stash, h_out, rw, peep, h0, c0)


def _lstm_core_bwd(interpret, bb, res, cots):
    dh_out, dcT = cots
    gates, c_stash, h_out, rw, peep, h0, c0 = res
    T, B, G = gates.shape
    H = G // 4
    sdt = _stat_dtype(gates.dtype)
    dz, dhc0 = _bwd_call(gates, c_stash, dh_out, dcT.astype(gates.dtype),
                         rw, peep, c0, bb=bb, interpret=interpret)
    # batched contractions over the full (T*B) slab — big single XLA GEMMs,
    # the MXU-friendly shape the per-step kernel deliberately leaves out
    dt = _mxu_dtype(gates.dtype)
    h_prev = jnp.concatenate([h0[None], h_out[:-1]], axis=0)
    drw = _dot(h_prev.reshape(T * B, H).astype(dt).T,
               dz.reshape(T * B, G).astype(dt), ((1,), (0,)), dt)
    c_prev = jnp.concatenate([c0[None], c_stash[:-1]], axis=0).astype(sdt)
    dzf32 = dz.astype(sdt)
    dpi = jnp.sum(dzf32[..., :H] * c_prev, axis=(0, 1))
    dpf = jnp.sum(dzf32[..., H:2 * H] * c_prev, axis=(0, 1))
    dpo = jnp.sum(dzf32[..., 2 * H:3 * H] * c_stash.astype(sdt),
                  axis=(0, 1))
    dpeep = jnp.stack([dpi, dpf, dpo]).astype(peep.dtype)
    return (dz, drw.astype(rw.dtype), dpeep,
            dhc0[0].astype(h0.dtype), dhc0[1].astype(c0.dtype))


_lstm_core.defvjp(_lstm_core_fwd, _lstm_core_bwd)


def _fwd_call_masked(xw, rw, peep, h0, c0, mask, *, bb: int,
                     with_stash: bool, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, B, G = xw.shape
    H = G // 4
    sdt = _stat_dtype(xw.dtype)
    kernel = functools.partial(_lstm_fwd_kernel_masked, n_out=H,
                               with_stash=with_stash)
    blk = lambda shape: pl.BlockSpec(shape, lambda b, t: (t, b, 0))
    const2 = lambda shape: pl.BlockSpec(shape, lambda b, t: (b, 0))
    small = pl.BlockSpec((1, 1, 1), lambda b, t: (0, 0, 0))
    stash = (T, B, H) if with_stash else (1, 1, 1)
    outs = pl.pallas_call(
        kernel,
        grid=(B // bb, T),
        in_specs=[
            blk((1, bb, G)),                                   # xw[t]
            pl.BlockSpec((H, G), lambda b, t: (0, 0)),         # RW
            pl.BlockSpec((3, H), lambda b, t: (0, 0)),         # peepholes
            const2((bb, H)),                                   # h0
            const2((bb, H)),                                   # c0
            blk((1, bb, H)),                                   # mask[t]
        ],
        out_specs=[blk((1, bb, H)),
                   const2((bb, H)), const2((bb, H)),
                   blk((1, bb, H)) if with_stash else small,
                   blk((1, bb, H)) if with_stash else small,
                   blk((1, bb, G)) if with_stash else small],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H), xw.dtype),         # masked out
            jax.ShapeDtypeStruct((B, H), xw.dtype),            # hT carry
            jax.ShapeDtypeStruct((B, H), xw.dtype),            # cT carry
            jax.ShapeDtypeStruct(stash, xw.dtype),             # h_sel
            jax.ShapeDtypeStruct(stash, xw.dtype),             # c_sel
            jax.ShapeDtypeStruct((T, B, G) if with_stash else (1, 1, 1),
                                 xw.dtype)],                   # gates
        scratch_shapes=[pltpu.VMEM((bb, H), sdt),
                        pltpu.VMEM((bb, H), sdt)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=_vmem_limit()),
        interpret=interpret,
    )(xw, rw, peep, h0, c0, mask)
    return outs


def _bwd_call_masked(gates, c_sel, dh_out, dhT, dcT, mask, rw, peep, c0,
                     *, bb: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, B, G = gates.shape
    H = G // 4
    sdt = _stat_dtype(gates.dtype)
    kernel = functools.partial(_lstm_bwd_kernel_masked, n_out=H)
    rev = lambda shape: pl.BlockSpec(shape, lambda b, t: (T - 1 - t, b, 0))
    const2 = lambda shape: pl.BlockSpec(shape, lambda b, t: (b, 0))
    dz, dhc0 = pl.pallas_call(
        kernel,
        grid=(B // bb, T),
        in_specs=[
            rev((1, bb, G)),                                   # gates[s]
            # c_sel shifted: c_prev[s] (clamped at s == 0; kernel uses c0)
            pl.BlockSpec((1, bb, H),
                         lambda b, t: (jnp.maximum(T - 2 - t, 0), b, 0)),
            rev((1, bb, H)),                                   # dh_out[s]
            const2((bb, H)),                                   # dhT
            const2((bb, H)),                                   # dcT
            rev((1, bb, H)),                                   # mask[s]
            pl.BlockSpec((H, G), lambda b, t: (0, 0)),         # RW
            pl.BlockSpec((3, H), lambda b, t: (0, 0)),         # peepholes
            const2((bb, H)),                                   # c0
        ],
        out_specs=[rev((1, bb, G)),
                   pl.BlockSpec((2, bb, H), lambda b, t: (0, b, 0))],
        out_shape=[jax.ShapeDtypeStruct((T, B, G), gates.dtype),
                   jax.ShapeDtypeStruct((2, B, H), sdt)],
        scratch_shapes=[pltpu.VMEM((bb, H), sdt),
                        pltpu.VMEM((bb, H), sdt)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=_vmem_limit()),
        interpret=interpret,
    )(gates, c_sel, dh_out, dhT, dcT, mask, rw, peep, c0)
    return dz, dhc0


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def _lstm_core_masked(xw, rw, peep, h0, c0, mask, interpret, bb):
    """Masked variant: returns (masked outputs (T,B,H), hT, cT)."""
    h_out, hT, cT, _, _, _ = _fwd_call_masked(
        xw, rw, peep, h0, c0, mask, bb=bb, with_stash=False,
        interpret=interpret)
    return h_out, hT, cT


def _lstm_core_masked_fwd(xw, rw, peep, h0, c0, mask, interpret, bb):
    h_out, hT, cT, h_sel, c_sel, gates = _fwd_call_masked(
        xw, rw, peep, h0, c0, mask, bb=bb, with_stash=True,
        interpret=interpret)
    return (h_out, hT, cT), (gates, h_sel, c_sel, mask, rw, peep, h0, c0)


def _lstm_core_masked_bwd(interpret, bb, res, cots):
    dh_out, dhT, dcT = cots
    gates, h_sel, c_sel, mask, rw, peep, h0, c0 = res
    T, B, G = gates.shape
    H = G // 4
    sdt = _stat_dtype(gates.dtype)
    dz, dhc0 = _bwd_call_masked(gates, c_sel, dh_out,
                                dhT.astype(gates.dtype),
                                dcT.astype(gates.dtype), mask, rw, peep,
                                c0, bb=bb, interpret=interpret)
    dt = _mxu_dtype(gates.dtype)
    h_prev = jnp.concatenate([h0[None], h_sel[:-1]], axis=0)
    drw = _dot(h_prev.reshape(T * B, H).astype(dt).T,
               dz.reshape(T * B, G).astype(dt), ((1,), (0,)), dt)
    c_prev = jnp.concatenate([c0[None], c_sel[:-1]], axis=0).astype(sdt)
    dzf32 = dz.astype(sdt)
    gi = gates[..., :H].astype(sdt)
    gf = gates[..., H:2 * H].astype(sdt)
    gg = gates[..., 3 * H:].astype(sdt)
    # candidate cell state reconstructed (the stash keeps the carry)
    c_pre = gf * c_prev + gi * gg
    dpi = jnp.sum(dzf32[..., :H] * c_prev, axis=(0, 1))
    dpf = jnp.sum(dzf32[..., H:2 * H] * c_prev, axis=(0, 1))
    dpo = jnp.sum(dzf32[..., 2 * H:3 * H] * c_pre, axis=(0, 1))
    dpeep = jnp.stack([dpi, dpf, dpo]).astype(peep.dtype)
    return (dz, drw.astype(rw.dtype), dpeep,
            dhc0[0].astype(h0.dtype), dhc0[1].astype(c0.dtype),
            jnp.zeros_like(mask))


_lstm_core_masked.defvjp(_lstm_core_masked_fwd, _lstm_core_masked_bwd)


_probe_cache: dict = {}  # (dtype name, batch block, H, masked) -> verdict


def _platform_ok() -> bool:
    if os.environ.get("DL4J_TPU_NO_PALLAS_LSTM"):
        return False
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def _eager_probe(dtype, bb, H, masked: bool = False) -> bool:
    """Compile + run fwd AND bwd once at the TILE configuration the real
    call will use — (T=2, B=batch block, H) — outside any trace, so a
    Mosaic failure becomes a silent scan fallback instead of an outer-jit
    compile crash (same rationale as the flash-attention probe). The block
    shapes are what Mosaic compiles; T and the number of batch blocks only
    set the grid length, so a tiny-T probe proves the real kernel without
    allocating GB-scale probe buffers (the real (T, B, 4H) could rival the
    training step itself near HBM capacity). `masked` probes the masked
    kernel pair instead."""
    T = 2
    kx, kr = jax.random.split(jax.random.PRNGKey(0))
    xw = jax.random.normal(kx, (T, bb, 4 * H), dtype)
    rw = jax.random.normal(kr, (H, 4 * H), dtype) * 0.05
    peep = jnp.zeros((3, H), dtype)
    z = jnp.zeros((bb, H), dtype)

    def loss(xw, rw):
        if masked:
            m = jnp.ones((T, bb, H), dtype)
            h, hT, cT = _lstm_core_masked(xw, rw, peep, z, z, m, False, bb)
            return (jnp.sum(h.astype(jnp.float32))
                    + jnp.sum(hT.astype(jnp.float32))
                    + jnp.sum(cT.astype(jnp.float32)))
        h, cT = _lstm_core(xw, rw, peep, z, z, False, bb)
        return jnp.sum(h.astype(jnp.float32)) + jnp.sum(
            cT.astype(jnp.float32))

    g = jax.grad(loss, argnums=(0, 1))(xw, rw)
    return bool(jnp.all(jnp.isfinite(g[1].astype(jnp.float32))))


def _probed_batch_block(dtype, B: int, H: int, masked: bool) -> Optional[int]:
    """Largest batch block dividing B whose (compile + run) probe passes.
    Falls through to the next smaller candidate on failure — a bb that
    overflows VMEM at a large H must not disqualify the kernel outright
    (per-candidate verdicts are cached, so the fallback probes run once
    per shape class)."""
    for bb in _BLOCK_CANDIDATES:
        if B % bb:
            continue
        key = (jnp.dtype(dtype).name, bb, H, masked)
        if _probe_verdict(_probe_cache, key, _eager_probe,
                          (dtype, bb, H, masked), "pallas fused LSTM"):
            return bb
    return None


def lstm_fused_or_none(x, W, RW, b, peephole, h0, c0, *,
                       gate_is_sigmoid: bool, cell_is_tanh: bool,
                       mask=None, reverse: bool = False,
                       interpret: bool = False
                       ) -> Optional[Tuple[jnp.ndarray,
                                           Tuple[jnp.ndarray,
                                                 jnp.ndarray]]]:
    """Fused-path dispatch: returns (out (B,T,H), (hT, cT)) or None when
    the kernel can't serve this call (the reflective cuDNN-helper
    contract). `interpret=True` runs the Pallas interpreter (any platform;
    used by parity/gradient-check tests)."""
    B, T, _ = x.shape
    H = RW.shape[0]
    f64 = (jnp.float64,) if interpret else ()
    if (not gate_is_sigmoid or not cell_is_tanh
            or H % 128 or T < 2 or _batch_block(B) is None
            or x.dtype not in (jnp.float32, jnp.bfloat16, *f64)):
        return None
    if not interpret and not _platform_ok():
        return None
    masked = mask is not None
    if interpret:
        bb = _batch_block(B)  # no probe: the interpreter always works
    else:
        bb = _probed_batch_block(x.dtype, B, H, masked)
        if bb is None:
            return None
    # time-major input projection: ONE big GEMM, with the transpose to the
    # layout the kernel streams fused into the GEMM output
    xw = jnp.einsum("bti,ig->tbg", x, W) + b
    if reverse:
        xw = xw[::-1]
    if peephole is None:
        peep = jnp.zeros((3, H), x.dtype)
    else:
        peep = jnp.stack(peephole).astype(x.dtype)
    zh = jnp.zeros((B, H), x.dtype)
    h0 = zh if h0 is None else h0.astype(x.dtype)
    c0 = zh if c0 is None else c0.astype(x.dtype)
    try:
        if masked:
            # (B, T) -> an (T, B, H) slab the kernel streams per step
            # (the lane-broadcast layout Mosaic tiles natively)
            m = jnp.swapaxes(jnp.asarray(mask), 0, 1)
            if reverse:
                m = m[::-1]
            m_slab = jnp.broadcast_to(m[..., None].astype(x.dtype),
                                      (T, B, H))
            h_tbh, hT, cT = _lstm_core_masked(xw, RW, peep, h0, c0,
                                              m_slab, interpret, bb)
        else:
            h_tbh, cT = _lstm_core(xw, RW, peep, h0, c0, interpret, bb)
            hT = None
    except Exception as e:  # per-shape staging failure: fall back
        logger.warning("pallas fused LSTM declined for shape %s (%s)",
                       x.shape, e)
        return None
    if reverse:
        h_tbh = h_tbh[::-1]
        if hT is None:
            hT = h_tbh[0]
    elif hT is None:
        hT = h_tbh[-1]
    return jnp.swapaxes(h_tbh, 0, 1), (hT, cT)


__all__ = ["lstm_fused_or_none"]
