"""Pallas TPU paged-attention decode kernel: walk the page table in
place, no dense gather.

The paged decode engine (`serving/decode_engine.py`) stores each block's
KV cache as a pool of fixed-size pages in the r4 decode layouts — K
`(P+1, Hkv, hd, page)`, V `(P+1, Hkv, page, hd)`, page 0 the reserved
trash page — with an int32 per-slot page table mapping logical page
index to pool page id. The portable XLA path
(`ops.attention.paged_gather` + `cached_attention_step` /
`cached_attention_chunk`) first REASSEMBLES each slot's pages into a
dense transient, then attends: every cache byte moves through HBM twice
(pool → transient write, transient → compute read) on a path that is
cache-bandwidth-bound by construction. This kernel is the PagedAttention
move (Kwon et al., SOSP 2023): the page ids ride the grid as
scalar-prefetch operands (`pltpu.PrefetchScalarGridSpec`), the BlockSpec
index map dereferences `page_table[slot, j]` directly, and the pipeline
DMAs each referenced page from the pool into VMEM exactly once — the
flash-style online-softmax accumulator (the `ops/pallas_attention.py`
recurrence) runs over pages in logical order with no intermediate
materialization.

One kernel serves every paged shape of the serving hot path via the
chunk width `C` of the query block `(S, C, H, hd)`:

- `C == 1`: the decode step (`cached_attention_step` semantics — each
  slot's single query at position `pos[s]` attends to cache entries
  `<= pos[s]`);
- `C == k+1`: the speculative verify chunk
  (`_verify_block_attention` semantics);
- `C == prefill_chunk`: the chunked-prefill suffix
  (`cached_attention_chunk` semantics, S=1 per dispatch).

All three mask identically because the serving paths only ever issue
CONTIGUOUS query positions: row `c` of slot `s` attends to entries
`<= positions[s] + c`. GQA contracts the un-repeated `Hkv` pool heads
against query groups of `G = H // Hkv` heads folded into the matmul's
sublane axis. The trash-page convention holds for free: unallocated
page-table entries point at page 0, whose logical positions are always
past the slot's limit and therefore masked; inactive lanes (optional
`active` mask) skip the page loop entirely and emit zeros via the
`l == 0` finalization, the same discipline the flash kernel uses for
fully-masked rows.

Dispatch rides the `ops/kernel_dispatch.py` contract: the probe
compiles AND runs the kernel at the exact shape class and CHECKS the
output against the gather+dense reference (a miscompiling Mosaic
toolchain degrades to the XLA path, never to wrong tokens); VMEM
residency (double-buffered K/V page tiles + accumulators) is sized
against the generation-derived `vmem_limit_bytes()` ceiling and
oversized shapes decline; `DL4J_TPU_NO_PALLAS_PAGED_ATTENTION` forces
the gather path (the bench's A/B kill switch); CPU backends never
dispatch, so tier-1 runs the XLA numerics bit-for-bit unchanged.
"""
from __future__ import annotations

import functools
import logging
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.kernel_dispatch import (
    dot as _dot,
    mxu_dtype as _mxu_dtype,
    probe_verdict as _probe_verdict,
    stat_dtype as _stat_dtype,
    tpu_compiler_params as _compiler_params,
    vmem_limit_bytes as _vmem_limit,
)

logger = logging.getLogger("deeplearning4j_tpu")

NEG_INF = -1e30  # matches ops/attention.py: exp()/where() stay NaN-free


def _paged_kernel(pt_ref, p0_ref, gate_ref, q_ref, k_ref, v_ref, *rest,
                  page: int, C: int, G: int, Hkv: int, hd: int,
                  sm_scale: float, quantized: bool = False):
    """Grid (S, n_pages), pages sequential: one (C·G, page) score tile
    per KV head per page, accumulated with the online-softmax
    recurrence in VMEM scratch. Scalar-prefetch refs: the page table
    (drives the K/V BlockSpec index maps — the in-place walk), the
    per-slot start positions, and the active gate.

    `quantized=True` is the int8-KV variant (ROADMAP item 1's
    "dequant inside the page loop"): `k_ref`/`v_ref` hold int8 pages —
    HALF the DMA bytes of bf16, the decode path's bandwidth bound on
    top of PR 9's no-gather win — and two extra (1, Hkv, page) f32
    scale refs ride the same page-table index map. Dequant happens
    in VMEM right before each matmul: one f32 multiply per element by
    the per-(head, position) scale row, then the cast to the MXU feed
    dtype. Numerics are pinned against the `paged_gather_quant` + dense
    reference by the dispatch probe and the interpret-mode tests."""
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, o_ref, acc_scr, m_scr, l_scr = rest
    else:
        o_ref, acc_scr, m_scr, l_scr = rest

    s = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    CG = C * G

    @pl.when(j == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    p0 = p0_ref[s]
    # skip pages whose every position is past the last query's limit
    # (p0 + C - 1) and skip inactive lanes outright: their l stays 0 and
    # the finalize emits exact zeros (the flash kernel's fully-masked-row
    # discipline). The DMA for skipped steps still lands (plain indexing
    # + compute skip measured faster than index-map clamping for the
    # flash kernel; the same trade holds here) — correctness never
    # depends on it because masking is positional.
    @pl.when((j * page <= p0 + C - 1) & (gate_ref[s] != 0))
    def _step():
        dt = _mxu_dtype(q_ref.dtype)
        q = q_ref[0]                                       # (C, H, hd)
        kpos = j * page + jax.lax.broadcasted_iota(
            jnp.int32, (CG, page), 1)
        rowc = jax.lax.broadcasted_iota(jnp.int32, (CG, page), 0) // G
        mask = kpos <= p0 + rowc
        for h in range(Hkv):
            # query heads h*G..(h+1)*G-1 share KV head h; fold (C, G)
            # into the sublane axis so one matmul serves the group
            qh = q[:, h * G:(h + 1) * G, :].reshape(CG, hd).astype(dt)
            if quantized:
                # dequant-in-VMEM: int8 page × per-position f32 scale
                # row, then the MXU-feed cast — the DMA moved 1 byte
                # per element, the matmul sees full-precision values
                ks = ks_ref[0, h].reshape(1, page)
                kh = (k_ref[0, h].astype(jnp.float32) * ks).astype(dt)
                vs = vs_ref[0, h].reshape(page, 1)
                vh = (v_ref[0, h].astype(jnp.float32) * vs).astype(dt)
            else:
                kh = k_ref[0, h].astype(dt)                # (hd, page)
                vh = v_ref[0, h].astype(dt)                # (page, hd)
            sc = _dot(qh, kh, ((1,), (0,)), dt) * sm_scale
            sc = jnp.where(mask, sc, NEG_INF)
            m_prev = m_scr[h][:, :1]
            l_prev = l_scr[h][:, :1]
            m_blk = jnp.max(sc, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_blk)
            p = jnp.exp(sc - m_new)
            # fully-masked-so-far rows sit at m ~ NEG_INF: zero their
            # weights so l stays 0 and finalize maps them to output 0
            p = jnp.where(sc <= NEG_INF / 2, 0.0, p)
            corr = jnp.exp(m_prev - m_new)
            l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
            acc_scr[h] = acc_scr[h] * corr + _dot(p.astype(dt), vh,
                                                  ((1,), (0,)), dt)
            m_scr[h] = jnp.broadcast_to(m_new, m_scr[h].shape)
            l_scr[h] = jnp.broadcast_to(l_new, l_scr[h].shape)

    @pl.when(j == nj - 1)
    def _finalize():
        for h in range(Hkv):
            l = l_scr[h][:, :1]
            o = jnp.where(l > 0, acc_scr[h] / jnp.where(l > 0, l, 1.0),
                          0.0)
            o_ref[0, :, h * G:(h + 1) * G, :] = \
                o.reshape(C, G, hd).astype(o_ref.dtype)


def paged_attention(q: jnp.ndarray, k_pool: jnp.ndarray,
                    v_pool: jnp.ndarray, page_table: jnp.ndarray,
                    positions: jnp.ndarray, *,
                    k_scale: Optional[jnp.ndarray] = None,
                    v_scale: Optional[jnp.ndarray] = None,
                    active: Optional[jnp.ndarray] = None,
                    interpret: bool = False) -> jnp.ndarray:
    """Paged decode/verify/chunk attention, streamed from the pool.

    `q`: (S, C, H, hd) — C contiguous query tokens per slot (C=1 for
    the decode step). `k_pool`/`v_pool`: (P+1, Hkv, hd, page) /
    (P+1, Hkv, page, hd) — the resident pool layouts, page 0 = trash.
    `page_table`: (S, n_pages) int32 pool page ids in logical order
    (unallocated entries 0). `positions`: (S,) int32 — row c of slot s
    attends to cache entries `<= positions[s] + c`, exactly
    `cached_attention_step` (C=1, positions=pos) and
    `cached_attention_chunk` (positions=first query position) over the
    gathered view. `active`: optional (S,) bool — False lanes skip all
    compute and emit zeros (their output is discarded downstream by the
    engine's masking; the gather path computes garbage-but-finite
    values for them instead, equally discarded).

    int8 pools pass `k_scale`/`v_scale` ((P+1, Hkv, page) f32): the
    scale pages ride the SAME page-table index map as the payload
    pages and the kernel dequantizes in VMEM inside the page loop —
    the `serving/quantize.py` tier's fast path.

    Returns (S, C, H, hd) in q.dtype.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, C, H, hd = q.shape
    _, Hkv, _, page = k_pool.shape
    n_pages = page_table.shape[1]
    G = H // Hkv
    quantized = k_scale is not None
    sdt = _stat_dtype(q.dtype)
    gate = jnp.ones((S,), jnp.int32) if active is None \
        else jnp.asarray(active).astype(jnp.int32)
    kernel = functools.partial(
        _paged_kernel, page=page, C=C, G=G, Hkv=Hkv, hd=hd,
        sm_scale=1.0 / float(hd) ** 0.5, quantized=quantized)
    in_specs = [
        pl.BlockSpec((1, C, H, hd),
                     lambda s, j, pt, p0, g: (s, 0, 0, 0)),
        # THE page-table walk: the block index map dereferences the
        # prefetched table, so the pipeline DMAs pool page
        # `page_table[s, j]` straight into VMEM — no dense transient
        pl.BlockSpec((1, Hkv, hd, page),
                     lambda s, j, pt, p0, g: (pt[s, j], 0, 0, 0)),
        pl.BlockSpec((1, Hkv, page, hd),
                     lambda s, j, pt, p0, g: (pt[s, j], 0, 0, 0)),
    ]
    operands = [page_table.astype(jnp.int32),
                positions.astype(jnp.int32), gate, q, k_pool, v_pool]
    if quantized:
        # the scale pages walk the same table: one (Hkv, page) f32 tile
        # per referenced page, prefetched alongside its int8 payload
        in_specs += [
            pl.BlockSpec((1, Hkv, page),
                         lambda s, j, pt, p0, g: (pt[s, j], 0, 0)),
            pl.BlockSpec((1, Hkv, page),
                         lambda s, j, pt, p0, g: (pt[s, j], 0, 0)),
        ]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(S, n_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, C, H, hd),
                               lambda s, j, pt, p0, g: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hkv, C * G, hd), sdt),   # unnormalised output
            pltpu.VMEM((Hkv, C * G, 128), sdt),  # running max m
            pltpu.VMEM((Hkv, C * G, 128), sdt),  # running denom l
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, C, H, hd), q.dtype),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=_vmem_limit()),
        interpret=interpret,
    )(*operands)


def vmem_bytes_estimate(C: int, H: int, Hkv: int, hd: int, page: int,
                        itemsize: int, kv_itemsize: Optional[int] = None
                        ) -> int:
    """Resident VMEM of one grid step: double-buffered q/K/V/out tiles
    plus the f32 accumulator scratch. Used to decline shapes that
    cannot fit under the generation-derived ceiling before Mosaic
    discovers it mid-serving. `kv_itemsize` prices the K/V page tiles
    separately from the q/out tiles (int8 pools: 1 byte per element
    plus the double-buffered f32 scale tiles); default: `itemsize`."""
    CG = C * (H // Hkv)
    kvi = itemsize if kv_itemsize is None else kv_itemsize
    tiles = 2 * itemsize * 2 * C * H * hd             # q + out
    tiles += 2 * kvi * 2 * Hkv * hd * page            # K + V page tiles
    if kv_itemsize == 1:
        tiles += 2 * 4 * 2 * Hkv * page               # f32 scale tiles
    scratch = 4 * (Hkv * CG * hd + 2 * Hkv * CG * 128)
    return tiles + scratch


_probe_cache: dict = {}  # (dtype, C, H, Hkv, hd, page) -> verdict


def _platform_supported() -> bool:
    import os

    if os.environ.get("DL4J_TPU_NO_PALLAS_PAGED_ATTENTION"):
        return False  # forced gather fallback (A/B benches, tests)
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def _int8_kv_allowed() -> bool:
    """The int8-KV kill switch at the DISPATCH layer: with
    ``DL4J_TPU_NO_INT8_KV=1`` the int8 kernel declines and callers run
    the `paged_gather_quant` + dense reference. (The engine honors the
    same switch at BUILD time — pools stay full-precision — so flipping
    it before construction is the bench's whole-tier A/B lever; here it
    additionally protects a live engine whose pools are already
    int8.)"""
    import os

    return os.environ.get("DL4J_TPU_NO_INT8_KV", "") \
        not in ("1", "true", "yes")


def _eager_probe(dtype, C: int, H: int, Hkv: int, hd: int, page: int,
                 quantized: bool = False) -> bool:
    """Compile + run the kernel once at this exact shape class on tiny
    concrete pools, out of trace, and CHECK the output against the
    gather+dense reference — the dispatch contract's parity-probed
    variant: a toolchain that compiles-but-miscompiles falls back to
    XLA instead of serving wrong tokens. The int8 variant probes with
    int8 pools + f32 scale pages against the `paged_gather_quant`
    oracle, so the page-loop dequant is parity-checked before the
    first live dispatch."""
    import numpy as np

    from deeplearning4j_tpu.ops.attention import (
        cached_attention_chunk,
        paged_gather,
        paged_gather_quant,
    )

    S, n_pages = 2, 2
    P = S * n_pages
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((S, C, H, hd)), dtype)
    pt = jnp.asarray(1 + np.arange(P).reshape(S, n_pages), jnp.int32)
    p0 = jnp.asarray([page - 1, 2 * page - 1], jnp.int32)
    qpos = p0[:, None] + jnp.arange(C)[None, :]
    if quantized:
        k_pool = jnp.asarray(rng.integers(
            -127, 128, (P + 1, Hkv, hd, page)), jnp.int8)
        v_pool = jnp.asarray(rng.integers(
            -127, 128, (P + 1, Hkv, page, hd)), jnp.int8)
        k_scale = jnp.asarray(
            rng.uniform(0.005, 0.02, (P + 1, Hkv, page)), jnp.float32)
        v_scale = jnp.asarray(
            rng.uniform(0.005, 0.02, (P + 1, Hkv, page)), jnp.float32)
        out = np.asarray(paged_attention(
            q, k_pool, v_pool, pt, p0, k_scale=k_scale,
            v_scale=v_scale))
        kd, vd = paged_gather_quant(k_pool, v_pool, k_scale, v_scale,
                                    pt, dtype)
    else:
        k_pool = jnp.asarray(
            rng.standard_normal((P + 1, Hkv, hd, page)), dtype)
        v_pool = jnp.asarray(
            rng.standard_normal((P + 1, Hkv, page, hd)), dtype)
        out = np.asarray(paged_attention(q, k_pool, v_pool, pt, p0))
        kd, vd = paged_gather(k_pool, v_pool, pt)
    ref = np.asarray(jax.vmap(cached_attention_chunk)(q, kd, vd, qpos))
    ref = ref.reshape(S, C, H, hd)
    if not np.all(np.isfinite(out.astype(np.float32))):
        return False
    tol = 2e-2 if dtype == jnp.bfloat16 else 2e-4
    return bool(np.allclose(out.astype(np.float32),
                            ref.astype(np.float32), atol=tol, rtol=tol))


def paged_attention_or_none(q, k_pool, v_pool, page_table, positions,
                            active=None, k_scale=None,
                            v_scale=None) -> Optional[jnp.ndarray]:
    """Dispatch probe (the reflective cuDNN-helper load): returns None
    when the kernel can't serve this call — CPU backend, kill switch,
    unsupported dtype, VMEM overflow at this shape — or when the shape
    class failed its compile+parity probe. Callers fall back to
    `paged_gather` + the dense step/chunk (`paged_gather_quant` for
    int8 pools). The int8 variant (scales present) is additionally
    gated by ``DL4J_TPU_NO_INT8_KV`` and probes its own shape-class
    key."""
    S, C, H, hd = q.shape
    _, Hkv, _, page = k_pool.shape
    quantized = k_scale is not None
    if not _platform_supported() \
            or q.dtype not in (jnp.float32, jnp.bfloat16) \
            or H % Hkv:
        return None
    if quantized and not _int8_kv_allowed():
        return None
    kv_itemsize = 1 if quantized else q.dtype.itemsize
    est = vmem_bytes_estimate(C, H, Hkv, hd, page, q.dtype.itemsize,
                              kv_itemsize=kv_itemsize)
    if est > _vmem_limit():
        logger.warning(
            "pallas paged-attention declined: shape (C=%d, H=%d, Hkv=%d, "
            "hd=%d, page=%d) needs ~%d MiB VMEM > %d MiB ceiling; using "
            "the gather path", C, H, Hkv, hd, page, est >> 20,
            _vmem_limit() >> 20)
        return None
    key = (jnp.dtype(q.dtype).name, C, H, Hkv, hd, page,
           "int8" if quantized else "dense")
    if not _probe_verdict(_probe_cache, key, _eager_probe,
                          (q.dtype, C, H, Hkv, hd, page, quantized),
                          "pallas paged-attention"):
        return None
    try:
        return paged_attention(q, k_pool, v_pool, page_table, positions,
                               k_scale=k_scale, v_scale=v_scale,
                               active=active)
    except Exception as e:  # per-shape staging failure: fall back
        logger.warning("pallas paged-attention declined for shape %s "
                       "(%s)", q.shape, e)
        return None
