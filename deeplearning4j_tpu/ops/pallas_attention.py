"""Pallas TPU flash-attention forward kernel.

The role of `deeplearning4j-cuda`'s helpers in the reference (SURVEY §2.3):
a hand-written accelerator kernel behind the same contract as the built-in
path, picked when available, falling through silently otherwise
(`ConvolutionLayer.initializeHelper`, `ConvolutionLayer.java:69-79`). Here
the built-in paths are `ops/attention.py` full/blockwise attention (XLA);
this module is the Mosaic/Pallas fast path for the no-mask case.

Kernel shape: grid (B·H, Tq/block_q, Tk/block_k), innermost KV dimension
sequential so the online-softmax accumulator lives in VMEM scratch across
KV steps (m/l/acc — the flash recurrence). Q·Kᵀ and P·V hit the MXU; the
rescale/exp traffic stays in VMEM, so HBM sees each K/V tile exactly once.
"""
from __future__ import annotations

import functools
import logging
from typing import Optional

import jax
import jax.numpy as jnp

logger = logging.getLogger("deeplearning4j_tpu")

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  sm_scale: float, causal: bool, block_q: int, block_k: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # with causal masking, KV blocks strictly above the diagonal contribute
    # nothing — skip their compute entirely
    needed = (not causal) or (ki * block_k <= qi * block_q + block_q - 1)

    @pl.when(needed)
    def _step():
        # bf16 operands into the MXU (its native feed width), f32 accumulate
        q = q_ref[0].astype(jnp.bfloat16)  # (block_q, D)
        k = k_ref[0].astype(jnp.bfloat16)  # (block_k, D)
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (bq, bk)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, NEG_INF)
        m_prev = m_scr[:, :1]                                 # (bq, 1)
        l_prev = l_scr[:, :1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new)
        # rows fully masked so far sit at m ~ NEG_INF: zero their weights so
        # l stays 0 (finalize maps them to output 0, matching
        # attention.attention_finalize)
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(jnp.bfloat16), v_ref[0].astype(jnp.bfloat16),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        o = jnp.where(l > 0, acc_scr[:] / jnp.where(l > 0, l, 1.0), 0.0)
        o_ref[0] = o.astype(o_ref.dtype)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    causal: bool = False, sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool = False) -> jnp.ndarray:
    """Exact attention, (B, T, H, D) layout, no key mask. Requires Tq/Tk
    divisible by the block sizes (callers pad or fall back)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if Tq % block_q or Tk % block_k:
        raise ValueError(f"Tq={Tq}/Tk={Tk} not divisible by blocks "
                         f"({block_q}, {block_k})")
    if causal and Tq != Tk:
        raise ValueError("causal flash path requires Tq == Tk")
    scale = sm_scale if sm_scale is not None else 1.0 / (D ** 0.5)

    # (B, T, H, D) -> (B*H, T, D): head-major rows so each grid program owns
    # one contiguous (T, D) slab
    qf = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)

    kernel = functools.partial(_flash_kernel, sm_scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)

    # NOTE: clamping the KV index map for skipped causal blocks (so they
    # issue no DMA) was measured SLOWER on v5e — the skipped steps leave no
    # compute to hide the next real tile's DMA behind. Plain indexing + the
    # kernel-side compute skip wins.
    def kv_index(b, i, j):
        return (b, j, 0)

    out = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // block_q, Tk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), kv_index),
            pl.BlockSpec((1, block_k, D), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max m
            pltpu.VMEM((block_q, 128), jnp.float32),  # running denom l
            pltpu.VMEM((block_q, D), jnp.float32),    # unnormalised output
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)


_probe_ok: Optional[bool] = None


def _platform_supported() -> bool:
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def flash_attention_or_none(q, k, v, *,
                            causal: bool = False) -> Optional[jnp.ndarray]:
    """Dispatch probe (the reflective cuDNN-helper load): returns None when
    the kernel can't serve this call — wrong platform, non-divisible shapes,
    tiny sequences — or when a first-call compile probe failed. Block sizes:
    largest of 512/256/128 dividing the sequence (bigger tiles amortise the
    per-grid-step overhead that dominates this kernel on v5e)."""
    global _probe_ok
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    block = next((b for b in (512, 256, 128) if Tq % b == 0 and Tk % b == 0),
                 None)
    if (_probe_ok is False or block is None or not _platform_supported()
            or (causal and Tq != Tk)
            or D % 128 or q.dtype not in (jnp.float32, jnp.bfloat16)):
        return None
    try:
        out = flash_attention(q, k, v, causal=causal, block_q=block,
                              block_k=block)
        _probe_ok = True
        return out
    except Exception as e:  # Mosaic/compile failure: remember and fall back
        if _probe_ok is None:
            logger.warning(
                "pallas flash-attention unavailable (%s); using XLA "
                "blockwise path", e)
        _probe_ok = False
        return None
