"""Pallas TPU flash-attention: forward AND backward (custom VJP).

The role of `deeplearning4j-cuda`'s helpers in the reference (SURVEY §2.3):
a hand-written accelerator kernel behind the same contract as the built-in
path, picked when available, falling through silently otherwise
(`ConvolutionLayer.initializeHelper`, `ConvolutionLayer.java:69-79`). Here
the built-in paths are `ops/attention.py` full/blockwise attention (XLA);
this module is the Mosaic/Pallas fast path for the no-mask case — and since
it carries a custom VJP (two backward kernels, the standard dQ / dKV
split), it serves TRAINING too, the analogue of the cuDNN backward helpers
gradient-checked in `CuDNNGradientChecks.java`. Measured IN-BENCH on v5e
(`bench.py gpt_long` reports `flash_speedup_vs_xla_blockwise` at the
exact bench shape every run): 2.6-3.0x the XLA blockwise path for causal
fwd+bwd at T=4096, block 1024 (block-512 tiles measured 1.9x). Block
sizes beyond 1024 are exhausted as a lever: with the scoped-VMEM ceiling
raised to admit them, (bq, bk) in {2048x1024, 1024x2048, 2048x2048,
4096x2048} all time within 0.3% of 1024x1024 at the gpt_long shape
(B=8, H=8, T=4096, D=128) — the kernel is HBM/matmul-bound there, so
the ladder keeps 1024 as its top candidate and the raised limit exists
to stop spurious probe declines at wider head dims, not for speed.

Kernel shape (fwd): grid (B·H, Tq/block_q, Tk/block_k), innermost KV
dimension sequential so the online-softmax accumulator lives in VMEM
scratch across KV steps (m/l/acc — the flash recurrence); the TRAINING
forward also writes the row logsumexp L = m + log l for the backward (the
inference primal skips it). Q·Kᵀ and P·V hit the MXU; HBM sees each K/V
tile exactly once.

Backward recomputes P = exp(S - L) tile by tile (no O(T²) residual):
  D  = rowsum(dO ∘ O)
  dV = Pᵀ dO          dP = dO Vᵀ       dS = P ∘ (dP - D)
  dQ = dS K · scale   dK = dSᵀ Q · scale
dQ runs on the fwd grid (KV inner); dK/dV run with the Q dimension inner.

Dtype policy: bf16 inputs feed the MXU natively; f32 multiplies at HIGHEST
precision (measured ~100x more accurate gradients than the XLA
default-precision reference); f64 (interpret-mode gradient checks) keeps
the whole pipeline f64 so eps-scale central differences stay meaningful.
"""
from __future__ import annotations

import functools
import logging
from typing import Optional

import jax
import jax.numpy as jnp

logger = logging.getLogger("deeplearning4j_tpu")

NEG_INF = -1e30


# shared kernel-dispatch policy helpers (kept under the historical private
# names — this module's kernels use them pervasively)
from deeplearning4j_tpu.ops.kernel_dispatch import (  # noqa: E402
    vmem_limit_bytes as _vmem_limit,
    dot as _dot,
    mxu_dtype as _mxu_dtype,
    probe_verdict as _probe_verdict,
    run_probe_out_of_trace as _run_probe_out_of_trace,
    stat_dtype as _stat_dtype,
    tpu_compiler_params as _compiler_params,
)


def _masked_scores(q_ref, k_ref, qi, ki, *, sm_scale, causal, block_q,
                   block_k):
    """One (block_q, block_k) tile of scaled scores with the causal mask
    applied — the SINGLE implementation shared by the forward and both
    backward kernels, so mask/scale semantics cannot drift between them."""
    dt = _mxu_dtype(q_ref.dtype)
    q = q_ref[0].astype(dt)
    k = k_ref[0].astype(dt)
    s = _dot(q, k, ((1,), (1,)), dt) * sm_scale
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(k_pos <= q_pos, s, NEG_INF)
    return s, dt


def _tile_p(s, lse):
    """P = exp(S - L) with fully-masked entries zeroed (matches the
    forward's l == 0 finalisation)."""
    p = jnp.exp(s - lse)
    return jnp.where(s <= NEG_INF / 2, 0.0, p)


def _causal_needed_kv(qi, ki, block_q, block_k, causal):
    # KV blocks strictly above the diagonal contribute nothing
    return (not causal) or (ki * block_k <= qi * block_q + block_q - 1)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest, sm_scale: float,
                      causal: bool, block_q: int, block_k: int,
                      with_lse: bool):
    from jax.experimental import pallas as pl

    if with_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        lse_ref, (m_scr, l_scr, acc_scr) = None, rest

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(_causal_needed_kv(qi, ki, block_q, block_k, causal))
    def _step():
        s, dt = _masked_scores(q_ref, k_ref, qi, ki, sm_scale=sm_scale,
                               causal=causal, block_q=block_q,
                               block_k=block_k)
        m_prev = m_scr[:, :1]                                 # (bq, 1)
        l_prev = l_scr[:, :1]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new)
        # rows fully masked so far sit at m ~ NEG_INF: zero their weights so
        # l stays 0 (finalize maps them to output 0, matching
        # attention.attention_finalize)
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + _dot(p.astype(dt),
                                              v_ref[0].astype(dt),
                                              ((1,), (0,)), dt)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        o = jnp.where(l > 0, acc_scr[:] / jnp.where(l > 0, l, 1.0), 0.0)
        o_ref[0] = o.astype(o_ref.dtype)
        if with_lse:
            # row logsumexp (scaled-score space) for the backward's
            # tile-by-tile P recomputation; fully-masked rows get NEG_INF
            m = m_scr[:, :1]
            lse = jnp.where(l > 0, m + jnp.log(jnp.where(l > 0, l, 1.0)),
                            NEG_INF)
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref,
                         dq_ref, dq_scr, *, sm_scale: float, causal: bool,
                         block_q: int, block_k: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(_causal_needed_kv(qi, ki, block_q, block_k, causal))
    def _step():
        s, dt = _masked_scores(q_ref, k_ref, qi, ki, sm_scale=sm_scale,
                               causal=causal, block_q=block_q,
                               block_k=block_k)
        p = _tile_p(s, lse_ref[0][:, :1])
        do = do_ref[0].astype(dt)
        dp = _dot(do, v_ref[0].astype(dt), ((1,), (1,)), dt)  # (bq, bk)
        ds = p * (dp - dsum_ref[0][:, :1])
        dq_scr[:] += _dot(ds.astype(dt), k_ref[0].astype(dt),
                          ((1,), (0,)), dt) * sm_scale

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref,
                          dk_ref, dv_ref, dk_scr, dv_scr, *,
                          sm_scale: float, causal: bool, block_q: int,
                          block_k: int):
    from jax.experimental import pallas as pl

    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(_causal_needed_kv(qi, kj, block_q, block_k, causal))
    def _step():
        s, dt = _masked_scores(q_ref, k_ref, qi, kj, sm_scale=sm_scale,
                               causal=causal, block_q=block_q,
                               block_k=block_k)
        p = _tile_p(s, lse_ref[0][:, :1])
        do = do_ref[0].astype(dt)
        dv_scr[:] += _dot(p.astype(dt), do, ((0,), (0,)), dt)   # (bk, D)
        dp = _dot(do, v_ref[0].astype(dt), ((1,), (1,)), dt)    # (bq, bk)
        ds = (p * (dp - dsum_ref[0][:, :1])).astype(dt)
        dk_scr[:] += _dot(ds, q_ref[0].astype(dt),
                          ((0,), (0,)), dt) * sm_scale          # (bk, D)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _to_slabs(x):
    """(B, T, H, D) -> (B*H, T, D)."""
    B, T, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)


def _from_slabs(x, B, H):
    BH, T, D = x.shape
    return x.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def _flash_forward(q, k, v, causal, sm_scale, block_q, block_k, interpret,
                   with_lse):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    qf, kf, vf = _to_slabs(q), _to_slabs(k), _to_slabs(v)
    kernel = functools.partial(_flash_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, block_q=block_q,
                               block_k=block_k, with_lse=with_lse)
    sdt = _stat_dtype(q.dtype)
    out_specs = [pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype)]
    if with_lse:
        # stats stored broadcast along the 128-lane axis: the natural TPU
        # tile; row-vector (1, block_q) layouts are fragile under Mosaic
        out_specs.append(pl.BlockSpec((1, block_q, 128),
                                      lambda b, i, j: (b, i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((B * H, Tq, 128), sdt))

    # NOTE: clamping the KV index map for skipped causal blocks (so they
    # issue no DMA) was measured SLOWER on v5e — the skipped steps leave no
    # compute to hide the next real tile's DMA behind. Plain indexing + the
    # kernel-side compute skip wins.
    def kv_index(b, i, j):
        return (b, j, 0)

    res = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // block_q, Tk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), kv_index),
            pl.BlockSpec((1, block_k, D), kv_index),
        ],
        out_specs=out_specs if with_lse else out_specs[0],
        out_shape=out_shape if with_lse else out_shape[0],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), sdt),  # running max m
            pltpu.VMEM((block_q, 128), sdt),  # running denom l
            pltpu.VMEM((block_q, D), sdt),    # unnormalised output
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=_vmem_limit()),
        interpret=interpret,
    )(qf, kf, vf)
    if with_lse:
        out, lse = res
        return _from_slabs(out, B, H), lse
    return _from_slabs(res, B, H), None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_mha(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    # inference primal: no lse output (skips an f32 HBM write larger than
    # the attention output itself)
    out, _ = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                            interpret, with_lse=False)
    return out


def _flash_mha_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, sm_scale, block_q, block_k,
                              interpret, with_lse=True)
    return out, (q, k, v, out, lse)


def _flash_mha_bwd(causal, sm_scale, block_q, block_k, interpret, res, do):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    q, k, v, out, lse = res
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    sdt = _stat_dtype(q.dtype)
    # D_i = rowsum(dO ∘ O), broadcast along the 128-lane stat axis like lse
    dsum = jnp.sum(do.astype(sdt) * out.astype(sdt), axis=-1)  # (B, Tq, H)
    dsum = dsum.transpose(0, 2, 1).reshape(B * H, Tq, 1)
    dsum = jnp.broadcast_to(dsum, (B * H, Tq, 128))
    qf, kf, vf = _to_slabs(q), _to_slabs(k), _to_slabs(v)
    dof = _to_slabs(do)

    dq_kernel = functools.partial(_flash_bwd_dq_kernel, sm_scale=sm_scale,
                                  causal=causal, block_q=block_q,
                                  block_k=block_k)
    dqf = pl.pallas_call(
        dq_kernel,
        grid=(B * H, Tq // block_q, Tk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), sdt)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=_vmem_limit()),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, dsum)

    dkv_kernel = functools.partial(_flash_bwd_dkv_kernel, sm_scale=sm_scale,
                                   causal=causal, block_q=block_q,
                                   block_k=block_k)
    dkf, dvf = pl.pallas_call(
        dkv_kernel,
        grid=(B * H, Tk // block_k, Tq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Tk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), sdt),
            pltpu.VMEM((block_k, D), sdt),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=_vmem_limit()),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, dsum)

    return (_from_slabs(dqf, B, H), _from_slabs(dkf, B, H),
            _from_slabs(dvf, B, H))


_flash_mha.defvjp(_flash_mha_fwd, _flash_mha_bwd)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    causal: bool = False, sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool = False) -> jnp.ndarray:
    """Exact attention, (B, T, H, D) layout, no key mask; differentiable
    (custom VJP with Pallas backward kernels). Requires Tq/Tk divisible by
    the block sizes (callers pad or fall back)."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if Tq % block_q or Tk % block_k:
        raise ValueError(f"Tq={Tq}/Tk={Tk} not divisible by blocks "
                         f"({block_q}, {block_k})")
    if causal and Tq != Tk:
        raise ValueError("causal flash path requires Tq == Tk")
    scale = sm_scale if sm_scale is not None else 1.0 / (D ** 0.5)
    return _flash_mha(q, k, v, causal, scale, block_q, block_k, interpret)


_probe_cache: dict = {}  # (dtype name, block, head_dim) -> probe verdict


def _platform_supported() -> bool:
    import os

    if os.environ.get("DL4J_TPU_NO_PALLAS_ATTENTION"):
        return False  # forced XLA-blockwise fallback (A/B benches, tests)
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def _eager_probe(dtype, block: int, head_dim: int) -> bool:
    """Compile + run the forward AND backward kernels once on tiny
    concrete inputs, OUTSIDE any trace. The dispatch itself usually runs
    inside a jit trace, where a Mosaic compile failure would surface at
    the OUTER jit's compile — far from any try/except here. Probing
    eagerly up front turns a platform that can't compile the kernels into
    a silent XLA fallback instead of a training crash. Probed per
    (dtype, block) at T=block so the exact tile configuration that will
    run is the one proven to compile."""
    B, T, H = 1, block, 1
    x = jnp.zeros((B, T, H, head_dim), dtype)

    def l(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=block,
                                       block_k=block).astype(jnp.float32))

    g = jax.grad(l, argnums=(0, 1, 2))(x, x, x)
    return bool(jnp.all(jnp.isfinite(g[0].astype(jnp.float32))))


# _vmem_limit() (generation-derived ceiling, kernel_dispatch): the default 16 MiB
# scoped-stack limit rejects 2048-wide tiles whose f32 score slabs
# alone are 16 MiB

_BLOCK_CANDIDATES = (1024, 512, 256, 128)


def _probed_block(dtype, Tq: int, Tk: int, D: int) -> Optional[int]:
    """Largest candidate tile that divides the sequence AND passes the
    fwd+bwd compile probe. A block whose probe fails (e.g. VMEM overflow
    at a bigger head dim) falls through to the next smaller candidate
    instead of abandoning the kernel outright."""
    for block in _BLOCK_CANDIDATES:
        if Tq % block or Tk % block:
            continue
        key = (jnp.dtype(dtype).name, block, D)
        if _probe_verdict(_probe_cache, key, _eager_probe,
                          (dtype, block, D), "pallas flash-attention"):
            return block
    return None


def flash_attention_or_none(q, k, v, *,
                            causal: bool = False) -> Optional[jnp.ndarray]:
    """Dispatch probe (the reflective cuDNN-helper load): returns None when
    the kernel can't serve this call — wrong platform, non-divisible shapes,
    tiny sequences — or when every candidate tile failed its fwd+bwd
    compile probe. Biggest tile first: fwd+bwd at T=4096/D=128 measured
    31.5 ms (b1024) vs 37.5 (b512) vs 54.6 (b256) vs XLA blockwise 70.6 —
    larger tiles amortise the per-grid-step overhead that dominates on
    v5e."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if (not _platform_supported() or (causal and Tq != Tk)
            or D % 128 or q.dtype not in (jnp.float32, jnp.bfloat16)):
        return None
    block = _probed_block(q.dtype, Tq, Tk, D)
    if block is None:
        return None
    try:
        return flash_attention(q, k, v, causal=causal, block_q=block,
                               block_k=block)
    except Exception as e:  # per-shape staging failure: fall back
        logger.warning("pallas flash-attention declined for shape %s (%s)",
                       q.shape, e)
        return None
