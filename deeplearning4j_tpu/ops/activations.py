"""Activation functions.

Reference surface: DL4J's `Activation` enum / `IActivation` implementations
(consumed via ND4J, e.g. `nn/conf/NeuralNetConfiguration.java:478` `activation`
builder field). Here each activation is a pure jnp function; under `jax.jit`
XLA fuses it into the producing GEMM/conv, which is the TPU analogue of the
reference's fused cuDNN activation path
(`CudnnConvolutionHelper.java` forward+activation fusion).
"""
from __future__ import annotations

import enum
from typing import Callable

import jax
import jax.numpy as jnp


class Activation(str, enum.Enum):
    """Mirrors the reference's Activation enum values."""

    IDENTITY = "identity"
    RELU = "relu"
    LEAKYRELU = "leakyrelu"
    RELU6 = "relu6"
    ELU = "elu"
    SELU = "selu"
    SIGMOID = "sigmoid"
    HARDSIGMOID = "hardsigmoid"
    TANH = "tanh"
    HARDTANH = "hardtanh"
    RATIONALTANH = "rationaltanh"
    RECTIFIEDTANH = "rectifiedtanh"
    SOFTMAX = "softmax"
    LOGSOFTMAX = "logsoftmax"
    SOFTPLUS = "softplus"
    SOFTSIGN = "softsign"
    CUBE = "cube"
    SWISH = "swish"
    GELU = "gelu"
    MISH = "mish"
    THRESHOLDEDRELU = "thresholdedrelu"

    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        return activation_fn(self)(x)


def _rational_tanh(x):
    # Padé-style tanh approximation used by the reference's RationalTanh
    # (ND4J ActivationRationalTanh): 1.7159 * tanh_approx(2x/3).
    a = 2.0 * x / 3.0
    clamped = jnp.clip(a, -22.0, 22.0)
    approx = jnp.sign(clamped) * (
        1.0 - 1.0 / (1.0 + jnp.abs(clamped) + clamped**2 + 1.41645 * clamped**4)
    )
    return 1.7159 * approx


_ACTIVATIONS: dict[Activation, Callable[[jnp.ndarray], jnp.ndarray]] = {
    Activation.IDENTITY: lambda x: x,
    Activation.RELU: jax.nn.relu,
    Activation.LEAKYRELU: lambda x: jax.nn.leaky_relu(x, negative_slope=0.01),
    Activation.RELU6: jax.nn.relu6,
    Activation.ELU: jax.nn.elu,
    Activation.SELU: jax.nn.selu,
    Activation.SIGMOID: jax.nn.sigmoid,
    # reference ActivationHardSigmoid: clip(0.2x + 0.5, 0, 1) — NOT jax's
    # relu6(x+3)/6 variant
    Activation.HARDSIGMOID: lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
    Activation.TANH: jnp.tanh,
    Activation.HARDTANH: lambda x: jnp.clip(x, -1.0, 1.0),
    Activation.RATIONALTANH: _rational_tanh,
    Activation.RECTIFIEDTANH: lambda x: jnp.maximum(0.0, jnp.tanh(x)),
    Activation.SOFTMAX: lambda x: jax.nn.softmax(x, axis=-1),
    Activation.LOGSOFTMAX: lambda x: jax.nn.log_softmax(x, axis=-1),
    Activation.SOFTPLUS: jax.nn.softplus,
    Activation.SOFTSIGN: jax.nn.soft_sign,
    Activation.CUBE: lambda x: x**3,
    Activation.SWISH: jax.nn.swish,
    Activation.GELU: jax.nn.gelu,
    Activation.MISH: jax.nn.mish,
    Activation.THRESHOLDEDRELU: lambda x: jnp.where(x > 1.0, x, 0.0),
}


def activation_fn(act: Activation | str) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """Resolve an activation enum/string to its jnp implementation."""
    act = Activation(act.lower()) if isinstance(act, str) else act
    return _ACTIVATIONS[act]
