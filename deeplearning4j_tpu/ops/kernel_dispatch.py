"""Shared plumbing for Pallas kernel dispatch (the cuDNN-helper pattern).

Every accelerated kernel in `ops/` follows the reference's reflective
helper contract (`ConvolutionLayer.initializeHelper`,
`ConvolutionLayer.java:69-79`): probe once whether the fast path compiles
here, fall through silently to the XLA path otherwise. This module holds
the pieces that contract needs so each new kernel doesn't re-implement
them: MXU dtype policy, accumulation dtype, a precision-pinned
dot_general, out-of-trace probe execution, and the cached-verdict helper.

Dispatch contract (every kernel family — `pallas_attention`,
`pallas_lstm`, `pallas_paged_attention` — holds all five):

1. **Same signature, same semantics** as the XLA path it replaces; the
   XLA path stays in-tree as the portable reference numerics.
2. **Probe before first dispatch**, out of trace (`probe_verdict`):
   compile AND run the kernel once at the exact shape class on tiny
   concrete inputs. A kernel whose probe also CHECKS its output against
   the XLA reference (the paged-attention family does) turns a
   miscompiling Mosaic toolchain into a silent fallback instead of a
   wrong-numerics serving path.
3. **Silent fallback**: any probe raise is logged once and cached as
   False; CPU/interpret platforms never dispatch (tier-1 tests run the
   XLA paths bit-for-bit unchanged).
4. **Kill switch**: a `DL4J_TPU_NO_<KERNEL>` env var forces the XLA
   path — how the benches price kernel-vs-XLA A/B lines on identical
   configs.
5. **VMEM ceiling**: kernels size their resident slabs against
   `vmem_limit_bytes()` (generation-derived, below) and decline shapes
   that cannot fit rather than letting Mosaic fail mid-training.
"""
from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

logger = logging.getLogger("deeplearning4j_tpu")


def mxu_dtype(ref_dtype):
    """bf16 inputs feed the MXU natively; f32 stays f32; f64 (interpret
    mode on CPU, gradient checks) stays f64."""
    return jnp.bfloat16 if ref_dtype == jnp.bfloat16 else ref_dtype


def stat_dtype(dt):
    """Accumulator/statistic dtype: f32 for bf16/f32 inputs, f64 for f64
    (interpret-mode gradient checks need the whole pipeline at f64, or
    eps-scale central differences drown in f32 forward noise)."""
    return jnp.float64 if dt == jnp.float64 else jnp.float32


def dot_precision(dt):
    """f32 operands multiply at HIGHEST precision (bf16x3 passes on the
    MXU) — measured ~100x more accurate gradients than the XLA
    default-precision einsum; bf16 takes the native single-pass feed."""
    return (jax.lax.Precision.DEFAULT if dt == jnp.bfloat16
            else jax.lax.Precision.HIGHEST)


def dot(a, b, dims, dt):
    """dot_general with the kernel dtype policy applied."""
    return jax.lax.dot_general(a, b, dimension_numbers=(dims, ((), ())),
                               preferred_element_type=stat_dtype(dt),
                               precision=dot_precision(dt))


def tpu_compiler_params(**kw):
    """Construct the Pallas TPU compiler-params struct across JAX
    versions: the class was renamed `TPUCompilerParams` →
    `CompilerParams` upstream, and a hard reference to either name makes
    every kernel family unimportable-at-dispatch on the other toolchain
    (probe failure → permanent XLA fallback on a platform the kernel
    compiles fine on). One shim so a rename retunes all kernels at
    once."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kw)


def run_probe_out_of_trace(fn, *args) -> bool:
    """Run an eager compile probe OUTSIDE any live jit trace. Dispatch
    usually happens while the caller's step function is being traced, and
    JAX trace contexts are dynamic: ops on concrete probe arrays would be
    staged into the caller's jaxpr and the probe's `bool()` would raise
    TracerBoolConversionError (silently caching a False verdict). Trace
    state is thread-local, so a worker thread gives the probe a clean
    eval context."""
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(1) as ex:
        return ex.submit(fn, *args).result()


def probe_verdict(cache: dict, key, probe_fn, args, what: str) -> bool:
    """Cached out-of-trace compile-probe verdict: True once `probe_fn`
    compiled and ran finite at this shape class; a raise is logged and
    cached as False (the silent-fallback contract)."""
    ok = cache.get(key)
    if ok is None:
        try:
            ok = run_probe_out_of_trace(probe_fn, *args)
        except Exception as e:  # Mosaic/compile failure: remember
            logger.warning("%s unavailable for %s (%s); using the XLA "
                           "fallback path", what, key, e)
            ok = False
        cache[key] = ok
    return bool(ok)


# Mosaic's default scoped-VMEM stack limit is 16 MiB; modern cores carry
# far more. Kernels whose double-buffered slabs exceed the default (the
# fused LSTM at H=1024 needs 100.1 MiB; 2048-wide attention tiles carry
# 16 MiB f32 score slabs) pass a shared ceiling via
# CompilerParams(vmem_limit_bytes=...). The ceiling is DERIVED from the
# detected device generation (one table so a new TPU generation retunes
# every kernel family at once): 7/8 of the core's physical VMEM, the
# same headroom fraction the old hardcoded 112-of-128 MiB constant
# carried — the reserve absorbs Mosaic's own scratch and avoids
# spilling at exactly-full occupancy. Unknown kinds (CPU interpret
# mode, future generations) keep the v4/v5-class default rather than
# the 16 MiB floor: an over-ask fails loudly at compile (and the probe
# machinery falls back to XLA), while a silent 16 MiB cap would
# permanently disable the big-slab kernels.
_MIB = 1024 * 1024
_VMEM_PER_CORE_BYTES = {
    # device_kind prefix -> physical scoped VMEM per core
    "TPU v2": 16 * _MIB,
    "TPU v3": 16 * _MIB,
    "TPU v4 lite": 128 * _MIB,   # v4i inference cores
    "TPU v4": 128 * _MIB,
    "TPU v5 lite": 128 * _MIB,   # v5e (device_kind "TPU v5 lite"/"TPU v5e")
    "TPU v5e": 128 * _MIB,
    "TPU v5p": 128 * _MIB,
    "TPU v5": 128 * _MIB,
    "TPU v6 lite": 128 * _MIB,   # v6e / Trillium
    "TPU v6e": 128 * _MIB,
}
_DEFAULT_VMEM_PER_CORE = 128 * _MIB

# Back-compat alias: the pre-table constant (112 MiB = 7/8 of the
# 128 MiB v4/v5-class core this build was tuned on). Prefer
# `vmem_limit_bytes()`.
VMEM_LIMIT_BYTES = _DEFAULT_VMEM_PER_CORE * 7 // 8

_vmem_limit_cache: dict = {}


def vmem_limit_for_kind(device_kind: str) -> int:
    """Scoped-VMEM ceiling for one `device_kind` string: 7/8 of the
    generation's physical per-core VMEM (longest-prefix match over the
    table, so "TPU v5 lite" resolves before "TPU v5"); unknown kinds
    get the v4/v5-class default."""
    best = None
    for prefix, size in _VMEM_PER_CORE_BYTES.items():
        if device_kind.startswith(prefix) and \
                (best is None or len(prefix) > len(best[0])):
            best = (prefix, size)
    physical = best[1] if best is not None else _DEFAULT_VMEM_PER_CORE
    return physical * 7 // 8


def vmem_limit_bytes() -> int:
    """The Pallas `vmem_limit_bytes` ceiling for THIS process's default
    device, detected once and cached. Every kernel family
    (`pallas_attention`, `pallas_lstm`) reads the same number, so a new
    TPU generation retunes all of them in one table row."""
    key = "default"
    if key not in _vmem_limit_cache:
        try:
            kind = jax.devices()[0].device_kind
        except Exception:  # no devices (early import, odd backends)
            kind = ""
        _vmem_limit_cache[key] = vmem_limit_for_kind(kind)
    return _vmem_limit_cache[key]
