"""Shared plumbing for Pallas kernel dispatch (the cuDNN-helper pattern).

Every accelerated kernel in `ops/` follows the reference's reflective
helper contract (`ConvolutionLayer.initializeHelper`,
`ConvolutionLayer.java:69-79`): probe once whether the fast path compiles
here, fall through silently to the XLA path otherwise. This module holds
the pieces that contract needs so each new kernel doesn't re-implement
them: MXU dtype policy, accumulation dtype, a precision-pinned
dot_general, out-of-trace probe execution, and the cached-verdict helper.
"""
from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

logger = logging.getLogger("deeplearning4j_tpu")


def mxu_dtype(ref_dtype):
    """bf16 inputs feed the MXU natively; f32 stays f32; f64 (interpret
    mode on CPU, gradient checks) stays f64."""
    return jnp.bfloat16 if ref_dtype == jnp.bfloat16 else ref_dtype


def stat_dtype(dt):
    """Accumulator/statistic dtype: f32 for bf16/f32 inputs, f64 for f64
    (interpret-mode gradient checks need the whole pipeline at f64, or
    eps-scale central differences drown in f32 forward noise)."""
    return jnp.float64 if dt == jnp.float64 else jnp.float32


def dot_precision(dt):
    """f32 operands multiply at HIGHEST precision (bf16x3 passes on the
    MXU) — measured ~100x more accurate gradients than the XLA
    default-precision einsum; bf16 takes the native single-pass feed."""
    return (jax.lax.Precision.DEFAULT if dt == jnp.bfloat16
            else jax.lax.Precision.HIGHEST)


def dot(a, b, dims, dt):
    """dot_general with the kernel dtype policy applied."""
    return jax.lax.dot_general(a, b, dimension_numbers=(dims, ((), ())),
                               preferred_element_type=stat_dtype(dt),
                               precision=dot_precision(dt))


def run_probe_out_of_trace(fn, *args) -> bool:
    """Run an eager compile probe OUTSIDE any live jit trace. Dispatch
    usually happens while the caller's step function is being traced, and
    JAX trace contexts are dynamic: ops on concrete probe arrays would be
    staged into the caller's jaxpr and the probe's `bool()` would raise
    TracerBoolConversionError (silently caching a False verdict). Trace
    state is thread-local, so a worker thread gives the probe a clean
    eval context."""
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(1) as ex:
        return ex.submit(fn, *args).result()


def probe_verdict(cache: dict, key, probe_fn, args, what: str) -> bool:
    """Cached out-of-trace compile-probe verdict: True once `probe_fn`
    compiled and ran finite at this shape class; a raise is logged and
    cached as False (the silent-fallback contract)."""
    ok = cache.get(key)
    if ok is None:
        try:
            ok = run_probe_out_of_trace(probe_fn, *args)
        except Exception as e:  # Mosaic/compile failure: remember
            logger.warning("%s unavailable for %s (%s); using the XLA "
                           "fallback path", what, key, e)
            ok = False
        cache[key] = ok
    return bool(ok)


# Mosaic's default scoped-VMEM stack limit is 16 MiB; v5e cores carry
# 128 MiB. Kernels whose double-buffered slabs exceed the default (the
# fused LSTM at H=1024 needs 100.1 MiB; 2048-wide attention tiles carry
# 16 MiB f32 score slabs) pass this shared ceiling via
# CompilerParams(vmem_limit_bytes=...). One constant so a new TPU
# generation retunes every kernel family at once.
VMEM_LIMIT_BYTES = 112 * 1024 * 1024
